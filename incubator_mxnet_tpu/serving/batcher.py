"""DynamicBatcher — coalesce concurrent inference requests into one
compiled dispatch.

Requests land in a bounded FIFO queue; a single worker thread pops the
head and keeps gathering compatible requests (same per-example shapes
and dtypes — FIFO order is never reordered past an incompatible head)
until the group reaches ``max_batch_size`` rows or the head request's
``max_delay_ms`` deadline expires.  The group is concatenated along the
batch axis, padded up to the engine's next bucket, dispatched as ONE
compiled program, and the output rows are scattered back to the waiting
callers.

Operational behavior is wired into the runtime's existing planes:

* **backpressure** — a full queue rejects immediately with
  :class:`QueueFullError` (``mxtpu_serve_rejected``); the client sees a
  429 from the HTTP front-end instead of unbounded latency.
* **deadlines** — a request may carry an end-to-end budget
  (``timeout_ms``; env default ``MXNET_SERVE_TIMEOUT_MS``).  Admission
  rejects a request whose queue-wait estimate already busts it, the
  gather loop sheds requests that expired while queued, and the caller's
  wait is bounded by the remaining budget — all three raise
  ``lifecycle.DeadlineExceeded`` (HTTP 504,
  ``mxtpu_serve_deadline_exceeded``), so a stuck dispatch can never pin
  an HTTP handler thread forever.
* **circuit breaker** — consecutive dispatch-after-retry failures (the
  :meth:`_fallback` path) trip the model's ``lifecycle.CircuitBreaker``
  CLOSED→OPEN; while OPEN, admission fast-fails with
  ``lifecycle.BreakerOpen`` (HTTP 503 + ``Retry-After``) until a
  half-open probe succeeds.
* **watchdog** — the worker heartbeats; :meth:`check_worker` (driven by
  ``lifecycle.Watchdog``) detects a dead or hung worker, fails that
  group's riders with ``lifecycle.RequestAborted``, restarts the worker
  on a fresh generation, trips the breaker and marks the model
  DEGRADED until the next successful dispatch.
* **faults** — ``serving.queue`` is polled at submit and
  ``serving.infer`` inside the batched dispatch (``MXNET_FAULT_PLAN``
  site grammar, docs/robustness.md; the ``hang`` kind drills the
  watchdog).  A failed batch dispatch retries under
  :func:`fault.retry_call`; on exhaustion the batcher publishes a
  ``fallback`` FAULT event, bumps ``mxtpu_serve_fallbacks``, and
  executes each request individually so one poisoned batch cannot fail
  every rider.
* **graceful drain** — :meth:`close` stops intake, lets the worker
  drain everything already queued (coalescing without waiting out the
  delay deadline), then joins the worker; if the worker cannot finish
  inside the join budget, every still-pending request is failed with a
  clear error instead of being stranded on an event nobody will set.
* **telemetry** — ``serve.request`` (submit-to-result) and
  ``serve.batch`` spans, queue-wait / batch-size / end-to-end latency
  histograms, per-model queue-depth gauge, breaker/watchdog series.
* **request tracing** — every request carries a request id (client's
  ``x-request-id`` via the HTTP front-end, else generated here) that is
  stamped on its ``serve.request`` span, on every FAULT event it
  triggers (deadline sheds, injected faults, watchdog aborts, worker
  crashes), and on the ``serve.batch`` span's ``links`` attr, so one id
  greps a failed request end to end — HTTP response header → span tree
  → flight-recorder dump (docs/observability.md).  The caller's span
  context is captured at submit and re-attached in the worker thread,
  so the batch span nests under the request that headed the batch.
* **SLO accounting** — every synchronous :meth:`submit` outcome lands
  in ``serving.slo``'s per-model rolling window (good/bad + latency),
  feeding the ``mxtpu_slo_*`` series and ``/slo`` burn-rate math.
"""
from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import List, Optional, Sequence

from ..base import MXNetError, getenv, getenv_int
from ..ndarray.ndarray import NDArray
from .. import fault as _fault
from .. import health as _health
from .. import telemetry as _telemetry
from .. import telemetry_device as _tdev
from . import lifecycle as _lc
from . import metrics as _m
from . import slo as _slo
from .sampling import (SamplingParams, JsonMaskMachine, stop_trim,
                       derive_candidate_seed)

__all__ = ["DynamicBatcher", "ContinuousBatcher", "QueueFullError"]


class QueueFullError(MXNetError):
    """The batcher's bounded queue is full — backpressure, not failure.
    ``retry_after`` (seconds) rides to the HTTP surface as a
    ``Retry-After`` header."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = float(retry_after)


class _Request:
    """One submitted batch: arrays + a latch the caller waits on."""

    __slots__ = ("arrays", "n", "sig", "event", "outputs", "error",
                 "t_submit", "deadline", "model", "request_id",
                 "trace_ctx")

    def __init__(self, arrays, n, sig, deadline=None, model="?",
                 request_id=None, trace_ctx=None):
        self.arrays = arrays
        self.n = n
        self.sig = sig
        self.event = threading.Event()
        self.outputs = None
        self.error = None
        self.t_submit = time.monotonic()
        self.deadline = deadline        # absolute monotonic, or None
        self.model = model
        self.request_id = request_id or _telemetry.new_request_id()
        self.trace_ctx = trace_ctx      # submitter's span, for the worker

    def fail(self, err: Exception) -> None:
        """Finish this request with ``err`` (idempotent).  The ONE
        protocol the batcher/watchdog/drain paths use to fail a request
        — subclasses with richer consumer channels (the generation
        request's token queue) override it so every waiter wakes, not
        just ``result()``."""
        if self.event.is_set():
            return
        self.error = err
        self.event.set()

    def result(self, timeout: Optional[float] = None) -> List:
        """Block for the scattered outputs; re-raises dispatch errors.
        The wait is additionally bounded by the request's own deadline —
        crossing it raises ``lifecycle.DeadlineExceeded`` (HTTP 504),
        a caller-supplied ``timeout`` alone raises ``TimeoutError``."""
        wait = timeout
        if self.deadline is not None:
            remaining = max(0.0, self.deadline - time.monotonic())
            wait = remaining if timeout is None else min(timeout, remaining)
        if not self.event.wait(wait):
            if self.deadline is not None \
                    and time.monotonic() >= self.deadline:
                _m.DEADLINE_EXCEEDED.inc(model=self.model, stage="wait")
                _telemetry.FAULT.publish(
                    site="serving.deadline", event="deadline", kind="wait",
                    model=self.model, request_id=self.request_id)
                raise _lc.DeadlineExceeded(
                    f"{self.model}: request {self.request_id} deadline "
                    f"exceeded after "
                    f"{time.monotonic() - self.t_submit:.3f}s")
            raise TimeoutError("inference request timed out")
        if self.error is not None:
            raise self.error
        return self.outputs


class DynamicBatcher:
    """Batch-coalescing front-end over one :class:`InferenceEngine`.

    Defaults come from the serving env knobs (``MXNET_SERVE_MAX_BATCH``
    = 32, ``MXNET_SERVE_MAX_DELAY_MS`` = 5.0, ``MXNET_SERVE_QUEUE`` =
    128, ``MXNET_SERVE_TIMEOUT_MS`` = 0 → deadline-free;
    docs/env_var.md)."""

    def __init__(self, engine, *, max_batch_size: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 queue_size: Optional[int] = None,
                 name: Optional[str] = None, retry_policy=None,
                 breaker: Optional[_lc.CircuitBreaker] = None,
                 default_timeout_ms: Optional[float] = None):
        self.engine = engine
        self.name = str(name or engine.name)
        if max_batch_size is None:
            max_batch_size = getenv_int("MXNET_SERVE_MAX_BATCH", 32)
        if engine.max_batch_size:
            max_batch_size = min(int(max_batch_size),
                                 int(engine.max_batch_size))
        self.max_batch_size = max(1, int(max_batch_size))
        if max_delay_ms is None:
            max_delay_ms = float(getenv("MXNET_SERVE_MAX_DELAY_MS", 5.0))
        self.max_delay = max(0.0, float(max_delay_ms)) / 1000.0
        if queue_size is None:
            queue_size = getenv_int("MXNET_SERVE_QUEUE", 128)
        self.queue_size = max(1, int(queue_size))
        if default_timeout_ms is None:
            default_timeout_ms = _lc.default_timeout_ms()
        self.default_timeout_ms = float(default_timeout_ms)
        self.retry_policy = retry_policy
        self.breaker = breaker if breaker is not None \
            else _lc.CircuitBreaker(self.name)
        self._queue: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        # worker health plane (all guarded by _cv): the generation
        # counter lets the watchdog replace a wedged worker — the old
        # thread notices its generation is stale and exits when (if) it
        # ever wakes up
        self._worker_gen = 0
        self._heartbeat = time.monotonic()
        self._busy_since: Optional[float] = None
        self._inflight: Optional[list] = None
        self._restarts = 0
        self._degraded = False
        self._avg_batch_seconds = 0.0
        self._thread = self._start_worker()

    def _start_worker(self) -> threading.Thread:
        # _cv NOT required; called from __init__ and (under _cv) from
        # check_worker/close — Thread.start is thread-safe either way
        t = threading.Thread(
            target=self._worker, args=(self._worker_gen,),
            name=f"mxtpu-serve-{self.name}-g{self._worker_gen}",
            daemon=True)
        t.start()
        return t

    # -- submit ---------------------------------------------------------
    @staticmethod
    def _signature(arrays):
        return tuple((tuple(a.shape[1:]), str(getattr(a, "dtype", "?")))
                     for a in arrays)

    def _estimate_wait_locked(self) -> float:
        """Queue-wait estimate for a newly admitted request, from the
        rows already queued and the EWMA batch service time (_cv held).
        0 until the first batch has been measured — admission control
        only ever sheds on *evidence* of a slow model."""
        if self._avg_batch_seconds <= 0.0:
            return 0.0
        rows = sum(r.n for r in self._queue)
        batches_ahead = rows // self.max_batch_size
        if self._busy_since is not None:    # current dispatch finishes first
            batches_ahead += 1
        return batches_ahead * self._avg_batch_seconds

    def submit_async(self, arrays: Sequence,
                     timeout_ms: Optional[float] = None,
                     request_id: Optional[str] = None) -> _Request:
        """Enqueue one request batch; returns a latch whose
        ``result()`` blocks for the outputs.  Raises
        :class:`QueueFullError` under backpressure,
        ``lifecycle.BreakerOpen`` while the model's breaker is OPEN,
        ``lifecycle.DeadlineExceeded`` when the queue-wait estimate
        already busts the request's budget, and ``MXNetError`` after
        :meth:`close`.  ``request_id`` (generated when absent) rides on
        every FAULT event the request triggers."""
        if request_id is None:
            request_id = _telemetry.new_request_id()
        _fault.inject("serving.queue", model=self.name,
                      request_id=request_id)
        self.breaker.allow()
        arrays = list(arrays)
        n = int(arrays[0].shape[0])
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        req = _Request(arrays, n, self._signature(arrays),
                       deadline=_lc.deadline_from_ms(timeout_ms),
                       model=self.name, request_id=request_id,
                       trace_ctx=_telemetry.tracer.current())
        with self._cv:
            if self._closed:
                raise MXNetError(f"batcher {self.name!r} is closed")
            if len(self._queue) >= self.queue_size:
                _m.REJECTED.inc(model=self.name)
                raise QueueFullError(
                    f"{self.name}: queue full ({self.queue_size} "
                    "pending) — backpressure")
            if req.deadline is not None:
                est = self._estimate_wait_locked()
                if time.monotonic() + est > req.deadline:
                    _m.DEADLINE_EXCEEDED.inc(model=self.name,
                                             stage="admission")
                    _telemetry.FAULT.publish(
                        site="serving.deadline", event="deadline",
                        kind="admission", model=self.name,
                        request_id=req.request_id)
                    raise _lc.DeadlineExceeded(
                        f"{self.name}: estimated queue wait {est:.3f}s "
                        "already exceeds the deadline of request "
                        f"{req.request_id}")
            self._queue.append(req)
            _m.QUEUE_DEPTH.set(len(self._queue), model=self.name)
            self._cv.notify_all()
        _m.REQUESTS.inc(model=self.name)
        return req

    def submit(self, arrays: Sequence,
               timeout: Optional[float] = None,
               timeout_ms: Optional[float] = None,
               request_id: Optional[str] = None) -> List:
        """Synchronous request: enqueue, wait, return per-row outputs
        (jax arrays, sliced to this request's rows).  ``timeout_ms`` is
        the end-to-end deadline budget (defaults from
        ``MXNET_SERVE_TIMEOUT_MS``); ``timeout`` additionally bounds
        just the wait.  Every outcome (including rejections and
        deadline busts) is recorded against the model's SLO window."""
        if request_id is None:
            request_id = _telemetry.new_request_id()
        t0 = time.monotonic()
        with _telemetry.trace_span("serve.request", cat="serving",
                                   model=self.name,
                                   request_id=request_id):
            try:
                out = self.submit_async(
                    arrays, timeout_ms=timeout_ms,
                    request_id=request_id).result(timeout)
            except Exception:
                _slo.tracker.record(self.name,
                                    time.monotonic() - t0, ok=False)
                raise
            _slo.tracker.record(self.name, time.monotonic() - t0, ok=True)
            return out

    # -- worker ---------------------------------------------------------
    def _current_gen(self) -> int:
        with self._cv:
            return self._worker_gen

    def _worker(self, gen: int):
        while True:
            if self._current_gen() != gen:
                return                  # replaced by the watchdog
            group = self._gather(gen)
            if group is None:
                return
            with self._cv:
                if gen == self._worker_gen:
                    self._busy_since = time.monotonic()
                    self._inflight = group
            self._run_group(group)
            with self._cv:
                if gen == self._worker_gen:
                    self._busy_since = None
                    self._inflight = None

    def _expire_locked(self, req: _Request) -> None:
        """Shed one already-expired request at gather time (_cv held;
        event.set() under the lock is fine — waiters wake after we
        release)."""
        _m.DEADLINE_EXCEEDED.inc(model=self.name, stage="queue")
        _telemetry.FAULT.publish(site="serving.deadline", event="deadline",
                                 kind="queue", model=self.name,
                                 request_id=req.request_id)
        req.fail(_lc.DeadlineExceeded(
            f"{self.name}: request {req.request_id} expired in queue "
            f"after {time.monotonic() - req.t_submit:.3f}s"))

    def _gather(self, gen: int):
        """Block for the head request, then coalesce until the batch is
        full, the head's delay deadline passes, or the next queued
        request is shape-incompatible (FIFO preserved).  Requests whose
        end-to-end deadline already expired are shed here (504), never
        dispatched.  Returns None when closed and drained or when this
        worker generation has been replaced."""
        with self._cv:
            while True:
                self._heartbeat = time.monotonic()
                while self._queue and self._queue[0].deadline is not None \
                        and self._queue[0].deadline <= self._heartbeat:
                    self._expire_locked(self._queue.popleft())
                if self._queue:
                    break
                if self._closed:
                    return None
                if gen != self._worker_gen:
                    return None
                self._cv.wait(0.05)
            head = self._queue.popleft()
            group, total = [head], head.n
            deadline = time.monotonic() + self.max_delay
            while total < self.max_batch_size:
                if self._queue:
                    nxt = self._queue[0]
                    if nxt.deadline is not None \
                            and nxt.deadline <= time.monotonic():
                        self._expire_locked(self._queue.popleft())
                        continue
                    if nxt.sig != head.sig \
                            or total + nxt.n > self.max_batch_size:
                        break
                    group.append(self._queue.popleft())
                    total += nxt.n
                    continue
                if self._closed:        # drain fast: no deadline wait
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            _m.QUEUE_DEPTH.set(len(self._queue), model=self.name)
        return group

    def _run_group(self, group):
        import jax.numpy as jnp
        t0 = time.monotonic()
        for r in group:
            _m.QUEUE_WAIT.observe(t0 - r.t_submit)
        total = sum(r.n for r in group)
        _m.BATCH_SIZE.observe(total)
        _m.BATCHES.inc(model=self.name)
        rids = [r.request_id for r in group]
        # nest the batch span under the span of the request that headed
        # the batch (cross-thread attach); `links` carries EVERY rider's
        # request id so one grep finds the dispatch a request rode on
        head_ctx = group[0].trace_ctx
        attach = _telemetry.tracer.attach(head_ctx) \
            if head_ctx is not None else contextlib.nullcontext()
        with attach, \
                _telemetry.trace_span("serve.batch", cat="serving",
                                      model=self.name,
                                      requests=len(group), rows=total,
                                      links=rids):
            try:
                def _val(a):
                    return a._data if isinstance(a, NDArray) \
                        else jnp.asarray(a)
                if len(group) == 1:
                    ins = group[0].arrays
                else:
                    ins = [jnp.concatenate(
                        [_val(r.arrays[i]) for r in group], axis=0)
                        for i in range(len(group[0].arrays))]

                def run():
                    _fault.inject("serving.infer", model=self.name,
                                  request_ids=rids)
                    return self.engine.predict(ins)

                try:
                    outs = _fault.retry_call(run, site="serving.infer",
                                             policy=self.retry_policy)
                except Exception as e:
                    self._fallback(group, e)
                    return
                off = 0
                for r in group:
                    r.outputs = [o[off:off + r.n] for o in outs]
                    off += r.n
                dt = time.monotonic() - t0
                self._avg_batch_seconds = dt \
                    if self._avg_batch_seconds <= 0.0 \
                    else 0.8 * self._avg_batch_seconds + 0.2 * dt
                self._degraded = False
                self.breaker.record_success()
            except Exception as e:      # worker must survive anything
                _telemetry.FAULT.publish(
                    site="serving.worker", event="crash",
                    kind=type(e).__name__, model=self.name,
                    request_ids=rids)
                for r in group:
                    r.error = e
            finally:
                done = time.monotonic()
                for r in group:
                    # the watchdog may already have failed (and woken)
                    # this rider — never double-count or clobber it
                    if not r.event.is_set():
                        _m.LATENCY.observe(done - r.t_submit)
                        r.event.set()

    def _fallback(self, group, err):
        """Batched dispatch failed after retries: run each request on
        its own so one poisoned batch can't fail every rider.  Singles
        bypass the ``serving.infer`` fault site — the plan already fired
        on the batch attempts.  Counts one consecutive failure on the
        circuit breaker (enough of these in a row trip it OPEN)."""
        _telemetry.FAULT.publish(site="serving.infer", event="fallback",
                                 kind=type(err).__name__,
                                 requests=len(group), model=self.name,
                                 request_ids=[r.request_id
                                              for r in group])
        _m.FALLBACKS.inc(model=self.name)
        self.breaker.record_failure(f"batch dispatch failed: "
                                    f"{type(err).__name__}")
        for r in group:
            try:
                r.outputs = self.engine.predict(r.arrays)
            except Exception as e:
                r.error = e

    # -- watchdog plane -------------------------------------------------
    def check_worker(self, hang_seconds: Optional[float] = None):
        """Detect a dead or hung worker (driven by
        ``lifecycle.Watchdog``, callable directly).  On detection: fail
        the in-flight group's riders with ``lifecycle.RequestAborted``,
        restart the worker on a fresh generation, trip the breaker and
        mark the model DEGRADED.  Returns the reason (``"died"`` /
        ``"hung"``) when a restart happened, else None.

        ``hang_seconds <= 0`` disables hang detection (dead-worker
        detection stays on)."""
        if hang_seconds is None:
            hang_seconds = _lc.default_hang_seconds()
        now = time.monotonic()
        with self._cv:
            if self._closed:
                return None
            if not self._thread.is_alive():
                reason = "died"
            elif hang_seconds > 0 and self._busy_since is not None \
                    and now - self._busy_since > float(hang_seconds):
                reason = "hung"
            else:
                return None
            failed = self._inflight or []
            self._inflight = None
            self._busy_since = None
            self._worker_gen += 1
            self._restarts += 1
            self._degraded = True
            self._thread = self._start_worker()
            self._cv.notify_all()
        for r in failed:
            r.fail(_lc.RequestAborted(
                f"{self.name}: batcher worker {reason}; request "
                f"{r.request_id} failed by the watchdog — retry on "
                "another replica"))
        # the watchdog event goes out BEFORE the breaker trip: the
        # flight recorder dumps on both, and the restart (with its rider
        # request ids) is the primary artifact of this incident
        _m.WATCHDOG_RESTARTS.inc(model=self.name)
        _telemetry.FAULT.publish(site="serving.worker", event="watchdog",
                                 kind=reason, model=self.name,
                                 riders=len(failed),
                                 request_ids=[r.request_id
                                              for r in failed])
        self.breaker.trip(f"worker {reason}")
        return reason

    @property
    def state(self) -> str:
        """This model's serving state (``lifecycle.SERVING`` /
        ``DEGRADED`` / ``UNHEALTHY`` / ``DRAINING``)."""
        with self._cv:
            if self._closed:
                return _lc.DRAINING
            worker_dead = not self._thread.is_alive()
        bs = self.breaker.state
        if worker_dead or bs == _lc.OPEN:
            return _lc.UNHEALTHY
        if self._degraded or bs == _lc.HALF_OPEN:
            return _lc.DEGRADED
        return _lc.SERVING

    @property
    def restarts(self) -> int:
        with self._cv:
            return self._restarts

    # -- lifecycle ------------------------------------------------------
    @property
    def pending(self) -> int:
        """Requests queued or riding the in-flight dispatch."""
        with self._cv:
            return len(self._queue) + len(self._inflight or ())

    @property
    def idle(self) -> bool:
        with self._cv:
            return not self._queue and self._busy_since is None

    def active_request_ids(self) -> dict:
        """Request ids currently queued / riding the in-flight dispatch
        (the flight recorder's "active requests" dump section)."""
        with self._cv:
            return {"queued": [r.request_id for r in self._queue],
                    "inflight": [r.request_id
                                 for r in (self._inflight or ())]}

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop intake.  ``drain=True`` (default) lets the worker finish
        everything already queued; ``drain=False`` fails pending
        requests immediately.  If the worker cannot finish inside
        ``timeout`` seconds (a wedged dispatch), every still-pending
        request is failed with a clear error instead of being left
        blocked on an event nobody will ever set.  Idempotent."""
        with self._cv:
            self._closed = True
            dropped = []
            if not drain:
                dropped = list(self._queue)
                self._queue.clear()
            self._cv.notify_all()
        for r in dropped:
            r.fail(MXNetError(f"batcher {self.name!r} closed"))
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # drain budget blown: the worker is wedged in a dispatch.
            # Strand nobody — fail everything still pending and retire
            # this worker generation so the zombie exits if it wakes.
            with self._cv:
                self._worker_gen += 1
                stranded = list(self._queue)
                self._queue.clear()
                stranded.extend(self._inflight or ())
                self._inflight = None
                self._busy_since = None
            for r in stranded:
                r.fail(_lc.RequestAborted(
                    f"batcher {self.name!r}: drain timed out after "
                    f"{timeout}s; request {r.request_id} abandoned"))
        with self._cv:
            _m.QUEUE_DEPTH.set(0, model=self.name)

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        with self._cv:
            depth = len(self._queue)
            restarts = self._restarts
        return {"model": self.name, "queue_depth": depth,
                "queue_size": self.queue_size,
                "max_batch_size": self.max_batch_size,
                "max_delay_ms": self.max_delay * 1000.0,
                "default_timeout_ms": self.default_timeout_ms,
                "closed": self._closed,
                "state": self.state,
                "breaker": self.breaker.state,
                "watchdog_restarts": restarts,
                "buckets": list(self.engine.buckets),
                "compiled_programs": self.engine.compiled_programs()}


# ===========================================================================
# ContinuousBatcher — per-slot join/leave generation serving
# ===========================================================================

class _GenRequest:
    """One generation request: a prompt, a token budget, and a stream of
    emitted tokens.  Unlike :class:`_Request` (one dispatch, one latch),
    a generation request spans MANY dispatches: tokens arrive one per
    decode step on ``_q`` and accumulate in ``tokens_out``; ``event``
    fires once, at finish (done / error / cancel)."""

    __slots__ = ("tokens", "n", "budget", "eos_id", "event", "error",
                 "tokens_out", "t_submit", "t_first", "t_emit",
                 "deadline", "model", "request_id", "trace_ctx",
                 "slot", "_q", "_cancelled",
                 "accepted_tokens", "draft_tokens",
                 "sampling", "seed", "logprobs_n", "logprobs_out",
                 "stops", "_machine")

    def __init__(self, tokens, budget, eos_id=None, deadline=None,
                 model="?", request_id=None, trace_ctx=None,
                 sampling=None):
        import queue as _pyqueue
        self.tokens = tokens            # prompt, np int32 1-D
        self.n = int(tokens.shape[0])
        self.budget = int(budget)       # max tokens to emit
        self.eos_id = eos_id
        # sampling plane (serving/sampling.py): the validated
        # SamplingParams (None: greedy), the EFFECTIVE seed (client's or
        # server-generated — echoed so any sampled response replays),
        # the clamped per-token logprobs top-N with its output list
        # (entry i describes tokens_out[i]; appended BEFORE the token is
        # queued so the streaming thread may index it immediately), the
        # stop token-id sequences, and the constrained-output machine
        self.sampling = sampling
        self.seed = sampling.seed if sampling is not None else None
        self.logprobs_n = int(sampling.logprobs) if sampling else 0
        self.logprobs_out: List[dict] = []
        self.stops = tuple(sampling.stop) if sampling else ()
        self._machine: Optional[JsonMaskMachine] = None
        self.event = threading.Event()
        self.error = None
        self.tokens_out: List[int] = []
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_emit = self.t_submit     # last emission (token latency)
        self.deadline = deadline
        self.model = model
        self.request_id = request_id or _telemetry.new_request_id()
        self.trace_ctx = trace_ctx
        self.slot: Optional[int] = None
        self._q = _pyqueue.Queue()
        self._cancelled = False
        # speculative-decoding accounting (stay 0 on the plain path):
        # draft_tokens counts tokens the draft proposed for THIS request,
        # accepted_tokens counts how many of those the target kept
        self.accepted_tokens = 0
        self.draft_tokens = 0

    # -- producer side (worker thread) ----------------------------------
    def _emit(self, tok: int) -> float:
        now = time.monotonic()
        if self.t_first is None:
            self.t_first = now
        gap = now - self.t_emit
        _m.TOKEN_LATENCY.observe(gap)
        self.t_emit = now
        self.tokens_out.append(int(tok))
        self._q.put(("tok", int(tok)))
        return gap

    def _emit_burst(self, toks) -> float:
        """Append a whole decode burst and flush it to the stream as
        individual ``("tok", t)`` events under ONE queue-lock
        acquisition — a k-token burst costs one notify pass instead of
        k ``put()`` round-trips on the consumer's mutex.  ``Queue`` is
        unbounded here so skipping ``not_full`` is safe; the manual
        bookkeeping mirrors ``Queue.put`` exactly (``not_empty`` shares
        ``mutex``).  The inter-burst gap is amortized evenly across the
        burst's tokens so the token-latency SLI keeps per-token units."""
        now = time.monotonic()
        if self.t_first is None:
            self.t_first = now
        n = len(toks)
        gap = (now - self.t_emit) / max(1, n)
        for _ in range(n):
            _m.TOKEN_LATENCY.observe(gap)
        self.t_emit = now
        self.tokens_out.extend(toks)
        q = self._q
        with q.mutex:
            q.queue.extend(("tok", t) for t in toks)
            q.unfinished_tasks += n
            q.not_empty.notify(n)
        return gap

    def _finish(self, error=None) -> None:
        if self.event.is_set():
            return
        self.error = error
        self.event.set()
        self._q.put(("end", error))

    def fail(self, err: Exception) -> None:
        self._finish(err)

    # -- consumer side --------------------------------------------------
    def cancel(self) -> None:
        """Ask the worker to free this request's slot at the next decode
        step boundary.  Safe from any thread; idempotent."""
        self._cancelled = True

    @property
    def done(self) -> bool:
        return self.event.is_set()

    def _bounded_wait(self, timeout):
        wait = timeout
        if self.deadline is not None:
            # small grace so the worker's own boundary check (which
            # frees the slot and stamps stage="decode") wins the race
            remaining = max(0.0, self.deadline - time.monotonic()) + 0.25
            wait = remaining if timeout is None else min(timeout,
                                                         remaining)
        return wait

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until generation finishes; returns ALL emitted tokens.
        Re-raises worker-side errors (deadline, abort, dispatch
        failure); a bare ``timeout`` raises ``TimeoutError``."""
        if not self.event.wait(self._bounded_wait(timeout)):
            if self.deadline is not None \
                    and time.monotonic() >= self.deadline:
                raise _lc.DeadlineExceeded(
                    f"{self.model}: generation request {self.request_id} "
                    "deadline exceeded")
            raise TimeoutError("generation request timed out")
        if self.error is not None:
            raise self.error
        return list(self.tokens_out)

    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as the worker emits them.  Closing the generator
        before the end (client disconnect) cancels the request — the
        slot frees on the next step boundary.  Worker-side errors
        re-raise here; ``lifecycle.Cancelled`` is swallowed (the
        consumer asked for it)."""
        import queue as _pyqueue
        try:
            while True:
                try:
                    kind, val = self._q.get(
                        timeout=self._bounded_wait(timeout))
                except _pyqueue.Empty:
                    if self.deadline is not None \
                            and time.monotonic() >= self.deadline:
                        raise _lc.DeadlineExceeded(
                            f"{self.model}: generation request "
                            f"{self.request_id} deadline exceeded")
                    raise TimeoutError("generation stream timed out")
                if kind == "tok":
                    yield val
                    continue
                if val is not None and not isinstance(val, _lc.Cancelled):
                    raise val
                return
        finally:
            if not self.event.is_set():
                self.cancel()


class _MultiGenRequest:
    """n>1 candidate fan-out: one handle over ``n`` independent child
    :class:`_GenRequest` streams, each decoding in its own slot under a
    derived seed (candidate 0 keeps the request seed, so an ``n=1``
    replay of the echoed seed reproduces it byte-for-byte).  The
    ``result()``/``request_id`` surface stays _GenRequest-shaped for
    back-compat — ``result()`` returns candidate 0's tokens,
    ``results()`` all of them."""

    def __init__(self, children, request_id: str):
        self.children = list(children)
        self.request_id = request_id

    @property
    def seed(self):
        return self.children[0].seed

    @property
    def request_ids(self):
        return [r.request_id for r in self.children]

    @property
    def accepted_tokens(self) -> int:
        return sum(r.accepted_tokens for r in self.children)

    @property
    def draft_tokens(self) -> int:
        return sum(r.draft_tokens for r in self.children)

    @property
    def logprobs_n(self) -> int:
        return self.children[0].logprobs_n

    @property
    def logprobs_out(self):
        return self.children[0].logprobs_out

    @property
    def done(self) -> bool:
        return all(r.done for r in self.children)

    def cancel(self) -> None:
        for r in self.children:
            r.cancel()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        return self.results(timeout)[0]

    def results(self, timeout: Optional[float] = None) -> List[List[int]]:
        """Block for every candidate; returns their token lists in
        candidate order.  The first child error re-raises (remaining
        candidates are cancelled — a half-failed fan-out has no
        well-defined response)."""
        out = []
        try:
            for r in self.children:
                out.append(r.result(timeout))
        except Exception:
            self.cancel()
            raise
        return out


class ContinuousBatcher(DynamicBatcher):
    """Continuous-batching front-end over one
    :class:`serving.engine.GenerationEngine`.

    The parent's core invariant — gather a FIFO group, dispatch ONCE,
    scatter — cannot serve autoregressive decode: requests finish at
    different times and new ones must not wait for the batch to drain.
    This subclass replaces the worker loop with per-slot join/leave over
    the engine's preallocated KV cache:

    * each iteration is one STEP BOUNDARY: free every slot whose request
      finished, was cancelled, or crossed its deadline
      (``mxtpu_serve_deadline_exceeded{stage="decode"}``); admit queued
      requests into the freed slots (one ``prefill`` dispatch each,
      emitting the first token); then advance ALL live slots with a
      single ``decode`` dispatch — one token per step, or up to
      ``engine.scan_steps`` tokens when :meth:`_burst_ready` sees
      steady state (no queued joins, cancels, or near deadlines) and
      the scanned ``decode_burst`` program takes over;
    * tokens stream back per-request as they are produced
      (:meth:`_GenRequest.stream`), so a late-arriving request emits its
      first token while earlier requests are still decoding — the
      continuous-admission property ``generate_smoke`` asserts;
    * everything the one-shot path had keeps working: backpressure,
      breaker, ``serving.queue``/``serving.infer`` fault sites (a
      ``hang`` during decode drills the watchdog; the restarted worker
      RESETS the cache — donated buffers a dying dispatch consumed are
      not trusted), request ids on every event, SLO accounting per
      finished generation, and ``serve.batch`` spans per decode step
      with ``slot.join``/``slot.leave`` child events so ``/trace``
      shows a request's whole decode lifetime.
    """

    def __init__(self, engine, token_strs=None, **kw):
        kw.setdefault("max_batch_size", engine.max_slots)
        self._slots: List[Optional[_GenRequest]] = \
            [None] * int(engine.max_slots)
        self._step = 0
        self._tokens_emitted = 0
        self._peak_slots = 0
        # sampling plane: token id -> string mapping for the
        # constrained-output (json_mode) machine (default: byte-level,
        # materialized lazily on the first constrained request), stop
        # limits, and host-side stop/trim accounting
        self._token_strs = list(token_strs) if token_strs is not None \
            else None
        self._max_stops = max(1, getenv_int("MXNET_SAMPLING_MAX_STOPS",
                                            4))
        self._stop_hits = 0
        self._stop_trimmed = 0
        # speculative decoding totals (see serving/metrics.py): verify
        # dispatches, tokens emitted from them, and draft proposals made
        self._spec_dispatches = 0
        self._spec_slot_steps = 0   # (live slot, dispatch) pairs
        self._spec_emitted = 0
        self._spec_accepted = 0
        self._spec_drafted = 0
        # dispatch economy: one batcher step = ONE target-model dispatch
        # (draft decodes ride on the draft model's own ledger).  Tokens
        # are per-slot-normalized, so per-step decode reads exactly 1.0,
        # the scanned burst path approaches 1/scan_steps at steady
        # state, and speculation reads 1/tokens-per-slot-per-dispatch
        # (< 1.0 when the draft earns its keep) — docs/observability.md.
        self._dpt_dispatches = 0
        self._dpt_tokens = 0.0
        # multi-token burst dispatches taken (engine.scan_steps >= 1 and
        # _burst_ready said steady state) — drives dispatches_per_token
        # toward 1/k; docs/serving.md "Multi-token decode bursts"
        self._burst_dispatches = 0
        self._kv_starved_sweeps = 0
        self._kv_starve_threshold = max(1, getenv_int(
            "MXNET_SERVE_KV_STARVE_SWEEPS", 3))
        # health plane (health.py): last folded decode-step stats and the
        # running nonfinite-generation count, surfaced in stats()/health
        self._decode_health_last: Optional[dict] = None
        self._nonfinite_generations = 0
        super().__init__(engine, **kw)

    # -- KV-capacity starvation (the ``kv:<model>`` readiness blocker) --
    def check_worker(self, hang_seconds: Optional[float] = None):
        """The watchdog sweep doubles as the KV-starvation sampler: a
        paged pool with zero free blocks for
        ``MXNET_SERVE_KV_STARVE_SWEEPS`` consecutive sweeps flips
        :attr:`kv_starved`, which surfaces as a ``kv:<model>`` blocker
        on ``/readyz`` — the router routes generation to replicas with
        capacity instead of eating this replica's 429s.  One free block
        resets the count (starvation must be sustained, not a blip)."""
        pool = getattr(self.engine, "pool", None)
        if pool is not None:
            if pool.free_blocks == 0:
                self._kv_starved_sweeps += 1
                if self._kv_starved_sweeps == self._kv_starve_threshold:
                    _telemetry.FAULT.publish(
                        site="serving.kv", event="starved",
                        kind="exhausted", model=self.name,
                        sweeps=self._kv_starved_sweeps)
            else:
                self._kv_starved_sweeps = 0
        return super().check_worker(hang_seconds)

    @property
    def kv_starved(self) -> bool:
        """True while the paged BlockPool has been fully exhausted for
        ``MXNET_SERVE_KV_STARVE_SWEEPS`` consecutive watchdog sweeps."""
        return self._kv_starved_sweeps >= self._kv_starve_threshold

    # admission control: the parent's rows//max_batch estimate is
    # meaningless for multi-dispatch requests — deadlines are enforced
    # at queue-shed and at every decode boundary instead
    def _estimate_wait_locked(self) -> float:
        return 0.0

    # -- submit ---------------------------------------------------------
    def _token_strings(self):
        """Token id -> string mapping for the constrained-output
        machine (ctor ``token_strs``; default byte-level, materialized
        on the first constrained request)."""
        if self._token_strs is None:
            vs = int(getattr(self.engine, "vocab_size", 0) or 0)
            self._token_strs = [chr(i) for i in range(vs)]
        return self._token_strs

    def submit_async(self, tokens, max_new_tokens: int = 32,
                     timeout_ms: Optional[float] = None,
                     request_id: Optional[str] = None,
                     eos_id: Optional[int] = None,
                     sampling: Optional[SamplingParams] = None):
        """Enqueue one generation request; returns a handle whose
        ``stream()`` yields tokens as they are produced and whose
        ``result()`` blocks for the full list.  Raises
        :class:`QueueFullError` under backpressure, ``BreakerOpen``
        while the breaker is OPEN, ``ValueError`` for an unservable
        prompt/budget or out-of-range sampling parameters.

        ``sampling`` (None: greedy) is validated here, its ``logprobs``
        clamped to the engine's baked top-N, and — for a sampled
        request without a client seed — an effective seed is generated
        and stored on the handle (``req.seed``) so the response is
        replayable.  ``sampling.n > 1`` fans out into ``n`` independent
        single-candidate children over distinct slots (derived seeds;
        candidate 0 keeps the request seed) behind one
        :class:`_MultiGenRequest` handle."""
        from dataclasses import replace as _dc_replace
        if request_id is None:
            request_id = _telemetry.new_request_id()
        if sampling is not None:
            sampling = sampling.validate(
                max_stops=self._max_stops,
                max_n=int(self.engine.max_slots))
            lp_cap = int(getattr(self.engine, "logprobs_topn", 0) or 0)
            if sampling.logprobs > lp_cap:
                sampling = _dc_replace(sampling, logprobs=lp_cap)
            if sampling.sampled and sampling.seed is None:
                import os as _os
                sampling = _dc_replace(
                    sampling,
                    seed=int.from_bytes(_os.urandom(8), "big") >> 1)
        if sampling is not None and sampling.n > 1:
            base = sampling.seed
            children: List[_GenRequest] = []
            try:
                for i in range(sampling.n):
                    child = _dc_replace(
                        sampling, n=1,
                        seed=derive_candidate_seed(base, i)
                        if base is not None else None)
                    children.append(self._submit_one(
                        tokens, max_new_tokens, timeout_ms=timeout_ms,
                        request_id=f"{request_id}.{i}", eos_id=eos_id,
                        sampling=child))
            except Exception:
                for c in children:   # no half-admitted fan-outs
                    c.cancel()
                raise
            return _MultiGenRequest(children, request_id)
        return self._submit_one(tokens, max_new_tokens,
                                timeout_ms=timeout_ms,
                                request_id=request_id, eos_id=eos_id,
                                sampling=sampling)

    def _submit_one(self, tokens, max_new_tokens: int = 32,
                    timeout_ms: Optional[float] = None,
                    request_id: Optional[str] = None,
                    eos_id: Optional[int] = None,
                    sampling: Optional[SamplingParams] = None) \
            -> _GenRequest:
        import numpy as _np
        _fault.inject("serving.queue", model=self.name,
                      request_id=request_id)
        self.breaker.allow()
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        n = int(toks.shape[0])
        max_len = int(self.engine.max_len)
        if n < 1:
            raise ValueError(f"{self.name}: empty prompt")
        if n > max_len - 1:
            raise ValueError(
                f"{self.name}: prompt length {n} leaves no room to "
                f"generate (max_len {max_len})")
        budget = min(int(max_new_tokens), max_len - n)
        if budget < 1:
            raise ValueError(
                f"{self.name}: max_new_tokens must be >= 1")
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        req = _GenRequest(toks, budget, eos_id=eos_id,
                          deadline=_lc.deadline_from_ms(timeout_ms),
                          model=self.name, request_id=request_id,
                          trace_ctx=_telemetry.tracer.current(),
                          sampling=sampling)
        if sampling is not None and sampling.json_mode:
            req._machine = JsonMaskMachine(self._token_strings())
            _m.SAMPLE_CONSTRAINED.inc(model=self.name)
        with self._cv:
            if self._closed:
                raise MXNetError(f"batcher {self.name!r} is closed")
            # capacity-aware backpressure: a queue the KV cache can never
            # drain fast enough is just a slow 504 — bound admissions by
            # how many streams of THIS request's footprint the cache
            # sustains, and tell the client when to come back
            allowed = self.queue_size
            cap_fn = getattr(self.engine, "kv_capacity_tokens", None)
            if cap_fn is not None:
                streams = max(1, min(int(self.engine.max_slots),
                                     int(cap_fn()) // (n + budget)))
                allowed = min(allowed, 4 * streams)
            if len(self._queue) >= allowed:
                _m.REJECTED.inc(model=self.name)
                retry = max(1.0, min(30.0,
                                     self._avg_batch_seconds * budget))
                raise QueueFullError(
                    f"{self.name}: queue full ({len(self._queue)} "
                    f"pending, {allowed} admitted for this request "
                    "size) — backpressure", retry_after=retry)
            self._queue.append(req)
            _m.QUEUE_DEPTH.set(len(self._queue), model=self.name)
            self._cv.notify_all()
        _m.REQUESTS.inc(model=self.name)
        _m.SAMPLED_REQUESTS.inc(
            model=self.name,
            mode="sampled" if (sampling is not None and sampling.sampled)
            else "greedy")
        return req

    def submit(self, tokens, max_new_tokens: int = 32,
               timeout: Optional[float] = None,
               timeout_ms: Optional[float] = None,
               request_id: Optional[str] = None,
               eos_id: Optional[int] = None,
               sampling: Optional[SamplingParams] = None) -> List[int]:
        """Synchronous generation: enqueue, wait, return all emitted
        tokens.  (SLO accounting happens worker-side at finish, for the
        streaming and sync paths alike; admission failures are recorded
        here.)"""
        if request_id is None:
            request_id = _telemetry.new_request_id()
        with _telemetry.trace_span("serve.request", cat="serving",
                                   model=self.name,
                                   request_id=request_id):
            try:
                req = self.submit_async(
                    tokens, max_new_tokens, timeout_ms=timeout_ms,
                    request_id=request_id, eos_id=eos_id,
                    sampling=sampling)
            except Exception:
                _slo.tracker.record(self.name, 0.0, ok=False)
                raise
            return req.result(timeout)

    # -- worker: the continuous loop ------------------------------------
    def _worker(self, gen: int):
        # a replaced worker's slots (and the donated cache a dying
        # dispatch may have consumed) are not trusted: start clean
        with self._cv:
            stale = [r for r in self._slots if r is not None]
            self._slots = [None] * int(self.engine.max_slots)
        if stale or gen > 0:
            self.engine.reset()
        for r in stale:     # watchdog already failed inflight riders
            r._finish(_lc.RequestAborted(
                f"{self.name}: worker replaced; request {r.request_id} "
                "aborted"))
        while True:
            leavers, joins, live = self._boundary(gen)
            if leavers is None:
                return
            if not (leavers or joins or live):
                continue    # woke empty; next wait happens in _boundary
            self._run_step(gen, leavers, joins)
            with self._cv:
                if gen == self._worker_gen:
                    self._busy_since = None
                    self._inflight = None

    def _boundary(self, gen: int):
        """One step boundary, under ``_cv``: collect slots to free
        (finished requests were freed eagerly in ``_run_step``; here we
        catch cancels and deadline expiries), admit queued requests into
        free slots, and decide whether there is work.  Returns
        ``(leavers, joins, live)`` — or ``(None, None, None)`` when this
        worker generation is done (closed+drained or replaced)."""
        with self._cv:
            while True:
                if gen != self._worker_gen:
                    return None, None, None
                now = time.monotonic()
                self._heartbeat = now
                leavers = []
                for s, r in enumerate(self._slots):
                    if r is None:
                        continue
                    if r._cancelled:
                        leavers.append((s, r, "cancelled"))
                        self._slots[s] = None
                    elif r.deadline is not None and r.deadline <= now:
                        leavers.append((s, r, "deadline"))
                        self._slots[s] = None
                while self._queue \
                        and self._queue[0].deadline is not None \
                        and self._queue[0].deadline <= now:
                    self._expire_locked(self._queue.popleft())
                joins = []
                free = [s for s, r in enumerate(self._slots)
                        if r is None]
                can = getattr(self.engine, "can_admit", None)
                est = getattr(self.engine, "reserve_estimate", None)
                reserved = 0    # blocks promised to earlier admits
                while self._queue and free:
                    req = self._queue[0]
                    if can is not None and not can(
                            req.tokens, req.n + req.budget, reserved):
                        break   # head-of-line waits for blocks to free
                    self._queue.popleft()
                    if est is not None:
                        reserved += est(req.n + req.budget)
                    slot = free.pop(0)
                    req.slot = slot
                    self._slots[slot] = req
                    joins.append((slot, req))
                live = [(s, r) for s, r in enumerate(self._slots)
                        if r is not None]
                _m.QUEUE_DEPTH.set(len(self._queue), model=self.name)
                _m.SLOTS_IN_USE.set(len(live), model=self.name)
                self._peak_slots = max(self._peak_slots, len(live))
                if leavers or joins or live:
                    self._busy_since = now
                    self._inflight = [r for _, r in live]
                    return leavers, joins, live
                if self._closed and not self._queue:
                    return None, None, None
                self._cv.wait(0.05)

    def _run_step(self, gen: int, leavers, joins):
        """One continuous-batching step OUTSIDE the lock: emit
        ``slot.leave`` events for boundary leavers, prefill the joins
        (first token each), then ONE decode dispatch advancing every
        live slot.  The ``serve.batch`` span wraps the whole step; its
        ``links`` carry every live request id."""
        self._step += 1
        with self._cv:
            live = [(s, r) for s, r in enumerate(self._slots)
                    if r is not None]
        rids = [r.request_id for _, r in live]
        head_ctx = live[0][1].trace_ctx if live else None
        attach = _telemetry.tracer.attach(head_ctx) \
            if head_ctx is not None else contextlib.nullcontext()
        with attach, \
                _telemetry.trace_span("serve.batch", cat="serving",
                                      model=self.name, step=self._step,
                                      slots=len(live), links=rids):
            for slot, req, reason in leavers:
                self._leave(slot, req, reason)
            for slot, req in joins:
                self._join(slot, req, gen)
            with self._cv:
                live = [(s, r) for s, r in enumerate(self._slots)
                        if r is not None]
            if live:
                # constrained slots update their vocab mask host-side
                # at every emit boundary — the k+1-wide spec verify
                # (like the burst scan) would sample past a stale mask
                dyn = any(r._machine is not None for _, r in live)
                if not dyn and \
                        getattr(self.engine, "draft", None) is not None:
                    self._spec_once(gen, live)
                elif not dyn and self._burst_ready(live):
                    self._decode_burst_once(gen, live)
                else:
                    self._decode_once(gen, live)

    def _join(self, slot: int, req: _GenRequest, gen: int):
        """Admit one request mid-flight: its prefill dispatch runs
        between decode steps and emits the first token."""
        with _telemetry.trace_span("slot.join", cat="serving",
                                   model=self.name, slot=slot,
                                   request_id=req.request_id,
                                   prompt_tokens=req.n):
            try:
                # sampling state rides the slot: params (and the
                # constraint mask row, for json_mode) must be installed
                # BEFORE prefill so the first sampled token is keyed
                self.engine.set_slot_sampling(slot, req.sampling)
                if req._machine is not None:
                    self.engine.update_slot_bias(
                        slot, req._machine.mask(budget=req.budget))
                first = self.engine.prefill(
                    req.tokens, slot, reserve_tokens=req.n + req.budget)
            except Exception as e:
                with self._cv:
                    if self._slots[slot] is req:
                        self._slots[slot] = None
                self._fail(req, e)
                return
        lp = getattr(self.engine, "last_prefill_logprobs",
                     lambda: None)()
        if lp is not None:
            self._push_logprobs(req, lp[0], lp[1])
        self._emit(req, first)
        self._advance_machine(slot, req, first)
        if self._maybe_finished(req):
            self._free_slot(slot, req, "finished")

    # mxtpu-lint: hot-path
    def _decode_once(self, gen: int, live):
        """ONE decode dispatch for every slot (free slots ride along at
        position 0); emit each live slot's token and free finished slots
        immediately."""
        import numpy as _np
        S = int(self.engine.max_slots)
        last = _np.zeros(S, _np.int32)
        pos = _np.zeros(S, _np.int32)
        for s, r in live:
            last[s] = r.tokens_out[-1]
            pos[s] = r.n + len(r.tokens_out) - 1
        rids = [r.request_id for _, r in live]
        _m.BATCHES.inc(model=self.name)
        _m.BATCH_SIZE.observe(len(live))

        def run():
            _fault.inject("serving.infer", model=self.name,
                          request_ids=rids)
            if self._current_gen() != gen:
                raise _lc.RequestAborted(
                    f"{self.name}: stale worker generation")
            return self.engine.decode(last, pos)

        t0 = time.monotonic()
        try:
            nxt = _fault.retry_call(run, site="serving.infer",
                                    policy=self.retry_policy)
        except Exception as e:
            self._decode_failed(gen, live, e)
            return
        dt = time.monotonic() - t0
        _m.DECODE_STEP.observe(dt)
        self._avg_batch_seconds = dt if self._avg_batch_seconds <= 0.0 \
            else 0.8 * self._avg_batch_seconds + 0.2 * dt
        self._degraded = False
        self.breaker.record_success()
        self._dpt_dispatches += 1
        self._dpt_tokens += 1.0     # one token per live slot, per slot
        _m.DISPATCHES_PER_TOKEN.set(
            self._dpt_dispatches / max(self._dpt_tokens, 1e-9),
            model=self.name)
        self._fold_decode_health(live)
        lp = self.engine.last_logprobs()    # (S, N) pair or None
        for s, r in live:
            if lp is not None:
                self._push_logprobs(r, lp[0][s], lp[1][s])
            # the stream boundary: ONE scalar pull per emitted token
            tok = int(nxt[s])  # mxtpu-lint: disable=host-sync-in-hot-path
            self._emit(r, tok)
            self._advance_machine(s, r, tok)
            if self._maybe_finished(r):
                self._free_slot(s, r, "finished")

    def _burst_ready(self, live) -> bool:
        """Steady-state gate for the multi-token burst path.  The
        k-step scanned dispatch is opaque to the scheduler — no join,
        cancel, or deadline check can land mid-burst — so only take it
        when none of that boundary work could be pending: the queue is
        empty (an admit would otherwise wait up to k tokens for its
        slot), no rider has asked to cancel, and every live deadline
        clears a conservative k×(per-dispatch EWMA) worst case.  Any
        `no` falls back to the per-step path, which is always correct —
        the gate only trades throughput for boundary granularity."""
        k = int(getattr(self.engine, "scan_steps", 0) or 0)
        if k < 1:
            return False
        with self._cv:
            if self._queue:
                return False
        horizon = time.monotonic() \
            + k * max(self._avg_batch_seconds, 1e-4)
        for _, r in live:
            if r._cancelled:
                return False
            if r.deadline is not None and r.deadline <= horizon:
                return False
            # a constrained slot needs its mask refreshed at EVERY emit
            # boundary — the k-step scan can't see host-side updates
            if r._machine is not None:
                return False
        return True

    # mxtpu-lint: hot-path
    def _decode_burst_once(self, gen: int, live):
        """ONE scanned dispatch advances every live slot by up to
        ``engine.scan_steps`` tokens with in-program termination (a
        finished slot freezes inside the scan — see
        ``GenerationEngine.decode_burst``); fan each slot's emitted
        prefix out to its SSE queue as a batch and free finished slots.
        Token-for-token identical to k calls of :meth:`_decode_once` —
        only the dispatch grouping and the emit batching change."""
        import numpy as _np
        S = int(self.engine.max_slots)
        last = _np.zeros(S, _np.int32)
        pos = _np.zeros(S, _np.int32)
        bud = _np.ones(S, _np.int32)
        eos = _np.full(S, -1, _np.int32)
        act = _np.zeros(S, bool)
        for s, r in live:
            last[s] = r.tokens_out[-1]
            pos[s] = r.n + len(r.tokens_out) - 1
            bud[s] = r.budget - len(r.tokens_out)
            if r.eos_id is not None:
                eos[s] = int(r.eos_id)
            act[s] = True
        rids = [r.request_id for _, r in live]
        _m.BATCHES.inc(model=self.name)
        _m.BATCH_SIZE.observe(len(live))

        def run():
            _fault.inject("serving.infer", model=self.name,
                          request_ids=rids)
            if self._current_gen() != gen:
                raise _lc.RequestAborted(
                    f"{self.name}: stale worker generation")
            return self.engine.decode_burst(last, pos, bud, eos, act)

        t0 = time.monotonic()
        try:
            toks, emitted = _fault.retry_call(
                run, site="serving.infer", policy=self.retry_policy)
        except Exception as e:
            self._decode_failed(gen, live, e)
            return
        dt = time.monotonic() - t0
        _m.DECODE_STEP.observe(dt)
        self._avg_batch_seconds = dt if self._avg_batch_seconds <= 0.0 \
            else 0.8 * self._avg_batch_seconds + 0.2 * dt
        self._degraded = False
        self.breaker.record_success()
        self._fold_decode_health(live)
        self._burst_dispatches += 1
        lp = self.engine.last_logprobs()    # (k, S, N) pair or None
        total = 0
        for s, r in live:
            # the stream boundary: one bounded pull per rider burst
            n = int(emitted[s])  # mxtpu-lint: disable=host-sync-in-hot-path
            if n < 1:
                continue
            # mxtpu-lint: disable=host-sync-in-hot-path
            new = [int(t) for t in toks[:n, s]]
            stopped = False
            if r.stops:
                # stop sequences are detected host-side AT the emit
                # boundary: keep through the stop, discard the
                # over-generated tail BEFORE anything reaches the
                # client's stream
                kept, stopped = stop_trim(r.tokens_out, new, r.stops)
                if stopped:
                    self._stop_hits += 1
                    self._stop_trimmed += n - kept
                    _m.SAMPLE_STOP_HITS.inc(model=self.name)
                    _m.SAMPLE_STOP_TRIMMED.inc(n - kept,
                                               model=self.name)
                    new = new[:kept]
                    n = kept
            if lp is not None:
                for j in range(n):
                    self._push_logprobs(r, lp[0][j, s], lp[1][j, s])
            self._emit_burst(r, new)
            total += n
            # `stopped` already counted the hit — bypass the endswith
            # re-check in _maybe_finished to keep the counter honest
            if stopped or self._maybe_finished(r):
                self._free_slot(s, r, "finished")
        _m.DECODE_BURST_TOKENS.observe(total)
        # dispatch economy: ONE dispatch bought up to k tokens per slot
        self._dpt_dispatches += 1
        self._dpt_tokens += total / max(1, len(live))
        _m.DISPATCHES_PER_TOKEN.set(
            self._dpt_dispatches / max(self._dpt_tokens, 1e-9),
            model=self.name)

    def _fold_decode_health(self, live):
        """Health plane: fold the dispatch's device-side logit stats
        (``engine.last_decode_health``) into the ``mxtpu_health_*``
        series and — on a non-finite row — a ``nonfinite_generation``
        anomaly naming the implicated request ids.  The token pull in
        ``engine.decode`` already synced this dispatch, so these reads
        retire without a device round-trip."""
        hd = getattr(self.engine, "last_decode_health", lambda: None)()
        if hd is None or not live:
            return
        import numpy as _np
        lmax, ent, fin = hd
        # same emit boundary as the token pull above
        lmax = _np.asarray(lmax)  # mxtpu-lint: disable=host-sync-in-hot-path
        ent = _np.asarray(ent)    # mxtpu-lint: disable=host-sync-in-hot-path
        fin = _np.asarray(fin)    # mxtpu-lint: disable=host-sync-in-hot-path
        slots = [s for s, _ in live]
        self._decode_health_last = {
            "step": self._step,
            "logit_max": float(lmax[slots].max()),
            "entropy_mean": float(ent[slots].mean()),
            "finite": bool(fin[slots].all()),
        }
        _m.HEALTH_LOGIT_MAX.set(self._decode_health_last["logit_max"],
                                model=self.name)
        _m.HEALTH_DECODE_ENTROPY.set(
            self._decode_health_last["entropy_mean"], model=self.name)
        bad = [r.request_id for s, r in live if not bool(fin[s])]
        if bad:
            self._nonfinite_generations += 1
            _m.NONFINITE_GENERATIONS.inc(model=self.name)
            _health.serving_anomaly(
                self.name, self._step, bad,
                detail=f"non-finite decode logits at step {self._step} "
                       f"for request(s) {', '.join(bad)}")

    # mxtpu-lint: hot-path
    def _spec_once(self, gen: int, live):
        """ONE speculative step for every slot: k draft dispatches plus
        ONE k+1-wide verify advance each live slot by 1..k+1 tokens.
        Token-for-token identical to :meth:`_decode_once` — only the
        grouping into dispatches changes.  Join/leave stays at step
        boundaries, so a stream that joined mid-flight never observes a
        neighbor's rejected-token rollback (rollback happens inside
        ``spec_step``, before any rider's next dispatch)."""
        import numpy as _np
        S = int(self.engine.max_slots)
        k = int(self.engine.spec_k)
        last = _np.zeros(S, _np.int32)
        pos = _np.zeros(S, _np.int32)
        for s, r in live:
            last[s] = r.tokens_out[-1]
            pos[s] = r.n + len(r.tokens_out) - 1
        rids = [r.request_id for _, r in live]
        _m.BATCHES.inc(model=self.name)
        _m.BATCH_SIZE.observe(len(live))

        def run():
            _fault.inject("serving.infer", model=self.name,
                          request_ids=rids)
            if self._current_gen() != gen:
                raise _lc.RequestAborted(
                    f"{self.name}: stale worker generation")
            return self.engine.spec_step(last, pos)

        t0 = time.monotonic()
        try:
            burst, accepted = _fault.retry_call(
                run, site="serving.infer", policy=self.retry_policy)
        except Exception as e:
            self._decode_failed(gen, live, e)
            return
        dt = time.monotonic() - t0
        _m.DECODE_STEP.observe(dt)
        _m.SPEC_STEP.observe(dt)
        self._avg_batch_seconds = dt if self._avg_batch_seconds <= 0.0 \
            else 0.8 * self._avg_batch_seconds + 0.2 * dt
        self._degraded = False
        self.breaker.record_success()
        # accounting lives HERE, not in the engine: free slots ride
        # along in the dispatch at position 0 and their accepts are
        # meaningless.  Of a request's emitted burst, everything past
        # the first token is a draft proposal the target kept — a
        # budget/eos cut mid-burst caps the accepted count to match.
        self._spec_dispatches += 1
        lp = getattr(self.engine, "last_verify_logprobs",
                     lambda: None)()     # (S, Q, N) pair or None
        step_emitted = 0
        step_accepted = 0
        for s, r in live:
            n_emit = 0
            # the stream boundary: scalar pulls gate each emitted token
            # mxtpu-lint: disable=host-sync-in-hot-path
            for j in range(int(accepted[s]) + 1):
                if lp is not None:
                    self._push_logprobs(r, lp[0][s, j], lp[1][s, j])
                # mxtpu-lint: disable=host-sync-in-hot-path
                self._emit(r, int(burst[s, j]))
                n_emit += 1
                if self._maybe_finished(r):
                    self._free_slot(s, r, "finished")
                    break
            r.draft_tokens += k
            r.accepted_tokens += n_emit - 1
            step_emitted += n_emit
            step_accepted += n_emit - 1
        self._spec_emitted += step_emitted
        self._spec_accepted += step_accepted
        self._spec_drafted += len(live) * k
        self._spec_slot_steps += len(live)
        _m.SPEC_DISPATCHES.inc(model=self.name)
        _m.SPEC_DRAFT_TOKENS.inc(len(live) * k, model=self.name)
        _m.SPEC_ACCEPTED_TOKENS.inc(step_accepted, model=self.name)
        # per live slot per verify dispatch: 1.0 means the draft never
        # helps, k+1 is the ceiling (full accept + bonus token)
        _m.SPEC_TOKENS_PER_DISPATCH.set(
            self._spec_emitted / self._spec_slot_steps, model=self.name)
        _m.SPEC_ACCEPT_RATE.set(
            self._spec_accepted / max(1, self._spec_drafted),
            model=self.name,
            mode="sampled" if any(
                r.sampling is not None and r.sampling.sampled
                for _, r in live) else "greedy")
        self._dpt_dispatches += 1
        self._dpt_tokens += step_emitted / max(1, len(live))
        _m.DISPATCHES_PER_TOKEN.set(
            self._dpt_dispatches / max(self._dpt_tokens, 1e-9),
            model=self.name)

    # -- step-boundary helpers ------------------------------------------
    def _push_logprobs(self, req: _GenRequest, vals, ids):
        """Append one per-token top-N logprobs record (sliced to the
        request's clamp) alongside the token about to be emitted."""
        n = req.logprobs_n
        if n < 1 or vals is None:
            return
        # the engine stashed these as host numpy at the dispatch's own
        # sync point (see engine.last_logprobs) — no device round-trip
        req.logprobs_out.append({
            "token_ids": [int(i) for i in ids[:n]],    # mxtpu-lint: disable=host-sync-in-hot-path
            "logprobs": [float(v) for v in vals[:n]],  # mxtpu-lint: disable=host-sync-in-hot-path
        })

    def _advance_machine(self, slot: int, req: _GenRequest, tok: int):
        """Constrained-output emit boundary: feed the token just
        emitted to the request's grammar machine and install the next
        step's vocab mask (a traced operand of the NEXT dispatch)."""
        m = req._machine
        if m is None:
            return
        # tok is the already-pulled host scalar from the emit boundary
        m.advance(int(tok))  # mxtpu-lint: disable=host-sync-in-hot-path
        if not m.done:
            self.engine.update_slot_bias(
                slot, m.mask(budget=req.budget - len(req.tokens_out)))

    def _emit(self, req: _GenRequest, tok: int):
        gap = req._emit(tok)
        self._tokens_emitted += 1
        _m.GENERATE_TOKENS.inc(model=self.name)
        if req.sampling is not None and req.sampling.sampled:
            _m.SAMPLE_TOKENS.inc(model=self.name)
        # feed the token-latency SLI (MXNET_SERVE_SLO_TOKEN_P99_MS)
        _slo.tracker.record_token(self.name, gap)

    def _emit_burst(self, req: _GenRequest, toks):
        """Burst-path twin of :meth:`_emit`: one queue flush for the
        whole burst, but the SLI and counters stay per-token — each of
        the n tokens records the amortized gap, so ``token_window``
        counts and the p99 keep their per-token meaning."""
        gap = req._emit_burst(toks)
        n = len(toks)
        self._tokens_emitted += n
        _m.GENERATE_TOKENS.inc(n, model=self.name)
        if n and req.sampling is not None and req.sampling.sampled:
            _m.SAMPLE_TOKENS.inc(n, model=self.name)
        for _ in range(n):
            _slo.tracker.record_token(self.name, gap)

    def _maybe_finished(self, req: _GenRequest) -> bool:
        if len(req.tokens_out) >= req.budget:
            return True
        if req.eos_id is not None \
                and req.tokens_out[-1] == int(req.eos_id):
            return True
        if req._machine is not None and req._machine.done:
            return True
        if req.stops:
            out = req.tokens_out
            for stop in req.stops:
                if len(out) >= len(stop) \
                        and tuple(out[-len(stop):]) == stop:
                    self._stop_hits += 1
                    _m.SAMPLE_STOP_HITS.inc(model=self.name)
                    return True
        return False

    def _free_slot(self, slot: int, req: _GenRequest, reason: str):
        with self._cv:
            if self._slots[slot] is req:
                self._slots[slot] = None
            _m.SLOTS_IN_USE.set(
                sum(1 for r in self._slots if r is not None),
                model=self.name)
        self._leave(slot, req, reason)

    def _leave(self, slot: int, req: _GenRequest, reason: str):
        """Emit the ``slot.leave`` event and settle the request: ok for
        ``finished``, ``Cancelled`` for a client that went away,
        ``DeadlineExceeded`` (stage=decode) for a budget bust.  Paged
        engines get the slot's KV blocks back here (decref — shared
        prefix blocks survive for other readers)."""
        rel = getattr(self.engine, "release_slot", None)
        if rel is not None:
            rel(slot)
        with _telemetry.trace_span("slot.leave", cat="serving",
                                   model=self.name, slot=slot,
                                   request_id=req.request_id,
                                   reason=reason,
                                   tokens=len(req.tokens_out)):
            pass
        dt = time.monotonic() - req.t_submit
        if reason == "finished":
            _m.LATENCY.observe(dt)
            _slo.tracker.record(self.name, dt, ok=True)
            req._finish(None)
        elif reason == "cancelled":
            _m.CANCELLED.inc(model=self.name)
            _telemetry.FAULT.publish(
                site="serving.generate", event="cancelled",
                model=self.name, request_id=req.request_id,
                tokens=len(req.tokens_out))
            # a cancel is the client's choice, not an SLO burn
            req._finish(_lc.Cancelled(
                f"{self.name}: request {req.request_id} cancelled after "
                f"{len(req.tokens_out)} tokens"))
        elif reason == "deadline":
            _m.DEADLINE_EXCEEDED.inc(model=self.name, stage="decode")
            _telemetry.FAULT.publish(
                site="serving.deadline", event="deadline", kind="decode",
                model=self.name, request_id=req.request_id,
                tokens=len(req.tokens_out))
            _slo.tracker.record(self.name, dt, ok=False)
            req._finish(_lc.DeadlineExceeded(
                f"{self.name}: request {req.request_id} deadline "
                f"exceeded mid-decode after {len(req.tokens_out)} "
                "tokens"))
        else:
            _slo.tracker.record(self.name, dt, ok=False)
            req._finish(_lc.RequestAborted(
                f"{self.name}: request {req.request_id} aborted "
                f"({reason})"))

    def _fail(self, req: _GenRequest, err: Exception):
        _slo.tracker.record(self.name,
                            time.monotonic() - req.t_submit, ok=False)
        _telemetry.FAULT.publish(
            site="serving.generate", event="error",
            kind=type(err).__name__, model=self.name,
            request_id=req.request_id)
        req._finish(err)

    def _decode_failed(self, gen: int, live, err: Exception):
        """A decode dispatch failed after retries.  There is no per-slot
        fallback — the cache is shared and may have been consumed by
        donation — so fail every rider, free all slots, and reset the
        cache so the next admission starts clean."""
        if _tdev.is_oom(err):
            # RESOURCE_EXHAUSTED: name the implicated requests on the
            # oom flight dump (the engine funnel already reported the
            # failure itself, but only the batcher knows the riders)
            _tdev.report_oom(
                "serving.infer", err, model=self.name,
                request_ids=[r.request_id for _, r in live])
        _telemetry.FAULT.publish(
            site="serving.infer", event="fallback",
            kind=type(err).__name__, model=self.name,
            requests=len(live),
            request_ids=[r.request_id for _, r in live])
        _m.FALLBACKS.inc(model=self.name)
        self.breaker.record_failure(
            f"decode dispatch failed: {type(err).__name__}")
        with self._cv:
            for s, r in live:
                if self._slots[s] is r:
                    self._slots[s] = None
            _m.SLOTS_IN_USE.set(0, model=self.name)
            current = gen == self._worker_gen
        # reset OUTSIDE _cv: it dispatches to the device and can wedge,
        # and the watchdog needs _cv to even diagnose a wedged worker.
        # A superseded worker (gen bumped after the check) skips reset
        # anyway; the restart path re-warms the engine itself.
        if current:
            self.engine.reset()
        for _, r in live:
            self._fail(r, err)

    # -- introspection ---------------------------------------------------
    @property
    def idle(self) -> bool:
        with self._cv:
            return not self._queue \
                and all(r is None for r in self._slots)

    @property
    def pending(self) -> int:
        with self._cv:
            return len(self._queue) \
                + sum(1 for r in self._slots if r is not None)

    def active_request_ids(self) -> dict:
        with self._cv:
            return {"queued": [r.request_id for r in self._queue],
                    "inflight": [r.request_id for r in self._slots
                                 if r is not None]}

    def slots_in_use(self) -> int:
        with self._cv:
            return sum(1 for r in self._slots if r is not None)

    def stats(self) -> dict:
        out = super().stats()
        with self._cv:
            out.update({
                "kind": "generation",
                "max_slots": int(self.engine.max_slots),
                "max_len": int(self.engine.max_len),
                "slots_in_use": sum(1 for r in self._slots
                                    if r is not None),
                "decode_steps": self._step,
                "decode_scan_steps":
                    int(getattr(self.engine, "scan_steps", 0) or 0),
                "decode_burst_dispatches": self._burst_dispatches,
                "tokens_emitted": self._tokens_emitted,
                "peak_slots_in_use": self._peak_slots,
                "prefill_buckets": list(self.engine.prefill_buckets),
                "kv_cache_bytes": int(self.engine.cache_bytes),
                "kv_starved": self.kv_starved,
                "dispatches_per_token":
                    self._dpt_dispatches
                    / max(self._dpt_tokens, 1e-9)
                    if self._dpt_dispatches else None,
                "logprobs_topn":
                    int(getattr(self.engine, "logprobs_topn", 0) or 0),
                "stop_hits": self._stop_hits,
                "stop_trimmed_tokens": self._stop_trimmed,
            })
            if getattr(self.engine, "draft", None) is not None:
                out.update({
                    "spec_k": int(self.engine.spec_k),
                    "spec_draft_model": self.engine.draft.name,
                    "spec_dispatches": self._spec_dispatches,
                    "accepted_tokens_per_dispatch":
                        self._spec_emitted
                        / max(1, self._spec_slot_steps),
                    "spec_accept_rate":
                        self._spec_accepted
                        / max(1, self._spec_drafted),
                })
            ks = getattr(self.engine, "kv_stats", None)
            if ks is not None:
                out.update(ks())
            if self._decode_health_last is not None:
                out["decode_health"] = dict(self._decode_health_last)
                out["nonfinite_generations"] = \
                    self._nonfinite_generations
        out.pop("max_delay_ms", None)
        return out
