"""Self-healing serve fleet: process supervision + autoscaling closing
the loop on the router's own signals (``mxtpu-supervise``;
docs/robustness.md "Self-healing fleet").

PR 12/13 made the fleet *observable* — breaker-based ejection, drain
orchestration, federated ``/slo``/``/metrics`` — but nothing acted on
those signals: a crashed replica stayed dead and fleet size was whatever
the operator typed.  :class:`Supervisor` owns the replica *processes*
end-to-end, in-system and drillable, the same host-out-of-the-loop
thesis the training side applies to whole-step capture:

* **Lifecycle supervision** — spawn replica processes (port allocated
  per slot and kept across restarts so the router-side identity is
  stable), health-gate each on ``/readyz`` before registering it with
  the router, detect crash (process exit) and hang (consecutive
  ``/healthz`` timeouts), and restart with exponential backoff.  A slot
  that flaps — more than ``MXNET_SUPERVISE_MAX_RESTARTS`` restarts
  within ``MXNET_SUPERVISE_RESTART_WINDOW_SECONDS`` — is quarantined:
  removed from the router, left dead, and an incident bundle is dumped
  through the flight recorder (the supervisor registers a
  ``"supervisor"`` provider, so every dump carries the fleet's slot
  table alongside the router's view).

* **Autoscaling** — a pure decision function :func:`scale_decision`
  evaluated every ``MXNET_AUTOSCALE_INTERVAL_SECONDS`` over the
  router's federated signals (worst-model SLO burn, fleet queue depth,
  worst-replica KV utilization) with hysteresis: separate up/down
  thresholds, a cooldown between actions, and min/max clamps.
  Scale-up spawns a fresh slot (cold-start is cheap when the replicas
  share ``MXNET_COMPILE_CACHE_DIR``); scale-down always routes through
  the router's drain, so it is zero-downtime by construction.
  Rendezvous hashing (PR 12) keeps either event to a ~1/N prefix-cache
  remap.

Every transition is published on the FAULT topic (event sites
``supervisor.replica`` and ``supervisor.autoscale``) and counted in the
``mxtpu_supervise_*`` / ``mxtpu_autoscale_*`` series, which render on
the router's ``/metrics`` (control-plane families, never federated from
replicas).  CI drill: ``ci/run_tests.sh autoscale_smoke`` — a diurnal
1→4→1 load cycle with a chaos thread SIGKILLing random replicas, zero
client-visible failures asserted.
"""
from __future__ import annotations

import http.client
import os
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..base import MXNetError, getenv_float, getenv_int
from .. import telemetry as _telemetry
from .. import telemetry_ring as _ring
from . import metrics as _m
from .router import Router

__all__ = [
    "Supervisor", "AutoscalePolicy", "ScaleSignals", "ScaleAction",
    "scale_decision", "FlapBreaker",
    "default_autoscale_interval", "default_supervise_interval",
]

# event sites (docs/robustness.md): every slot transition and executed
# scale action is attributable on the FAULT topic / flight ring
REPLICA_SITE = "supervisor.replica"
AUTOSCALE_SITE = "supervisor.autoscale"

# slot states
STARTING = "STARTING"          # spawned, waiting for /readyz
RUNNING = "RUNNING"            # ready and registered with the router
BACKOFF = "BACKOFF"            # died; respawn scheduled
QUARANTINED = "QUARANTINED"    # flap breaker fired; left dead
STOPPED = "STOPPED"            # deliberately scaled down / shut down

_ACTIVE_STATES = (STARTING, RUNNING, BACKOFF)


def default_supervise_interval() -> float:
    """``MXNET_SUPERVISE_INTERVAL_SECONDS``: watch-loop cadence."""
    return getenv_float("MXNET_SUPERVISE_INTERVAL_SECONDS", 0.5)


def default_autoscale_interval() -> float:
    """``MXNET_AUTOSCALE_INTERVAL_SECONDS``: policy evaluation cadence."""
    return getenv_float("MXNET_AUTOSCALE_INTERVAL_SECONDS", 10.0)


class FlapBreaker:
    """Pure restart-rate breaker for one replica slot.

    :meth:`record` logs one restart attempt at time ``now`` and returns
    True when the slot should be QUARANTINED instead of restarted:
    i.e. when this attempt would exceed ``max_restarts`` restarts
    within the trailing ``window_seconds``.  Time is injected, never
    read, so the policy is a pure function of its inputs and the table
    tests in tests/test_supervisor.py enumerate it exactly."""

    def __init__(self, max_restarts: Optional[int] = None,
                 window_seconds: Optional[float] = None):
        self.max_restarts = getenv_int("MXNET_SUPERVISE_MAX_RESTARTS", 3) \
            if max_restarts is None else int(max_restarts)
        self.window_seconds = getenv_float(
            "MXNET_SUPERVISE_RESTART_WINDOW_SECONDS", 60.0) \
            if window_seconds is None else float(window_seconds)
        self._events: List[float] = []

    def _prune(self, now: float) -> None:
        horizon = now - self.window_seconds
        self._events = [t for t in self._events if t > horizon]

    def record(self, now: float) -> bool:
        """Count one restart attempt; True → quarantine (budget blown)."""
        self._prune(now)
        self._events.append(now)
        return len(self._events) > self.max_restarts

    def count(self, now: float) -> int:
        """Restart attempts inside the trailing window."""
        self._prune(now)
        return len(self._events)


class AutoscalePolicy:
    """Thresholds for :func:`scale_decision`.  Constructor args override
    the ``MXNET_AUTOSCALE_*`` env defaults (docs/env_var.md).

    Hysteresis is structural: the up thresholds (``burn_up``,
    ``queue_up``, ``kv_up``) and the down thresholds (``burn_down``,
    ``queue_down``) are separate, and only a fleet calm on EVERY signal
    scales down — so a load level sitting between the bands holds
    steady instead of oscillating."""

    def __init__(self, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 burn_up: Optional[float] = None,
                 burn_down: Optional[float] = None,
                 queue_up: Optional[float] = None,
                 queue_down: Optional[float] = None,
                 kv_up: Optional[float] = None,
                 cooldown_seconds: Optional[float] = None):
        self.min_replicas = getenv_int("MXNET_AUTOSCALE_MIN_REPLICAS", 1) \
            if min_replicas is None else int(min_replicas)
        self.max_replicas = getenv_int("MXNET_AUTOSCALE_MAX_REPLICAS", 4) \
            if max_replicas is None else int(max_replicas)
        self.burn_up = getenv_float("MXNET_AUTOSCALE_BURN_UP", 1.0) \
            if burn_up is None else float(burn_up)
        self.burn_down = getenv_float("MXNET_AUTOSCALE_BURN_DOWN", 0.25) \
            if burn_down is None else float(burn_down)
        self.queue_up = getenv_float("MXNET_AUTOSCALE_QUEUE_UP", 8.0) \
            if queue_up is None else float(queue_up)
        self.queue_down = getenv_float("MXNET_AUTOSCALE_QUEUE_DOWN", 1.0) \
            if queue_down is None else float(queue_down)
        self.kv_up = getenv_float("MXNET_AUTOSCALE_KV_UP", 0.85) \
            if kv_up is None else float(kv_up)
        self.cooldown_seconds = getenv_float(
            "MXNET_AUTOSCALE_COOLDOWN_SECONDS", 30.0) \
            if cooldown_seconds is None else float(cooldown_seconds)
        if self.min_replicas < 1:
            raise MXNetError("AutoscalePolicy: min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise MXNetError("AutoscalePolicy: max_replicas "
                             f"{self.max_replicas} < min_replicas "
                             f"{self.min_replicas}")

    def snapshot(self) -> dict:
        return {"min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "burn_up": self.burn_up, "burn_down": self.burn_down,
                "queue_up": self.queue_up, "queue_down": self.queue_down,
                "kv_up": self.kv_up,
                "cooldown_seconds": self.cooldown_seconds}


class ScaleSignals:
    """One policy evaluation's inputs — all injected, nothing read from
    ambient state, so :func:`scale_decision` is a pure function."""

    __slots__ = ("replicas", "burn_rate", "queue_depth",
                 "kv_utilization", "now", "last_scale_time")

    def __init__(self, replicas: int, burn_rate: float = 0.0,
                 queue_depth: float = 0.0, kv_utilization: float = 0.0,
                 now: float = 0.0, last_scale_time: float = -1e9):
        self.replicas = int(replicas)
        self.burn_rate = float(burn_rate)
        self.queue_depth = float(queue_depth)
        self.kv_utilization = float(kv_utilization)
        self.now = float(now)
        self.last_scale_time = float(last_scale_time)

    def snapshot(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


class ScaleAction:
    """The decision: ``action`` in ``("up", "down", "hold")``,
    ``target`` fleet size, and the human-readable ``reason``."""

    __slots__ = ("action", "target", "reason")

    def __init__(self, action: str, target: int, reason: str):
        self.action = action
        self.target = int(target)
        self.reason = reason

    def __repr__(self):
        return f"ScaleAction({self.action!r}, target={self.target}, " \
               f"reason={self.reason!r})"


def scale_decision(signals: ScaleSignals,
                   policy: Optional[AutoscalePolicy] = None) -> ScaleAction:
    """The autoscaling policy as a pure function of its inputs.

    Precedence (each clause documented by a table test):

    1. **Below-min repair** beats everything, cooldown included — a
       quarantine that shrank the fleet under ``min_replicas`` is a
       capacity hole, not a scaling opinion.
    2. **Cooldown**: within ``cooldown_seconds`` of the last executed
       action the verdict is ``hold`` — restarts settle before the next
       opinion.
    3. **Up-pressure** (checked in precedence order burn → queue → kv;
       the reason names the winning signal): SLO burn at/over
       ``burn_up``, per-replica queue depth at/over ``queue_up``, or KV
       utilization at/over ``kv_up``.  At ``max_replicas`` the verdict
       degrades to ``hold("at_max")``.
    4. **Scale-down** only when EVERY signal is calm (burn at/under
       ``burn_down``, per-replica queue at/under ``queue_down``, kv
       under ``kv_up``) and the fleet is above ``min_replicas``.
    5. Otherwise ``hold("steady")`` — the hysteresis dead band.

    One step at a time in either direction: the executor only ever has
    to spawn or drain a single replica per action."""
    p = policy if policy is not None else AutoscalePolicy()
    n = signals.replicas
    if n < p.min_replicas:
        return ScaleAction("up", n + 1, "below_min")
    if signals.now - signals.last_scale_time < p.cooldown_seconds:
        return ScaleAction("hold", n, "cooldown")
    per_replica_queue = signals.queue_depth / max(1, n)
    pressure = None
    if signals.burn_rate >= p.burn_up:
        pressure = "burn"
    elif per_replica_queue >= p.queue_up:
        pressure = "queue"
    elif signals.kv_utilization >= p.kv_up:
        pressure = "kv"
    if pressure is not None:
        if n >= p.max_replicas:
            return ScaleAction("hold", n, "at_max")
        return ScaleAction("up", n + 1, pressure)
    if (n > p.min_replicas
            and signals.burn_rate <= p.burn_down
            and per_replica_queue <= p.queue_down
            and signals.kv_utilization < p.kv_up):
        return ScaleAction("down", n - 1, "idle")
    return ScaleAction("hold", n, "steady")


# ---------------------------------------------------------------------------
# federated-signal extraction helpers (pure; unit-tested)
# ---------------------------------------------------------------------------
def _fleet_gauge_sum(state: dict, name: str) -> float:
    """Sum a gauge family's fleet-level series (the merged label sets —
    per-replica ``replica=``-tagged duplicates are excluded so nothing
    double-counts)."""
    fam = (state or {}).get("gauges", {}).get(name) or {}
    return sum(float(v) for labels, v in (fam.get("values") or {}).items()
               if "replica=" not in labels)


def _kv_utilization(state: dict) -> float:
    """Worst per-replica KV utilization from the federated gauge pair
    ``mxtpu_kv_blocks_in_use`` / ``mxtpu_kv_blocks_total``."""
    gauges = (state or {}).get("gauges", {})
    in_use = (gauges.get("mxtpu_kv_blocks_in_use") or {}).get("values") or {}
    totals = (gauges.get("mxtpu_kv_blocks_total") or {}).get("values") or {}
    worst = 0.0
    for labels, total in totals.items():
        if "replica=" not in labels:
            continue
        try:
            total = float(total)
        except (TypeError, ValueError):
            continue
        if total <= 0:
            continue
        worst = max(worst, float(in_use.get(labels, 0.0)) / total)
    return worst


def _fleet_burn(slo_body: dict) -> float:
    """Worst-model burn rate from the router's merged ``/slo`` body."""
    models = (slo_body or {}).get("models") or {}
    burns = [float(m.get("burn_rate") or 0.0)
             for m in models.values() if isinstance(m, dict)]
    return max(burns) if burns else 0.0


class _Slot:
    """One supervised replica slot.  The port — and therefore the
    router-side replica id — is allocated once and survives restarts,
    so a bounce shows up as DOWN→READY on the same member instead of a
    membership change."""

    def __init__(self, index: int, host: str, port: int,
                 breaker: FlapBreaker):
        self.index = index
        self.host = host
        self.port = port
        self.id = f"{host}:{port}"
        self.breaker = breaker
        self.proc: Optional[subprocess.Popen] = None
        self.log = None                 # open log file handle
        self.log_path: Optional[str] = None
        self.state = STOPPED
        self.spawns = 0
        self.restarts = 0
        self.backoff_until = 0.0
        self.start_deadline = 0.0
        self.healthz_failures = 0
        self.last_exit: Optional[int] = None
        self.last_event = ""

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def snapshot(self) -> dict:
        return {"index": self.index, "id": self.id, "state": self.state,
                "pid": self.pid, "spawns": self.spawns,
                "restarts": self.restarts,
                "last_exit": self.last_exit,
                "last_event": self.last_event,
                "log": self.log_path}


class Supervisor:
    """Fleet controller: owns replica processes AND the router fronting
    them.  Programmatic use (the ``mxtpu-supervise`` CLI wraps this)::

        sup = Supervisor([sys.executable, "-c", ..., "--port", "{port}"],
                         replicas=2, policy=AutoscalePolicy(max_replicas=4))
        sup.start()            # spawns, health-gates, starts the router
        ... traffic against sup.router.port ...
        sup.stop()

    ``command`` is the replica argv; every element has ``{port}``
    substituted with the slot's allocated port.  ``child_env`` overlays
    the inherited environment (set ``MXNET_COMPILE_CACHE_DIR`` here so
    replicas share compiled artifacts and cold-start stays cheap).
    ``autoscale=False`` supervises a fixed-size fleet.  Pass
    ``router=`` to adopt an externally-owned router (it will NOT be
    stopped on :meth:`stop`)."""

    def __init__(self, command: Sequence[str], *,
                 replicas: int = 1,
                 policy: Optional[AutoscalePolicy] = None,
                 autoscale: bool = True,
                 router: Optional[Router] = None,
                 router_port: int = 0,
                 host: str = "127.0.0.1",
                 child_env: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None,
                 interval_seconds: Optional[float] = None,
                 autoscale_interval_seconds: Optional[float] = None,
                 ready_timeout: Optional[float] = None,
                 health_timeout: Optional[float] = None,
                 hang_failures: Optional[int] = None,
                 backoff_base: Optional[float] = None,
                 backoff_max: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 restart_window_seconds: Optional[float] = None,
                 port_allocator: Optional[Callable[[], int]] = None):
        command = [str(c) for c in command]
        if not any("{port}" in c for c in command):
            raise MXNetError(
                "Supervisor command must carry a '{port}' placeholder "
                "(the supervisor allocates each slot's port)")
        self.command = command
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.autoscale = bool(autoscale)
        self.host = host
        self.child_env = dict(child_env or {})
        self.log_dir = log_dir
        self.interval = default_supervise_interval() \
            if interval_seconds is None else float(interval_seconds)
        self.autoscale_interval = default_autoscale_interval() \
            if autoscale_interval_seconds is None \
            else float(autoscale_interval_seconds)
        self.ready_timeout = getenv_float(
            "MXNET_SUPERVISE_READY_TIMEOUT_SECONDS", 120.0) \
            if ready_timeout is None else float(ready_timeout)
        self.health_timeout = getenv_float(
            "MXNET_SUPERVISE_HEALTH_TIMEOUT_SECONDS", 5.0) \
            if health_timeout is None else float(health_timeout)
        self.hang_failures = getenv_int(
            "MXNET_SUPERVISE_HANG_FAILURES", 3) \
            if hang_failures is None else int(hang_failures)
        self.backoff_base = getenv_float(
            "MXNET_SUPERVISE_BACKOFF_SECONDS", 0.5) \
            if backoff_base is None else float(backoff_base)
        self.backoff_max = getenv_float(
            "MXNET_SUPERVISE_BACKOFF_MAX_SECONDS", 10.0) \
            if backoff_max is None else float(backoff_max)
        self._max_restarts = max_restarts
        self._restart_window = restart_window_seconds
        self._initial = max(int(replicas), self.policy.min_replicas)
        if self._initial > self.policy.max_replicas:
            raise MXNetError(
                f"Supervisor: replicas {self._initial} > policy "
                f"max_replicas {self.policy.max_replicas}")
        self._router = router
        self._owns_router = router is None
        self._router_port = int(router_port)
        self._alloc = port_allocator if port_allocator is not None \
            else self._free_port
        self._slots: List[_Slot] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._scale_thread: Optional[threading.Thread] = None
        self._recorder: Optional[_ring.FlightRecorder] = None
        self._last_scale = -1e9
        self._last_decision: Optional[dict] = None
        self._next_index = 0

    # -- plumbing -------------------------------------------------------
    @property
    def router(self) -> Optional[Router]:
        return self._router

    def _free_port(self) -> int:
        import socket
        s = socket.socket()
        s.bind((self.host, 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def slots(self) -> List[_Slot]:
        with self._lock:
            return list(self._slots)

    def alive_count(self) -> int:
        return sum(1 for s in self.slots() if s.state == RUNNING)

    def active_count(self) -> int:
        """Fleet size the policy reasons about: slots that are serving,
        starting, or between restarts — everything not deliberately
        stopped or quarantined."""
        return sum(1 for s in self.slots() if s.state in _ACTIVE_STATES)

    def state(self) -> dict:
        """The flight-recorder provider payload: the whole slot table
        plus the last autoscale evaluation."""
        return {"slots": [s.snapshot() for s in self.slots()],
                "active": self.active_count(),
                "alive": self.alive_count(),
                "policy": self.policy.snapshot(),
                "autoscale": self.autoscale,
                "last_decision": self._last_decision}

    # -- probes ---------------------------------------------------------
    def _http_get(self, slot: _Slot, path: str,
                  timeout: float) -> Optional[int]:
        conn = http.client.HTTPConnection(slot.host, slot.port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            resp.read()
            return resp.status
        finally:
            conn.close()

    def _ready(self, slot: _Slot, timeout: float) -> bool:
        try:
            return self._http_get(slot, "/readyz", timeout) == 200
        except OSError:
            return False

    def _healthy(self, slot: _Slot) -> bool:
        try:
            return self._http_get(slot, "/healthz",
                                  self.health_timeout) is not None
        except OSError:
            return False

    # -- spawning -------------------------------------------------------
    def _spawn(self, slot: _Slot) -> None:
        argv = [c.replace("{port}", str(slot.port)) for c in self.command]
        env = dict(os.environ)
        env.update(self.child_env)
        if slot.log is None and self.log_dir is not None:
            os.makedirs(self.log_dir, exist_ok=True)
            slot.log_path = os.path.join(
                self.log_dir, f"replica-{slot.port}.log")
            slot.log = open(slot.log_path, "ab")
        out = slot.log if slot.log is not None else subprocess.DEVNULL
        # own session: a Ctrl-C aimed at the supervisor must reach the
        # replicas as an orderly drain (our stop()), not a shared SIGINT
        slot.proc = subprocess.Popen(argv, stdout=out, stderr=out,
                                     env=env, start_new_session=True)
        restart = slot.spawns > 0
        slot.spawns += 1
        if restart:
            slot.restarts += 1
            _m.SUPERVISE_RESTARTS.inc(replica=slot.id)
        _m.SUPERVISE_SPAWNS.inc()
        slot.state = STARTING
        slot.healthz_failures = 0
        slot.start_deadline = time.monotonic() + self.ready_timeout
        slot.last_event = "restart" if restart else "spawn"
        _telemetry.FAULT.publish(site=REPLICA_SITE, event="spawn",
                                 kind="restart" if restart else "initial",
                                 replica=slot.id, pid=slot.proc.pid)

    def _new_slot(self) -> _Slot:
        with self._lock:
            breaker = FlapBreaker(self._max_restarts,
                                  self._restart_window)
            slot = _Slot(self._next_index, self.host, int(self._alloc()),
                         breaker)
            self._next_index += 1
            self._slots.append(slot)
        return slot

    def _kill(self, slot: _Slot, grace: float = 3.0) -> None:
        proc = slot.proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                pass

    # -- slot transitions ----------------------------------------------
    def _on_ready(self, slot: _Slot) -> None:
        slot.state = RUNNING
        slot.healthz_failures = 0
        slot.last_event = "ready"
        _telemetry.FAULT.publish(site=REPLICA_SITE, event="ready",
                                 kind="gate", replica=slot.id)
        # health-gated registration: the router only ever learns about
        # a replica that has already answered /readyz.  Idempotent, so
        # a restarted slot (same port → same id) is a no-op re-add.
        if self._router is None:
            self._router = Router([slot.id], port=self._router_port,
                                  host="0.0.0.0")
            self._router.start()
        else:
            self._router.add_replica(slot.id)
        _m.SUPERVISE_REPLICAS.set(self.alive_count())

    def _on_death(self, slot: _Slot, kind: str) -> None:
        if slot.state == STOPPED or self._stop.is_set():
            return                      # deliberate kill, not a crash
        slot.last_exit = slot.proc.returncode if slot.proc is not None \
            else None
        slot.last_event = kind
        now = time.monotonic()
        _telemetry.FAULT.publish(site=REPLICA_SITE, event="died",
                                 kind=kind, replica=slot.id,
                                 exit_code=slot.last_exit)
        _m.SUPERVISE_REPLICAS.set(self.alive_count())
        if slot.breaker.record(now):
            self._quarantine(slot)
            return
        attempt = slot.breaker.count(now)
        delay = min(self.backoff_max,
                    self.backoff_base * (2.0 ** max(0, attempt - 1)))
        slot.state = BACKOFF
        slot.backoff_until = now + delay
        _telemetry.FAULT.publish(site=REPLICA_SITE, event="backoff",
                                 kind=kind, replica=slot.id,
                                 seconds=round(delay, 3), attempt=attempt)

    def _quarantine(self, slot: _Slot) -> None:
        slot.state = QUARANTINED
        slot.last_event = "quarantine"
        _m.SUPERVISE_QUARANTINES.inc(replica=slot.id)
        _telemetry.FAULT.publish(site=REPLICA_SITE, event="quarantined",
                                 kind="flap", replica=slot.id,
                                 restarts=slot.restarts)
        if self._router is not None:
            try:
                # the corpse has nothing left to drain
                self._router.remove_replica(slot.id, drain=False)
            except KeyError:
                pass
        rec = self._recorder
        if rec is not None:
            try:
                rec.dump("replica_quarantined")
            except OSError:
                pass

    # -- watch loop -----------------------------------------------------
    def poll_once(self) -> None:
        """One synchronous supervision sweep (tests drive this directly;
        the background loop calls it on ``interval_seconds``)."""
        now = time.monotonic()
        for slot in self.slots():
            if slot.state == STARTING:
                if not slot.alive():
                    self._on_death(slot, "exit")
                elif self._ready(slot, min(1.0, self.health_timeout)):
                    self._on_ready(slot)
                elif now > slot.start_deadline:
                    self._kill(slot)
                    self._on_death(slot, "start_timeout")
            elif slot.state == RUNNING:
                if not slot.alive():
                    self._on_death(slot, "exit")
                elif self._healthy(slot):
                    slot.healthz_failures = 0
                else:
                    slot.healthz_failures += 1
                    if slot.healthz_failures >= self.hang_failures:
                        self._kill(slot)
                        self._on_death(slot, "hang")
            elif slot.state == BACKOFF:
                if now >= slot.backoff_until:
                    self._spawn(slot)

    def _watch_run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:          # the watch loop must survive
                pass                   # anything one replica throws

    # -- autoscaling ----------------------------------------------------
    def collect_signals(self) -> ScaleSignals:
        """Pull one :class:`ScaleSignals` sample off the router's
        federated views (merged ``/slo`` burn, fleet queue depth,
        worst-replica KV utilization)."""
        burn = queue = kv = 0.0
        if self._router is not None:
            try:
                burn = _fleet_burn(self._router.fleet_slo())
            except Exception:
                pass
            try:
                state = self._router.fleet_metrics_state()
                queue = _fleet_gauge_sum(state, "mxtpu_serve_queue_depth")
                kv = _kv_utilization(state)
            except Exception:
                pass
        return ScaleSignals(replicas=self.active_count(),
                            burn_rate=burn, queue_depth=queue,
                            kv_utilization=kv, now=time.monotonic(),
                            last_scale_time=self._last_scale)

    def autoscale_once(self) -> ScaleAction:
        """One policy evaluation + execution (tests and the loop share
        this path)."""
        signals = self.collect_signals()
        _m.AUTOSCALE_BURN.set(signals.burn_rate)
        _m.AUTOSCALE_QUEUE.set(signals.queue_depth)
        _m.AUTOSCALE_KV.set(signals.kv_utilization)
        act = scale_decision(signals, self.policy)
        _m.AUTOSCALE_DECISIONS.inc(action=act.action)
        _m.AUTOSCALE_TARGET.set(act.target)
        self._last_decision = {"action": act.action,
                               "target": act.target,
                               "reason": act.reason,
                               "signals": signals.snapshot()}
        if act.action == "up":
            self._scale_up(act)
        elif act.action == "down":
            self._scale_down(act)
        return act

    def _scale_up(self, act: ScaleAction) -> None:
        slot = self._new_slot()
        self._spawn(slot)
        self._last_scale = time.monotonic()
        _m.AUTOSCALE_EVENTS.inc(action="up")
        _telemetry.FAULT.publish(site=AUTOSCALE_SITE, event="scale",
                                 kind="up", reason=act.reason,
                                 target=act.target, replica=slot.id)

    def _scale_down(self, act: ScaleAction) -> None:
        victims = [s for s in self.slots() if s.state == RUNNING]
        if len(victims) <= self.policy.min_replicas:
            return                      # nothing safely removable
        slot = victims[-1]              # newest first: LIFO shrink
        slot.state = STOPPED            # watch loop hands it off NOW
        self._last_scale = time.monotonic()
        if self._router is not None:
            try:
                # zero-downtime by construction: drain routes the
                # member's traffic away before the process dies
                self._router.remove_replica(slot.id, drain=True)
            except KeyError:
                pass
        self._kill(slot)
        slot.last_event = "scale_down"
        _m.SUPERVISE_REPLICAS.set(self.alive_count())
        _m.AUTOSCALE_EVENTS.inc(action="down")
        _telemetry.FAULT.publish(site=AUTOSCALE_SITE, event="scale",
                                 kind="down", reason=act.reason,
                                 target=act.target, replica=slot.id)

    def _scale_run(self) -> None:
        while not self._stop.wait(self.autoscale_interval):
            try:
                self.autoscale_once()
            except Exception:          # policy loop must survive too
                pass

    # -- lifecycle ------------------------------------------------------
    def start(self, ready_deadline: Optional[float] = None) -> "Supervisor":
        """Spawn the initial fleet, health-gate it, bring up the router,
        then hand off to the background watch + autoscale loops.
        Blocks until at least one replica is RUNNING (the fleet can
        serve) or ``ready_deadline`` (default ``ready_timeout``)
        expires — then tears down and raises."""
        if self._watch_thread is not None:
            return self
        self._stop.clear()
        self._recorder = _ring.recorder
        self._recorder.start()
        self._recorder.register_provider("supervisor", self.state)
        for _ in range(self._initial):
            self._spawn(self._new_slot())
        deadline = time.monotonic() + (self.ready_timeout
                                       if ready_deadline is None
                                       else float(ready_deadline))
        while self.alive_count() == 0:
            if time.monotonic() > deadline or all(
                    s.state == QUARANTINED for s in self.slots()):
                self.stop()
                raise MXNetError(
                    "Supervisor: no replica became ready within "
                    f"{self.ready_timeout}s — see replica logs"
                    + (f" under {self.log_dir}" if self.log_dir else ""))
            time.sleep(min(0.05, self.interval))
            self.poll_once()
        self._watch_thread = threading.Thread(
            target=self._watch_run, name="mxtpu-supervise-watch",
            daemon=True)
        self._watch_thread.start()
        if self.autoscale:
            self._scale_thread = threading.Thread(
                target=self._scale_run, name="mxtpu-supervise-scale",
                daemon=True)
            self._scale_thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the loops, drain + stop the owned router, terminate
        every replica process."""
        self._stop.set()
        for th in (self._watch_thread, self._scale_thread):
            if th is not None:
                th.join(timeout=timeout)
        self._watch_thread = self._scale_thread = None
        router, owned = self._router, self._owns_router
        if router is not None and owned:
            self._router = None
            router.stop()
        for slot in self.slots():
            if slot.state in _ACTIVE_STATES:
                slot.state = STOPPED
            self._kill(slot)
            if slot.log is not None:
                try:
                    slot.log.close()
                except OSError:
                    pass
                slot.log = None
        _m.SUPERVISE_REPLICAS.set(0)
        rec, self._recorder = self._recorder, None
        if rec is not None:
            rec.unregister_provider("supervisor")
            rec.stop()

    def shutdown(self, drain_seconds: Optional[float] = None) -> None:
        """The SIGTERM sequence (``lifecycle.run_until_shutdown``): let
        the router drain client traffic, then stop everything."""
        router = self._router
        if router is not None and self._owns_router:
            self._router = None
            router.shutdown(drain_seconds=drain_seconds)
        self.stop()

    def __enter__(self) -> "Supervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
