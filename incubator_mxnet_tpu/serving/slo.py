"""Per-model SLO accounting — rolling-window SLIs, error budget, and
burn rate for the serving plane (docs/observability.md).

Counters say how many requests failed; an operator paging decision
needs *rates against an objective*.  This module keeps, per model, a
bounded rolling window of request outcomes (ok/failed + end-to-end
latency, recorded by ``DynamicBatcher.submit``) and derives the two
SLIs the serving plane promises:

* **availability** — fraction of requests in the window that returned
  a result (anything raised — 429 backpressure, 503 breaker/abort,
  504 deadline, 500 dispatch errors — counts against it; 4xx client
  errors never reach the batcher, so they never burn budget).
* **latency** — the window's p99 versus the objective
  ``MXNET_SERVE_SLO_P99_MS``.
* **token latency** (generation models) — p99 of per-token emission
  gaps versus ``MXNET_SERVE_SLO_TOKEN_P99_MS``.  End-to-end latency is
  the wrong SLI for a streamed response: a 200-token request that
  stalls 5 s mid-stream can still post a fine total.  The continuous
  batcher records every inter-token gap here, so decode-loop stalls
  (slot contention, a wedged dispatch riding retry) burn budget even
  when requests eventually finish.

Each SLI yields a **burn rate** — how fast the error budget is being
spent, where 1.0 means "exactly consuming the budget the objective
allows" (the Google SRE workbook convention):

* availability burn = (bad/total) / (1 − availability_objective)
* latency burn = fraction of requests slower than the p99 objective
  / 0.01 (an SLO of "p99 under X" budgets 1% of requests over X)

``burn_rate`` is the worst of the applicable burns;
``error_budget_remaining = clamp(1 − burn_rate, 0, 1)``; the budget is
*exhausted* once burn ≥ 1 with at least ``MXNET_SERVE_SLO_MIN_REQUESTS``
requests observed (a floor so one failed canary request cannot flip
``/readyz``).  Exhaustion shows up as a ``slo:<model>`` blocker in
``ModelServer.readiness()`` → ``/readyz`` 503, taking the replica out
of the balancer rotation until the window recovers.

Exported: ``mxtpu_slo_availability``, ``mxtpu_slo_p99_seconds``,
``mxtpu_slo_burn_rate``, ``mxtpu_slo_error_budget_remaining`` gauges
(per model) plus the ``mxtpu_slo_bad_requests`` counter; the full
JSON view is ``GET /slo`` and ``mxtpu-stats --slo``.

Knobs (docs/env_var.md): ``MXNET_SERVE_SLO_AVAILABILITY`` (objective,
default 0.999), ``MXNET_SERVE_SLO_P99_MS`` (latency objective in ms,
default 0 → latency SLO off), ``MXNET_SERVE_SLO_TOKEN_P99_MS``
(per-token gap objective in ms, default 0 → token SLO off),
``MXNET_SERVE_SLO_WINDOW`` (window size in requests, default 512),
``MXNET_SERVE_SLO_MIN_REQUESTS`` (readiness floor, default 10).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

from ..base import getenv, getenv_int
from . import metrics as _m

__all__ = ["ModelSLO", "SLOTracker", "tracker", "merge_snapshots",
           "objective_availability", "objective_p99_ms",
           "objective_token_p99_ms", "default_window", "min_requests"]


def objective_availability() -> float:
    """``MXNET_SERVE_SLO_AVAILABILITY``: availability objective in
    [0, 1) — e.g. 0.999 budgets 0.1% failed requests."""
    return float(getenv("MXNET_SERVE_SLO_AVAILABILITY", 0.999))


def objective_p99_ms() -> float:
    """``MXNET_SERVE_SLO_P99_MS``: p99 latency objective in
    milliseconds; 0 disables the latency SLI."""
    return float(getenv("MXNET_SERVE_SLO_P99_MS", 0.0))


def objective_token_p99_ms() -> float:
    """``MXNET_SERVE_SLO_TOKEN_P99_MS``: p99 inter-token gap objective
    in milliseconds for generation models; 0 disables the token SLI."""
    return float(getenv("MXNET_SERVE_SLO_TOKEN_P99_MS", 0.0))


def default_window() -> int:
    """``MXNET_SERVE_SLO_WINDOW``: rolling window size in requests."""
    return getenv_int("MXNET_SERVE_SLO_WINDOW", 512)


def min_requests() -> int:
    """``MXNET_SERVE_SLO_MIN_REQUESTS``: observations required before
    an exhausted budget may block readiness."""
    return getenv_int("MXNET_SERVE_SLO_MIN_REQUESTS", 10)


class ModelSLO:
    """Rolling window of (ok, latency) outcomes for one model."""

    def __init__(self, model: str, window: Optional[int] = None):
        self.model = str(model)
        size = max(1, int(window or default_window()))
        self._window = deque(maxlen=size)
        # inter-token emission gaps (generation models); one request
        # contributes many samples, so give gaps their own window
        # rather than crowding request outcomes out of the budget math
        self._token_window = deque(maxlen=size)
        self._lock = threading.Lock()

    def record_token(self, gap_seconds: float) -> None:
        """Fold one inter-token emission gap into the token window
        (recorded by ``ContinuousBatcher`` per emitted token).  Gauges
        refresh on the next :meth:`record` — per-token gauge updates
        would cost a sort per decode step per slot.

        Under speculative decoding tokens arrive in bursts of 1..k+1
        per verify dispatch: the first token of a burst carries the
        whole step's latency and the rest land with near-zero gaps.
        That is exactly what a streaming client observes, so the
        token-latency SLI keeps the raw gaps — a p99 over them rewards
        high accept rates instead of hiding them."""
        with self._lock:
            self._token_window.append(float(gap_seconds))

    def record(self, latency_seconds: float, ok: bool) -> None:
        """Fold one request outcome into the window and refresh the
        ``mxtpu_slo_*`` gauges (a sort of ≤ window samples — cheap next
        to a batched dispatch)."""
        with self._lock:
            self._window.append((bool(ok), float(latency_seconds)))
        if not ok:
            _m.SLO_BAD.inc(model=self.model)
        snap = self.snapshot()
        _m.SLO_AVAILABILITY.set(snap["availability"], model=self.model)
        if snap["p99_seconds"] is not None:
            _m.SLO_P99.set(snap["p99_seconds"], model=self.model)
        _m.SLO_BURN.set(snap["burn_rate"], model=self.model)
        _m.SLO_BUDGET.set(snap["error_budget_remaining"], model=self.model)

    def snapshot(self) -> dict:
        """JSON-ready SLI/burn/budget view of the current window."""
        with self._lock:
            window = list(self._window)
            token_window = list(self._token_window)
        total = len(window)
        bad = sum(1 for ok, _ in window if not ok)
        avail_obj = min(1.0, max(0.0, objective_availability()))
        p99_obj_s = max(0.0, objective_p99_ms()) / 1000.0
        tok_obj_s = max(0.0, objective_token_p99_ms()) / 1000.0
        out = {
            "model": self.model,
            "window": total,
            "bad": bad,
            "availability": 1.0 if total == 0 else (total - bad) / total,
            "availability_objective": avail_obj,
            "p99_seconds": None,
            "p99_objective_seconds": p99_obj_s or None,
            # absolute over-objective counts ride along so a federator
            # can recompute fleet burn from summed windows instead of
            # averaging per-replica rates (which over-weights idle
            # replicas)
            "slow": None,
            "token_window": len(token_window),
            "token_p99_seconds": None,
            "token_p99_objective_seconds": tok_obj_s or None,
            "token_slow": None,
            "burn_rate": 0.0,
            "error_budget_remaining": 1.0,
            "exhausted": False,
        }

        def _p99(samples):
            # same nearest-rank convention as telemetry.Histogram.stats()
            n = len(samples)
            return samples[min(n - 1, max(0, int(round(0.99 * (n - 1)))))]

        burns = []
        if token_window:
            gaps = sorted(token_window)
            out["token_p99_seconds"] = _p99(gaps)
            if tok_obj_s > 0.0:
                slow = sum(1 for g in token_window if g > tok_obj_s)
                out["token_slow"] = slow
                burns.append((slow / len(token_window)) / 0.01)
        if total == 0:
            # token-gap burn alone can spend budget, but readiness only
            # flips once enough whole requests have been observed
            out["burn_rate"] = max(burns) if burns else 0.0
            out["error_budget_remaining"] = \
                min(1.0, max(0.0, 1.0 - out["burn_rate"]))
            return out
        out["p99_seconds"] = _p99(sorted(lat for _, lat in window))
        if avail_obj < 1.0:
            burns.append((bad / total) / (1.0 - avail_obj))
        if p99_obj_s > 0.0:
            slow = sum(1 for _, lat in window if lat > p99_obj_s)
            out["slow"] = slow
            burns.append((slow / total) / 0.01)
        burn = max(burns) if burns else 0.0
        out["burn_rate"] = burn
        out["error_budget_remaining"] = min(1.0, max(0.0, 1.0 - burn))
        out["exhausted"] = burn >= 1.0 and total >= min_requests()
        return out


class SLOTracker:
    """Registry of :class:`ModelSLO` windows (one process-wide
    instance: :data:`tracker`)."""

    def __init__(self):
        self._models: Dict[str, ModelSLO] = {}
        self._lock = threading.Lock()

    def model(self, name: str) -> ModelSLO:
        name = str(name)
        m = self._models.get(name)
        if m is None:
            with self._lock:
                m = self._models.setdefault(name, ModelSLO(name))
        return m

    def record(self, name: str, latency_seconds: float, ok: bool) -> None:
        self.model(name).record(latency_seconds, ok)

    def record_token(self, name: str, gap_seconds: float) -> None:
        self.model(name).record_token(gap_seconds)

    def snapshot(self) -> dict:
        """``GET /slo`` body: every model's SLI/burn/budget view plus
        the shared objectives."""
        with self._lock:
            models = dict(self._models)
        return {
            "objectives": {
                "availability": objective_availability(),
                "p99_ms": objective_p99_ms() or None,
                "token_p99_ms": objective_token_p99_ms() or None,
                "window": default_window(),
                "min_requests": min_requests(),
            },
            "models": {name: m.snapshot() for name, m in models.items()},
        }

    def exhausted(self) -> Dict[str, dict]:
        """Models whose error budget is exhausted (→ readiness
        blockers)."""
        with self._lock:
            models = dict(self._models)
        out = {}
        for name, m in models.items():
            snap = m.snapshot()
            if snap["exhausted"]:
                out[name] = snap
        return out

    def reset(self) -> None:
        """Drop every window (test hygiene)."""
        with self._lock:
            self._models.clear()


tracker = SLOTracker()


def merge_snapshots(snapshots: Dict[str, Optional[dict]]) -> dict:
    """Fold per-replica ``tracker.snapshot()`` bodies (keyed by replica
    id) into the FLEET ``/slo`` view — the burn a user sees through the
    router.  Windows merge by summing absolute counts (window/bad/slow),
    so fleet burn is ``(Σbad/Σtotal)/(1−objective)`` rather than an
    average of per-replica burns: one replica failing 100% of its 10
    requests in a 1000-request fleet burns the fleet at 1%, not 50%.
    Fleet p99 is reported as the worst replica's p99 (windows don't
    carry raw latencies; the merged-histogram quantile lives on the
    federated ``/metrics``)."""
    per_model: Dict[str, Dict[str, dict]] = {}
    objectives: dict = {}
    for rid, snap in snapshots.items():
        if not snap:
            continue
        objectives = snap.get("objectives") or objectives
        for name, ms in (snap.get("models") or {}).items():
            per_model.setdefault(name, {})[rid] = ms

    def _sum(parts, key):
        vals = [p.get(key) for p in parts if p.get(key) is not None]
        return sum(vals) if vals else None

    models = {}
    for name, by_rep in per_model.items():
        parts = list(by_rep.values())
        total = int(_sum(parts, "window") or 0)
        bad = int(_sum(parts, "bad") or 0)
        slow = _sum(parts, "slow")
        tok_total = int(_sum(parts, "token_window") or 0)
        tok_slow = _sum(parts, "token_slow")
        avail_obj = next((p["availability_objective"] for p in parts
                          if p.get("availability_objective") is not None),
                         min(1.0, max(0.0, objective_availability())))
        burns = []
        if total and avail_obj < 1.0:
            burns.append((bad / total) / (1.0 - avail_obj))
        if total and slow is not None:
            burns.append((slow / total) / 0.01)
        if tok_total and tok_slow is not None:
            burns.append((tok_slow / tok_total) / 0.01)
        burn = max(burns) if burns else 0.0
        p99s = [p.get("p99_seconds") for p in parts
                if p.get("p99_seconds") is not None]
        tok_p99s = [p.get("token_p99_seconds") for p in parts
                    if p.get("token_p99_seconds") is not None]
        models[name] = {
            "model": name,
            "window": total,
            "bad": bad,
            "slow": slow,
            "availability": 1.0 if total == 0 else (total - bad) / total,
            "availability_objective": avail_obj,
            "p99_seconds_worst_replica": max(p99s) if p99s else None,
            "token_window": tok_total,
            "token_slow": tok_slow,
            "token_p99_seconds_worst_replica":
                max(tok_p99s) if tok_p99s else None,
            "burn_rate": burn,
            "error_budget_remaining": min(1.0, max(0.0, 1.0 - burn)),
            "exhausted": burn >= 1.0 and total >= min_requests(),
            "per_replica": {rid: {"window": p.get("window"),
                                  "bad": p.get("bad"),
                                  "burn_rate": p.get("burn_rate")}
                            for rid, p in sorted(by_rep.items())},
        }
    return {"fleet": True,
            "replicas": sorted(r for r, s in snapshots.items() if s),
            "objectives": objectives,
            "models": models}
