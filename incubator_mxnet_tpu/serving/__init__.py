"""Inference serving subsystem — dynamic-batching model server over
shape-bucketed compiled engines (see docs/serving.md).

Four layers, importable à la carte:

* :class:`InferenceEngine` (``engine.py``) — a model (Gluon block,
  Module, or exported symbol+params) as donated jitted forward
  programs keyed by batch-size bucket; requests pad up to the next
  bucket so the compile cache stays bounded.
* :class:`DynamicBatcher` (``batcher.py``) — bounded queue coalescing
  concurrent requests into ONE dispatch per batch, with backpressure,
  per-request deadlines, retry + single-request fallback, a per-model
  circuit breaker, and graceful drain.
* :mod:`lifecycle` — the fault-domain plane shared by batcher and
  server: serving states (SERVING/DEGRADED/…), :class:`CircuitBreaker`,
  the worker :class:`Watchdog`, deadline helpers, and the SIGTERM-safe
  shutdown machinery (``install_signal_handler`` /
  ``run_until_shutdown``); docs/robustness.md.
* :class:`ModelServer` (``server.py``) — stdlib HTTP front-end
  (``/v1/models/<name>:predict``, multi-model registry, ``/healthz``,
  ``/readyz``, ``/metrics``) sharing plumbing with the telemetry
  exporter.  CLI: ``mxtpu-serve``.

Above the single process sits :class:`Router` (``router.py``) — the
``mxtpu-router`` front tier spreading ``:predict``/``:generate`` over
N replicas with health-aware least-loaded balancing, breaker-based
outlier ejection, retry-with-failover, SSE passthrough, zero-downtime
drain orchestration, and rendezvous-hash prefix-affine routing for
the paged KV prefix cache (docs/serving.md "Serving a fleet").
Membership is dynamic (``POST``/``DELETE /admin/replicas``), and
:class:`Supervisor` (``supervisor.py``, CLI ``mxtpu-supervise``)
closes the loop: it owns the replica processes — spawn, ``/readyz``
health-gating, crash/hang detection, restart-with-backoff, flap
quarantine — and autoscales the fleet off the router's own federated
signals through the pure :func:`scale_decision` policy
(docs/robustness.md "Self-healing fleet").

Generation serving rides the same layers: :class:`GenerationEngine`
(paged KV cache over a :class:`~.kvcache.BlockPool` — fixed-size
blocks, per-slot block tables, refcounted prefix sharing; dense mode
via ``MXNET_KV_PAGED=0`` — with a prefill/decode split) behind a
:class:`ContinuousBatcher` (per-slot join/leave, one decode dispatch
per step over all live requests, pool-capacity admission) behind
``POST /v1/models/<name>:generate`` with SSE streaming.  The sampling
plane (``sampling.py``) threads per-slot :class:`SamplingParams`
through those same compiled programs as traced operands — stochastic
decoding, seeded replay, speculative sampling, per-token logprobs,
multi-token stop sequences, and JSON-mode constrained output
(docs/serving.md "Sampling").

Importing this package registers the ``mxtpu_serve_*`` metrics on the
shared telemetry registry, so they appear on every exporter
automatically.
"""
from . import metrics
from . import lifecycle
from .lifecycle import (
    CircuitBreaker, Watchdog, DeadlineExceeded, BreakerOpen, Draining,
    RequestAborted, Cancelled, SERVING, STARTING, DEGRADED, UNHEALTHY,
    DRAINING,
)
from .engine import InferenceEngine, GenerationEngine, derive_buckets, \
    derive_prefill_buckets
from .kvcache import BlockPool, blocks_for
from .sampling import SamplingParams, JsonMaskMachine
from .batcher import ContinuousBatcher, DynamicBatcher, QueueFullError
from .server import ModelServer
from .router import Router, Replica, UpstreamError, NoReplicaAvailable
from .supervisor import (Supervisor, AutoscalePolicy, ScaleSignals,
                         ScaleAction, scale_decision, FlapBreaker)

__all__ = ["InferenceEngine", "GenerationEngine", "derive_buckets",
           "derive_prefill_buckets", "BlockPool", "blocks_for",
           "SamplingParams", "JsonMaskMachine",
           "DynamicBatcher",
           "ContinuousBatcher", "QueueFullError", "ModelServer",
           "Router", "Replica", "UpstreamError", "NoReplicaAvailable",
           "Supervisor", "AutoscalePolicy", "ScaleSignals",
           "ScaleAction", "scale_decision", "FlapBreaker",
           "metrics", "lifecycle",
           "CircuitBreaker", "Watchdog", "DeadlineExceeded",
           "BreakerOpen", "Draining", "RequestAborted", "Cancelled",
           "SERVING", "STARTING", "DEGRADED", "UNHEALTHY", "DRAINING"]
