"""Serving fault domains — the resilience layer of the serving
subsystem (docs/robustness.md "Serving fault domains").

PR 3 built the training-side resilience plane (deterministic fault
injection, retry/backoff, checkpoint/resume); this module extends it to
the serving fault domain, where the failure modes are different: a
request is latency-bounded, a replica is one of many behind a load
balancer, and the correct reaction to trouble is almost always *shed,
isolate, restart a thread, tell the balancer* — never "crash the
process".  Five cooperating pieces:

* **deadlines** — requests carry an end-to-end budget (``timeout_ms``
  per request, env default ``MXNET_SERVE_TIMEOUT_MS``).  The batcher
  sheds work that cannot meet it (at admission by queue-wait estimate,
  at gather time for already-expired requests, and at the dispatch wait)
  with :class:`DeadlineExceeded` → HTTP 504, so a handler thread can
  never block unboundedly on a wedged dispatch.
* **circuit breaker** — :class:`CircuitBreaker` per model.  Consecutive
  dispatch-after-retry failures trip CLOSED→OPEN; while OPEN, admission
  fast-fails with :class:`BreakerOpen` → HTTP 503 + ``Retry-After``
  instead of queueing onto a broken model.  After a cooldown one probe
  request is let through (HALF_OPEN); success re-closes the breaker.
  Transitions ride the FAULT telemetry topic and the
  ``mxtpu_serve_breaker_state`` gauge.
* **watchdog** — :class:`Watchdog` polls every batcher's worker: a dead
  thread or one stuck in a dispatch past ``MXNET_SERVE_HANG_SECONDS``
  gets its riders failed (:class:`RequestAborted` → HTTP 503), the
  worker restarted, the model marked DEGRADED and the breaker tripped.
  Drill it deterministically with the ``hang`` fault kind
  (``MXNET_FAULT_PLAN=serving.infer:hang``).
* **liveness/readiness split** — per-model states (:data:`SERVING`,
  :data:`STARTING`, :data:`DEGRADED`, :data:`UNHEALTHY`,
  :data:`DRAINING`) aggregate into ``GET /readyz``: 503 until every
  ``warmup=True`` model has its buckets compiled and no breaker is
  OPEN.  ``/healthz`` stays pure liveness.
* **SIGTERM-safe shutdown** — :func:`install_signal_handler` flips a
  process-wide flag (and runs :func:`on_shutdown` callbacks);
  :func:`run_until_shutdown` parks a server until then and drains it
  within ``MXNET_DRAIN_SECONDS`` (503 on new work, in-flight finishes,
  ``/readyz`` flips before the port closes).  Training loops poll
  :func:`shutdown_requested` at step boundaries and publish an
  emergency ``checkpoint.save_sync`` — the handler itself never
  snapshots mid-step state, so a preempted trainer resumes
  bit-identically (``ci/run_tests.sh lifecycle_smoke``).
"""
from __future__ import annotations

import signal
import sys
import threading
import time
from typing import Callable, Iterable, Optional

from ..base import MXNetError, getenv
from .. import telemetry as _telemetry
from .. import telemetry_ring as _ring
from . import metrics as _m

__all__ = [
    # states
    "STARTING", "SERVING", "DEGRADED", "UNHEALTHY", "DRAINING",
    # errors
    "DeadlineExceeded", "BreakerOpen", "Draining", "RequestAborted",
    "Cancelled",
    # pieces
    "CircuitBreaker", "Watchdog",
    # deadline helpers
    "default_timeout_ms", "deadline_from_ms",
    # shutdown plumbing
    "install_signal_handler", "on_shutdown", "shutdown_requested",
    "request_shutdown", "reset_shutdown_state", "run_until_shutdown",
]

# -- model states -----------------------------------------------------------
STARTING = "STARTING"       # registered, warmup still compiling buckets
SERVING = "SERVING"         # healthy, taking traffic
DEGRADED = "DEGRADED"       # recovering (watchdog restart / half-open
#                             breaker) — still takes traffic, still ready
UNHEALTHY = "UNHEALTHY"     # breaker OPEN or worker dead — not ready
DRAINING = "DRAINING"       # shutting down — not ready, sheds new work

#: numeric encoding for the ``mxtpu_serve_model_state`` gauge
STATE_CODE = {SERVING: 0, STARTING: 1, DEGRADED: 2, UNHEALTHY: 3,
              DRAINING: 4}


# -- errors (each maps to one HTTP status in serving/server.py) -------------
class DeadlineExceeded(MXNetError):
    """The request's end-to-end deadline expired (HTTP 504)."""


class BreakerOpen(MXNetError):
    """The model's circuit breaker is OPEN — fast-fail instead of
    queueing onto a broken model (HTTP 503 + ``Retry-After``)."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = max(0.0, float(retry_after))


class Draining(MXNetError):
    """The server is draining: in-flight work finishes, new work is
    refused (HTTP 503 + ``Retry-After``)."""

    retry_after = 1.0


class RequestAborted(MXNetError):
    """The request was failed by the watchdog (dead/hung worker) or by
    a drain timeout — the server, not the request, was at fault, so the
    client should retry elsewhere (HTTP 503)."""

    retry_after = 1.0


class Cancelled(MXNetError):
    """The request was cancelled by its own client (streaming disconnect
    or explicit ``cancel()``) mid-generation — the slot frees on the
    next decode-step boundary.  Never surfaces as an HTTP error: the
    client that would receive it is gone."""


# -- deadlines --------------------------------------------------------------
def default_timeout_ms() -> float:
    """Env default for per-request deadlines (``MXNET_SERVE_TIMEOUT_MS``;
    0 disables — the PR-5 block-forever behavior)."""
    return float(getenv("MXNET_SERVE_TIMEOUT_MS", 0.0))


def deadline_from_ms(timeout_ms: Optional[float],
                     now: Optional[float] = None) -> Optional[float]:
    """Absolute ``time.monotonic`` deadline for a request budget, or
    None when the budget is absent/zero (deadline-free)."""
    if timeout_ms is None:
        timeout_ms = default_timeout_ms()
    timeout_ms = float(timeout_ms)
    if timeout_ms <= 0:
        return None
    return (time.monotonic() if now is None else now) + timeout_ms / 1000.0


# -- circuit breaker --------------------------------------------------------
CLOSED, HALF_OPEN, OPEN = "CLOSED", "HALF_OPEN", "OPEN"
_BREAKER_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-model CLOSED → OPEN → HALF_OPEN → CLOSED breaker.

    ``record_failure`` counts *consecutive* dispatch-after-retry
    failures (the batcher's single-request fallback path); reaching
    ``threshold`` of them — or an explicit :meth:`trip` from the
    watchdog — opens the breaker.  While OPEN, :meth:`allow` raises
    :class:`BreakerOpen` so admission fast-fails; after
    ``cooldown_seconds`` exactly ONE request is admitted as a probe
    (HALF_OPEN).  The probe's success re-closes the breaker; its failure
    re-opens it for another cooldown.

    Knobs: ``MXNET_SERVE_BREAKER_THRESHOLD`` (default 5 consecutive
    failures) and ``MXNET_SERVE_BREAKER_COOLDOWN_SECONDS`` (default 2).
    """

    def __init__(self, name: str, threshold: Optional[int] = None,
                 cooldown_seconds: Optional[float] = None):
        self.name = str(name)
        if threshold is None:
            threshold = int(float(getenv("MXNET_SERVE_BREAKER_THRESHOLD",
                                         5)))
        if cooldown_seconds is None:
            cooldown_seconds = float(
                getenv("MXNET_SERVE_BREAKER_COOLDOWN_SECONDS", 2.0))
        self.threshold = max(1, int(threshold))
        self.cooldown_seconds = max(0.0, float(cooldown_seconds))
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        _m.BREAKER_STATE.set(0, model=self.name)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def retry_after(self) -> float:
        """Seconds until the next probe will be admitted."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._opened_at + self.cooldown_seconds
                       - time.monotonic())

    # -- transitions (callers hold no lock) -----------------------------
    def _to(self, state: str, reason: str) -> None:
        # _lock held by caller
        if state == self._state:
            return
        self._state = state
        _m.BREAKER_STATE.set(_BREAKER_CODE[state], model=self.name)
        _telemetry.FAULT.publish(site="serving.breaker", event="breaker",
                                 kind=state, model=self.name,
                                 reason=reason)

    def allow(self) -> None:
        """Admission gate: no-op when CLOSED; raises
        :class:`BreakerOpen` while OPEN (before the cooldown) and for
        every HALF_OPEN request beyond the single probe."""
        with self._lock:
            if self._state == CLOSED:
                return
            now = time.monotonic()
            if self._state == OPEN:
                if now - self._opened_at < self.cooldown_seconds:
                    raise BreakerOpen(
                        f"{self.name}: circuit breaker is OPEN",
                        retry_after=self._opened_at
                        + self.cooldown_seconds - now)
                self._to(HALF_OPEN, "cooldown elapsed")
                self._probing = False
            if self._probing:       # one probe at a time
                raise BreakerOpen(
                    f"{self.name}: circuit breaker is HALF_OPEN "
                    "(probe in flight)", retry_after=1.0)
            self._probing = True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._to(CLOSED, "probe succeeded")

    def record_failure(self, reason: str = "dispatch failed") -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == HALF_OPEN or self._failures >= self.threshold:
                self._open(reason)

    def trip(self, reason: str = "forced") -> None:
        """Force OPEN immediately (the watchdog's reaction to a hung or
        dead worker — no point counting to the threshold)."""
        with self._lock:
            self._open(reason)

    def _open(self, reason: str) -> None:
        # _lock held by caller
        self._opened_at = time.monotonic()
        self._failures = 0
        self._probing = False
        if self._state != OPEN:
            _m.BREAKER_TRIPS.inc(model=self.name)
        self._to(OPEN, reason)

    def __repr__(self):
        return (f"<CircuitBreaker {self.name!r}: {self.state}, "
                f"threshold={self.threshold}, "
                f"cooldown={self.cooldown_seconds}s>")


# -- watchdog ---------------------------------------------------------------
def default_hang_seconds() -> float:
    """``MXNET_SERVE_HANG_SECONDS`` (default 30; <= 0 disables hang
    detection — dead-worker detection stays on)."""
    return float(getenv("MXNET_SERVE_HANG_SECONDS", 30.0))


class Watchdog:
    """Background sweep over a set of batchers: each tick calls every
    batcher's ``check_worker(hang_seconds)``, which detects a dead or
    hung worker, fails that group's riders, restarts the worker and
    trips the breaker (see ``DynamicBatcher.check_worker``).

    Targets come from an explicit :meth:`watch` list and/or a
    ``supplier`` callable returning the current batchers — the
    ``ModelServer`` passes its live registry so models loaded after the
    watchdog started are covered without registration bookkeeping."""

    def __init__(self, supplier: Optional[Callable[[], Iterable]] = None,
                 hang_seconds: Optional[float] = None,
                 interval: Optional[float] = None):
        self.hang_seconds = default_hang_seconds() \
            if hang_seconds is None else float(hang_seconds)
        if interval is None:
            interval = min(1.0, max(0.05, self.hang_seconds / 4.0)) \
                if self.hang_seconds > 0 else 1.0
        self.interval = float(interval)
        self._supplier = supplier
        self._watched: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def watch(self, batcher) -> None:
        with self._lock:
            if batcher not in self._watched:
                self._watched.append(batcher)

    def unwatch(self, batcher) -> None:
        with self._lock:
            if batcher in self._watched:
                self._watched.remove(batcher)

    def _targets(self):
        with self._lock:
            targets = list(self._watched)
        if self._supplier is not None:
            try:
                for b in self._supplier():
                    if b not in targets:
                        targets.append(b)
            except Exception:
                pass
        return targets

    def start(self) -> "Watchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="mxtpu-serve-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def sweep(self) -> list:
        """One synchronous pass; returns ``(batcher, reason)`` pairs for
        every restart performed (tests drive this directly)."""
        hits = []
        for b in self._targets():
            try:
                reason = b.check_worker(self.hang_seconds)
            except Exception:       # a broken batcher must not kill the
                continue            # sweep for the healthy ones
            if reason:
                hits.append((b, reason))
        return hits

    def _run(self):
        while not self._stop.wait(self.interval):
            self.sweep()
            # the watchdog tick doubles as the flight recorder's metrics
            # sampler: the ring gets a coarse counter-delta timeline
            # (rate-limited inside note_metrics) for free
            _ring.recorder.note_metrics()


# -- SIGTERM-safe shutdown plumbing -----------------------------------------
_shutdown_event = threading.Event()
_shutdown_lock = threading.Lock()
_shutdown_callbacks: list = []
_installed_signals: dict = {}


def default_drain_seconds() -> float:
    """``MXNET_DRAIN_SECONDS`` (default 10): the budget between the
    shutdown signal and the port closing."""
    return float(getenv("MXNET_DRAIN_SECONDS", 10.0))


def on_shutdown(fn: Callable[[], None]) -> Callable[[], None]:
    """Register ``fn`` to run (on the main thread, inside the signal
    handler) when a shutdown signal arrives.  Keep callbacks
    signal-safe: set events, flip flags — a training loop should poll
    :func:`shutdown_requested` at its step boundary and checkpoint
    there, never snapshot mid-step state from the handler itself."""
    with _shutdown_lock:
        _shutdown_callbacks.append(fn)
    return fn


def shutdown_requested() -> bool:
    """True once a shutdown signal (or :func:`request_shutdown`) fired."""
    return _shutdown_event.is_set()


def request_shutdown(signum: Optional[int] = None,
                     frame=None) -> None:
    """Flip the shutdown flag and run the registered callbacks.  Also
    the installed signal handler."""
    first = not _shutdown_event.is_set()
    _shutdown_event.set()
    if not first:
        return
    _telemetry.FAULT.publish(site="serving.lifecycle", event="shutdown",
                             kind="signal" if signum else "requested",
                             signum=signum)
    with _shutdown_lock:
        callbacks = list(_shutdown_callbacks)
    for fn in callbacks:
        try:
            fn()
        except SystemExit:
            raise
        except Exception:           # one bad callback must not eat the
            pass                    # drain for the rest


def install_signal_handler(signals=(signal.SIGTERM,
                                    signal.SIGINT)) -> None:
    """Install :func:`request_shutdown` for ``signals`` (idempotent;
    main thread only — the ``signal`` module's own constraint)."""
    for s in signals:
        if s in _installed_signals:
            continue
        _installed_signals[s] = signal.signal(s, request_shutdown)


def reset_shutdown_state() -> None:
    """Clear the flag/callbacks and restore the previous signal
    handlers (test hygiene)."""
    _shutdown_event.clear()
    with _shutdown_lock:
        _shutdown_callbacks.clear()
    for s, prev in list(_installed_signals.items()):
        try:
            signal.signal(s, prev)
        except (ValueError, TypeError, OSError):
            pass
        del _installed_signals[s]


def run_until_shutdown(server, drain_seconds: Optional[float] = None,
                       poll_seconds: float = 0.5) -> int:
    """Park the calling (main) thread until SIGTERM/SIGINT, then drain
    ``server`` gracefully: new work gets 503, ``/readyz`` flips before
    the port closes, in-flight requests finish within
    ``MXNET_DRAIN_SECONDS``.  Returns 0 (the process exit code)."""
    install_signal_handler()
    try:
        while not _shutdown_event.wait(poll_seconds):
            pass
    except KeyboardInterrupt:       # SIGINT delivered around the wait
        pass
    sys.stderr.write("mxtpu-serve: shutdown signal — draining...\n")
    server.shutdown(drain_seconds=drain_seconds)
    sys.stderr.write("mxtpu-serve: drained, exiting\n")
    return 0
