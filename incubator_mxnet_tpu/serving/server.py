"""ModelServer — the HTTP front-end of the serving subsystem.

A stdlib ``ThreadingHTTPServer`` (one handler thread per connection —
the threads ARE the concurrent clients the batcher coalesces) over a
multi-model registry of ``(InferenceEngine, DynamicBatcher)`` pairs.
HTTP plumbing is shared with the telemetry exporter via
:mod:`incubator_mxnet_tpu.http_util`.

Routes (JSON tensors everywhere):

* ``POST /v1/models/<name>:predict`` — ``{"inputs": [...]}``
  (positional, nested lists with a leading batch dim) or
  ``{"inputs": {"data": [...]}}`` (keyed by the engine's input names),
  plus an optional ``"timeout_ms"`` end-to-end deadline (env default
  ``MXNET_SERVE_TIMEOUT_MS``); responds ``{"outputs": [...],
  "shapes": [...]}``.  Error mapping is the serving fault-domain
  contract (docs/robustness.md): 429 under backpressure, 404 for
  unknown models, 400 for malformed bodies, 504 when the deadline
  expires anywhere in the pipeline, 503 + ``Retry-After`` when the
  model's circuit breaker is OPEN, the watchdog failed the request, or
  the server is draining.
* ``POST /v1/models/<name>:generate`` — token generation against a
  :class:`GenerationEngine`-backed model: ``{"tokens": [...],
  "max_new_tokens": 32, "timeout_ms": ..., "eos_id": ...,
  "stream": false}``.  Non-streaming responds ``{"tokens": [...],
  "count": N, "request_id": ...}`` once generation finishes;
  ``"stream": true`` answers with chunked SSE (``event: token`` per
  emitted token as it leaves the decode loop, then ``event: done`` —
  or a terminal ``event: error``).  The same error ladder applies at
  admission; client disconnect mid-stream cancels the request and its
  KV-cache slot frees at the next decode-step boundary
  (docs/serving.md).
* ``POST /v1/models/<name>:load`` — ``{"prefix": ..., "epoch": 0,
  "input_names": ["data"], "input_specs": [[784]]}`` loads an exported
  symbol+params artifact into the registry.
* ``POST /v1/models/<name>:unload`` — drain + remove.
* ``GET /v1/models`` — registry with per-model batcher stats.
* ``GET /healthz`` — pure liveness: 200 whenever the process can
  answer, no matter how unhealthy the models are.
* ``GET /readyz`` — readiness: 200 only when every model can take
  traffic (every ``warmup=True`` model has its buckets compiled, no
  breaker is OPEN, no worker is dead) and the server is not draining;
  503 otherwise, so a load balancer / rollout controller pulls the
  replica without killing it.
* ``GET /metrics`` — the SHARED telemetry registry in Prometheus text
  form; ``mxtpu_serve_*`` and ``mxtpu_slo_*`` series ride along with
  every other runtime metric, no extra wiring.
* ``GET /slo`` — per-model SLIs, burn rate, and error-budget state
  (serving/slo.py); an exhausted budget also surfaces as a
  ``slo:<model>`` blocker on ``/readyz``.
* ``GET /trace`` — the span tree, bounded (``?limit=``/``?since=``)
  with per-request lookup (``?request_id=``); same contract as the
  telemetry exporter's route (shared via ``telemetry_http.trace_body``).
* ``GET /programs`` — the runtime program-set inventory: the dispatch
  ledger (per-site dispatch counts, wall-time percentiles, compile
  time, last-dispatch age) plus every engine's expected-vs-compiled
  accounting — the closed-program-set contract, checkable at runtime.
* ``GET /memory`` — device-memory breakdown: per-device bytes-in-use /
  peak watermarks plus the per-owner attribution
  (``kv:<model>`` / ``params:<model>`` / ``optimizer``) and the
  unattributed residue (telemetry_device).
* ``POST /debug/profile?seconds=`` — on-demand ``jax.profiler``
  capture; blocks for the (clamped) window and answers with the
  artifact directory, 409 while another capture runs.
* ``POST /admin/drain`` / ``POST /admin/undrain`` — the rolling-update
  pair: drain flips ``/readyz`` to 503 (port stays open, in-flight
  finishes) so a router pulls the replica; undrain takes traffic again.
  ``mxtpu-router`` orchestrates these for zero-downtime weight updates
  (docs/serving.md).

Every response carries an ``X-Request-Id`` header (client-supplied
``x-request-id`` or generated — ``http_util.BaseJSONHandler``); predict
errors additionally carry ``"request_id"`` in the JSON body, and the
same id is stamped on the request's span and FAULT events, so one grep
follows a failed request end to end (docs/observability.md).

Shutdown: ``stop()`` is the immediate programmatic teardown;
``shutdown()`` is the SIGTERM-safe sequence (flip to DRAINING → 503 on
new work and on ``/readyz`` → wait for in-flight work within
``MXNET_DRAIN_SECONDS`` → close the port) used by ``mxtpu-serve`` via
``lifecycle.run_until_shutdown``.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional

import numpy as _np

from ..base import MXNetError, getenv_int
from ..http_util import BaseJSONHandler, HTTPServerBase, \
    start_http_server, stop_http_server
from .. import telemetry as _telemetry
from .. import telemetry_device as _telemetry_device
from .. import telemetry_ring as _ring
from .batcher import ContinuousBatcher, DynamicBatcher, QueueFullError
from .engine import GenerationEngine, InferenceEngine
from .sampling import SamplingParams
from . import lifecycle as _lc
from . import metrics as _m
from . import slo as _slo

__all__ = ["ModelServer"]


def _retry_after_header(seconds: float) -> dict:
    return {"Retry-After": str(max(1, int(math.ceil(seconds))))}


class _ServingHTTPServer(HTTPServerBase):
    model_server: "ModelServer" = None


class _Handler(BaseJSONHandler):
    server_version = "mxtpu-serve/1.0"

    def do_GET(self):  # noqa: N802 (http.server API)
        self.guard(self._get)

    def do_POST(self):  # noqa: N802
        self.guard(self._post)

    def _get(self):
        from urllib.parse import parse_qs, urlsplit
        ms = self.server.model_server
        split = urlsplit(self.path)
        params = parse_qs(split.query)
        path = split.path.rstrip("/") or "/"
        if path == "/healthz":
            # liveness ONLY: answering at all is the signal
            self.send_json(200, {"status": "ok",
                                 "models": sorted(ms.models())})
        elif path == "/readyz":
            ready, body = ms.readiness()
            self.send_json(200 if ready else 503, body,
                           headers=None if ready
                           else _retry_after_header(1.0))
        elif path == "/v1/models":
            self.send_json(200, {"models": ms.model_stats()})
        elif path == "/slo":
            self.send_json(200, _slo.tracker.snapshot())
        elif path == "/health":
            # health-plane forensics (health.py): anomaly state, ring
            # tail, per-model decode stats — not liveness (/healthz)
            self.send_json(200, ms.health_report())
        elif path == "/trace":
            from .. import telemetry_http
            self.send_json(200, telemetry_http.trace_body(params))
        elif path == "/flight":
            from .. import telemetry_http
            self.send_json(200, telemetry_http.flight_body())
        elif path == "/programs":
            # the runtime program-set inventory: dispatch ledger plus
            # every engine's expected-vs-compiled accounting — the
            # closed-program-set contract, observable at runtime
            self.send_json(200, ms.program_report())
        elif path == "/memory":
            # refresh + return the device-memory breakdown (per-device
            # watermarks, per-owner attribution, unattributed residue)
            self.send_json(200, _telemetry_device.sample())
        elif path == "/metrics.json":
            from .. import telemetry_http
            self.send_json(200, telemetry_http.metrics_state_body())
        elif path in ("/metrics", "/"):
            from .. import telemetry
            self._send(200, telemetry.render_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
        else:
            self.send_text(404, "not found: try /v1/models /healthz "
                                "/readyz /metrics /metrics.json /slo "
                                "/health /trace /flight /programs "
                                "/memory\n")

    def _remote_trace(self):
        """Adopt the router's ``X-Trace-Id`` hop as the remote parent of
        spans this request opens (``serve.request`` and below), so the
        router's ``GET /trace`` stitcher can graft this replica's
        subtree under its hop span.  A no-op context when the header is
        absent or malformed — propagation never fails a request."""
        tp = self.trace_parent()
        if tp is None:
            import contextlib
            return contextlib.nullcontext()
        return _telemetry.tracer.remote(*tp)

    def _post(self):
        ms = self.server.model_server
        path = self.path.split("?", 1)[0]
        if path == "/debug/profile":
            # on-demand jax.profiler capture: blocks THIS handler
            # thread for the (clamped) window, then names the artifact
            # directory; a second concurrent capture answers 409
            from urllib.parse import parse_qs, urlsplit
            params = parse_qs(urlsplit(self.path).query)
            try:
                seconds = float(params.get("seconds", ["1.0"])[0])
            except ValueError:
                self.send_json(400, {"error":
                                     "seconds must be a number"})
                return
            try:
                artifact = _telemetry_device.capture_profile(seconds)
            except _telemetry_device.CaptureBusy as e:
                self.send_json(409, {"error": str(e)},
                               headers=_retry_after_header(1.0))
                return
            except Exception as e:
                self.send_json(500, {"error": f"profiler capture "
                                     f"failed: {e}"})
                return
            self.send_json(200, {"profile": artifact})
            return
        if path == "/admin/drain":
            # flip to DRAINING without closing the port: /readyz answers
            # 503 so the router/balancer stops sending, in-flight work
            # finishes — the first half of a zero-downtime rolling update
            ms.begin_drain()
            self.send_json(200, {"draining": True,
                                 "inflight": ms.inflight_http})
            return
        if path == "/admin/undrain":
            # weight update done: take traffic again (readiness still
            # gates on model state, so an unhealthy model stays blocked)
            ms.end_drain()
            self.send_json(200, {"draining": False})
            return
        if not path.startswith("/v1/models/") or ":" not in path:
            self.send_text(404,
                           "not found: POST /v1/models/<name>:predict\n")
            return
        name, _, verb = path[len("/v1/models/"):].rpartition(":")
        rid = self.request_id()

        def err(code, body, headers=None):
            body["request_id"] = rid
            self.send_json(code, body, headers=headers)

        try:
            payload = self.read_json()
        except ValueError as e:
            err(400, {"error": str(e)})
            return
        try:
            if verb == "predict":
                ms._http_enter()
                try:
                    with self._remote_trace():
                        out = ms.predict_json(name, payload,
                                              request_id=rid)
                finally:
                    ms._http_exit()
                self.send_json(200, out)
            elif verb == "generate":
                ms._http_enter()
                try:
                    with self._remote_trace():
                        self._generate(ms, name, payload, rid)
                finally:
                    ms._http_exit()
            elif verb == "load":
                ms.load_model(name, payload)
                self.send_json(200, {"loaded": name})
            elif verb == "unload":
                ms.remove_model(name)
                self.send_json(200, {"unloaded": name})
            else:
                err(404, {"error": f"unknown verb {verb!r}; "
                          "try :predict :generate :load :unload"})
        except KeyError:
            err(404, {"error": f"model {name!r} is not "
                      "loaded", "models": sorted(ms.models())})
        except QueueFullError as e:
            retry = getattr(e, "retry_after", 1.0)
            err(429, {"error": str(e), "retry_after": retry},
                headers=_retry_after_header(retry))
        except _lc.DeadlineExceeded as e:
            err(504, {"error": str(e)})
        except TimeoutError as e:
            # a bare result() timeout (no deadline set) is still the
            # server failing to answer in time, not a client error
            err(504, {"error": str(e) or
                      "inference request timed out"})
        except _lc.BreakerOpen as e:
            err(503, {"error": str(e),
                      "retry_after": e.retry_after},
                headers=_retry_after_header(e.retry_after))
        except (_lc.Draining, _lc.RequestAborted) as e:
            err(503, {"error": str(e)},
                headers=_retry_after_header(e.retry_after))
        except (ValueError, TypeError, MXNetError) as e:
            err(400, {"error": str(e)})

    def _generate(self, ms, name, payload, rid):
        """``:generate`` body.  Admission errors raise out of here into
        ``_post``'s error ladder — the status line has not been sent
        yet.  Once the stream is open the status is on the wire, so
        worker-side failures become terminal SSE ``error`` events
        instead, and a broken pipe (client disconnect) cancels the
        request so its slot frees at the next decode-step boundary.

        The whole admission-to-last-event lifetime runs under a
        ``serve.request`` span opened HERE: the blocking predict path
        gets its span inside ``DynamicBatcher.submit``, but generation
        hands the request handle back to this thread, so without this
        span the HTTP ``:generate`` path would leave no request-scoped
        trace — and nothing for :meth:`_remote_trace` to stamp the
        router's hop onto."""
        stream = bool(payload.get("stream", False)) \
            if isinstance(payload, dict) else False
        with _telemetry.trace_span("serve.request", cat="serving",
                                   model=name, request_id=rid,
                                   stream=stream):
            req = ms.generate_request(name, payload, request_id=rid)
            if not stream:
                toks = req.result()
                body = {"tokens": toks, "count": len(toks),
                        "accepted_tokens": int(req.accepted_tokens),
                        "draft_tokens": int(req.draft_tokens),
                        "request_id": req.request_id}
                # the replay contract (docs/serving.md): a sampled
                # response always echoes its effective seed
                if req.seed is not None:
                    body["seed"] = int(req.seed)
                if getattr(req, "logprobs_n", 0):
                    body["logprobs"] = list(req.logprobs_out)
                children = getattr(req, "children", None)
                if children is not None:
                    body["candidates"] = [
                        {"tokens": list(c.tokens_out),
                         "seed": None if c.seed is None
                         else int(c.seed),
                         "request_id": c.request_id,
                         **({"logprobs": list(c.logprobs_out)}
                            if c.logprobs_n else {})}
                        for c in children]
                self.send_json(200, body)
                return
            self.start_stream(200)
            try:
                lp_n = int(getattr(req, "logprobs_n", 0) or 0)
                for i, tok in enumerate(req.stream()):
                    ev = {"token": int(tok), "index": i}
                    if lp_n and i < len(req.logprobs_out):
                        ev["logprobs"] = req.logprobs_out[i]
                    self.send_event(ev, event="token")
                done_ev = {"tokens": list(req.tokens_out),
                           "count": len(req.tokens_out),
                           "accepted_tokens":
                               int(req.accepted_tokens),
                           "draft_tokens": int(req.draft_tokens),
                           "request_id": req.request_id}
                if req.seed is not None:
                    done_ev["seed"] = int(req.seed)
                self.send_event(done_ev, event="done")
            except (BrokenPipeError, ConnectionError, OSError):
                req.cancel()            # client went away mid-stream
                return
            except Exception as e:
                try:
                    self.send_event({"error": str(e),
                                     "request_id": req.request_id},
                                    event="error")
                except OSError:
                    req.cancel()
                    return
            try:
                self.end_stream()
            except OSError:
                pass


class ModelServer:
    """Multi-model inference server.  Programmatic use::

        srv = ModelServer(port=0)
        srv.add_model("mnist", engine)          # or engine kwargs
        srv.start()
        ... requests against srv.port ...
        srv.stop()                              # immediate teardown
        # or srv.shutdown() — the SIGTERM drain sequence

    Batcher knobs passed to :meth:`add_model` override the env defaults
    (``MXNET_SERVE_MAX_BATCH`` / ``MXNET_SERVE_MAX_DELAY_MS`` /
    ``MXNET_SERVE_QUEUE`` / ``MXNET_SERVE_TIMEOUT_MS``); the port
    default is ``MXNET_SERVE_PORT`` (8080)."""

    def __init__(self, port: Optional[int] = None, host: str = "0.0.0.0",
                 **batcher_defaults):
        self._port = getenv_int("MXNET_SERVE_PORT", 8080) \
            if port is None else int(port)
        self._host = host
        self._batcher_defaults = dict(batcher_defaults)
        self._models: Dict[str, DynamicBatcher] = {}
        self._lock = threading.Lock()
        self._http: Optional[_ServingHTTPServer] = None
        self._watchdog: Optional[_lc.Watchdog] = None
        self._draining = False
        self._warm_pending: set = set()
        self._warm_errors: Dict[str, BaseException] = {}
        self._inflight_http = 0
        self._last_http = time.monotonic()

    # -- registry -------------------------------------------------------
    def add_model(self, name: str, engine: InferenceEngine,
                  warmup: bool = False, async_warmup: bool = False,
                  **batcher_kw) -> DynamicBatcher:
        """Register ``engine`` under ``name`` behind a fresh
        :class:`DynamicBatcher`.  ``warmup=True`` AOT-compiles every
        declared bucket before the model takes traffic;
        ``async_warmup=True`` does that compilation on a background
        thread instead — the model registers immediately in the
        STARTING state and ``/readyz`` stays 503 until its programs
        exist (the AOT-warmed readiness gate)."""
        if self._draining:
            raise _lc.Draining(
                f"server is draining; refusing to load {name!r}")
        if warmup and not async_warmup:
            engine.warmup()
        kw = dict(self._batcher_defaults)
        kw.update(batcher_kw)
        if isinstance(engine, GenerationEngine):
            # generation engines serve token streams, not one-shot
            # batches: slot-based continuous batching instead of the
            # gather→dispatch→scatter cycle
            batcher = ContinuousBatcher(engine, name=name, **kw)
        else:
            batcher = DynamicBatcher(engine, name=name, **kw)
        with self._lock:
            if name in self._models:
                batcher.close(drain=False)
                raise MXNetError(f"model {name!r} is already loaded")
            self._models[name] = batcher
            self._warm_errors.pop(name, None)
            if warmup and async_warmup:
                self._warm_pending.add(name)
            _m.MODELS_LOADED.set(len(self._models))
        if warmup and async_warmup:
            threading.Thread(target=self._warm_async,
                             args=(name, engine),
                             name=f"mxtpu-serve-warmup-{name}",
                             daemon=True).start()
        return batcher

    def _warm_async(self, name: str, engine: InferenceEngine) -> None:
        try:
            engine.warmup()
        except Exception as e:          # readiness shows the model
            with self._lock:            # UNHEALTHY instead of wedging
                self._warm_errors[name] = e
        finally:
            with self._lock:
                self._warm_pending.discard(name)

    def load_model(self, name: str, payload: dict) -> DynamicBatcher:
        """Registry ``:load`` verb — build an engine from an exported
        artifact described by the JSON payload."""
        if self._draining:
            raise _lc.Draining(
                f"server is draining; refusing to load {name!r}")
        if not isinstance(payload, dict) or "prefix" not in payload:
            raise ValueError(':load needs {"prefix": ..., "epoch": 0}')
        engine = InferenceEngine.from_export(
            str(payload["prefix"]), int(payload.get("epoch", 0)),
            input_names=payload.get("input_names", ("data",)),
            input_specs=payload.get("input_specs"),
            max_batch_size=payload.get("max_batch_size"),
            buckets=payload.get("buckets"), name=name)
        return self.add_model(name, engine,
                              warmup=bool(payload.get("warmup", False)))

    def remove_model(self, name: str) -> None:
        """Drain the model's batcher and drop it from the registry."""
        with self._lock:
            batcher = self._models.pop(name)   # KeyError → HTTP 404
            self._warm_pending.discard(name)
            self._warm_errors.pop(name, None)
            _m.MODELS_LOADED.set(len(self._models))
        batcher.close(drain=True)

    def get_model(self, name: str) -> DynamicBatcher:
        with self._lock:                # :load/:unload mutate the dict
            return self._models[name]

    def models(self):
        with self._lock:
            return list(self._models)

    def model_stats(self) -> dict:
        with self._lock:
            items = sorted(self._models.items())
        out = {}
        for n, b in items:
            st = b.stats()
            inv = getattr(b.engine, "program_inventory", None)
            if inv is not None:
                try:        # program accounting rides /v1/models too
                    st["programs"] = inv()
                except Exception as e:
                    st["programs"] = {"error": repr(e)}
            out[n] = st
        return out

    def program_report(self) -> dict:
        """``GET /programs``: the dispatch ledger plus every registered
        engine's expected-vs-compiled program accounting (also the
        ``programs`` provider in flight dumps — telemetry_device)."""
        return _telemetry_device.program_report()

    # -- health ---------------------------------------------------------
    def health_report(self) -> dict:
        """``GET /health``: the health-plane summary (health.report —
        detector status, anomaly counts, last anomaly, StepHealth ring
        tail) plus each generation model's latest decode-step stats.
        Distinct from ``/healthz`` (liveness) and ``/readyz``
        (routability): this is the FORENSIC view — what the in-program
        stats say about the numerics."""
        from .. import health as _health
        body = _health.report()
        with self._lock:
            batchers = dict(self._models)
        models = {}
        for n, b in sorted(batchers.items()):
            dh = getattr(b, "_decode_health_last", None)
            if dh is not None:
                models[n] = {
                    "decode_health": dict(dh),
                    "nonfinite_generations":
                        getattr(b, "_nonfinite_generations", 0),
                }
        if models:
            body["models"] = models
        return body

    def model_state(self, name: str) -> str:
        """One model's serving state, folding in async-warmup progress
        (STARTING while compiling, UNHEALTHY if warmup failed)."""
        with self._lock:
            batcher = self._models[name]       # KeyError → HTTP 404
            if name in self._warm_pending:
                return _lc.STARTING
            if name in self._warm_errors:
                return _lc.UNHEALTHY
        return batcher.state

    def readiness(self):
        """``(ready, body)`` for ``GET /readyz``: ready only when not
        draining and every model's state is SERVING or DEGRADED (a
        degraded model still takes traffic; STARTING and UNHEALTHY do
        not)."""
        with self._lock:
            names = list(self._models)
            draining = self._draining
        states = {}
        for n in names:
            try:
                states[n] = _lc.DRAINING if draining \
                    else self.model_state(n)
            except KeyError:            # unloaded while we looked
                continue
            _m.MODEL_STATE.set(_lc.STATE_CODE[states[n]], model=n)
        blockers = [n for n, s in states.items()
                    if s not in (_lc.SERVING, _lc.DEGRADED)]
        # an exhausted error budget pulls the replica from rotation even
        # while the model itself still answers (serving/slo.py)
        blockers += [f"slo:{n}" for n in _slo.tracker.exhausted()
                     if n in states]
        # a paged KV pool exhausted for K consecutive watchdog sweeps
        # pulls the replica too: the router should route generation to
        # replicas with capacity instead of eating this one's 429s
        with self._lock:
            batchers = dict(self._models)
        blockers += [f"kv:{n}" for n, b in batchers.items()
                     if n in states and getattr(b, "kv_starved", False)]
        blockers = sorted(blockers)
        ready = not draining and not blockers
        body = {"status": "ready" if ready else
                ("draining" if draining else "unready"),
                "draining": draining, "models": states}
        if blockers and not draining:
            body["blockers"] = blockers
        return ready, body

    @property
    def draining(self) -> bool:
        return self._draining

    # -- inference ------------------------------------------------------
    def predict_json(self, name: str, payload: dict,
                     request_id: Optional[str] = None) -> dict:
        """Decode JSON tensors, run them through the model's batcher,
        re-encode the per-request outputs.  Inputs decode at the
        engine's DECLARED dtypes when it has input specs (an int32
        model served over HTTP gets int32 tensors, not a silent
        float32 cast); ``timeout_ms`` in the payload sets the
        end-to-end deadline; ``request_id`` (the HTTP front-end passes
        the echoed ``x-request-id``) tags the request's span and any
        FAULT events it triggers."""
        if self._draining:
            raise _lc.Draining(f"server is draining; model {name!r} is "
                               "not accepting new work")
        batcher = self.get_model(name)          # KeyError → HTTP 404
        timeout_ms = None
        inputs = payload
        if isinstance(payload, dict):
            timeout_ms = payload.get("timeout_ms")
            if timeout_ms is not None:
                timeout_ms = float(timeout_ms)  # ValueError → HTTP 400
            inputs = payload.get("inputs", payload)
        if isinstance(inputs, dict):
            names = batcher.engine.input_names
            missing = [n for n in names if n not in inputs]
            if missing:
                raise ValueError(f"missing inputs {missing}; "
                                 f"{name!r} takes {names}")
            inputs = [inputs[n] for n in names]
        if not isinstance(inputs, (list, tuple)) or not inputs:
            raise ValueError('"inputs" must be a non-empty list of '
                             "tensors or a {name: tensor} object")
        dtypes = batcher.engine.input_dtypes
        arrays = []
        for i, v in enumerate(inputs):
            dt = dtypes[i] if dtypes and i < len(dtypes) else _np.float32
            arrays.append(_np.asarray(v, dtype=dt))
        for a in arrays:
            if a.ndim == 0:
                raise ValueError("each input needs a leading batch dim")
        outs = batcher.submit(arrays, timeout_ms=timeout_ms,
                              request_id=request_id)
        outs = [_np.asarray(o) for o in outs]
        return {"outputs": [o.tolist() for o in outs],
                "shapes": [list(o.shape) for o in outs]}

    def generate_request(self, name: str, payload: dict,
                         request_id: Optional[str] = None):
        """Parse a ``:generate`` payload and admit it into the model's
        continuous batcher; returns the live request handle (the HTTP
        front-end either waits on ``.result()`` or iterates
        ``.stream()``).  Admission failures are recorded against the
        model's SLO here because — unlike the blocking ``submit`` path —
        the handler owns the request lifetime from this point on."""
        if self._draining:
            raise _lc.Draining(f"server is draining; model {name!r} is "
                               "not accepting new work")
        batcher = self.get_model(name)          # KeyError → HTTP 404
        if not isinstance(batcher, ContinuousBatcher):
            raise ValueError(
                f"model {name!r} is not a generation model; "
                "use :predict")
        if not isinstance(payload, dict):
            raise ValueError(':generate needs a JSON object body')
        tokens = payload.get("tokens", payload.get("inputs"))
        if isinstance(tokens, (list, tuple)) and len(tokens) == 1 \
                and isinstance(tokens[0], (list, tuple)):
            tokens = tokens[0]          # accept a [[...]] batch of one
        if not isinstance(tokens, (list, tuple)) or not tokens:
            raise ValueError('"tokens" must be a non-empty list of '
                             "token ids")
        tokens = [int(t) for t in tokens]       # ValueError → HTTP 400
        max_new = int(payload.get("max_new_tokens", 32))
        timeout_ms = payload.get("timeout_ms")
        if timeout_ms is not None:
            timeout_ms = float(timeout_ms)      # ValueError → HTTP 400
        eos_id = payload.get("eos_id")
        if eos_id is not None:
            eos_id = int(eos_id)
        sampling = None
        if any(k in payload for k in
               ("temperature", "top_k", "top_p", "seed", "logprobs",
                "stop", "n", "logit_bias", "json_mode")):
            lb = payload.get("logit_bias")
            if lb is not None:
                if not isinstance(lb, dict):
                    raise ValueError(
                        '"logit_bias" must be an object mapping token '
                        "id -> bias")
                lb = {int(t): float(b) for t, b in lb.items()}
            seed = payload.get("seed")
            sampling = SamplingParams(
                temperature=float(payload.get("temperature", 0.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 1.0)),
                seed=int(seed) if seed is not None else None,
                logprobs=int(payload.get("logprobs", 0)),
                stop=tuple(payload.get("stop") or ()),
                n=int(payload.get("n", 1)),
                logit_bias=lb,
                json_mode=bool(payload.get("json_mode", False)))
            if sampling.n > 1 and bool(payload.get("stream", False)):
                raise ValueError(
                    "streaming and n > 1 cannot be combined; stream "
                    "each candidate as its own request")
        try:
            return batcher.submit_async(
                tokens, max_new_tokens=max_new, timeout_ms=timeout_ms,
                request_id=request_id, eos_id=eos_id, sampling=sampling)
        except Exception:
            _slo.tracker.record(name, 0.0, ok=False)
            raise

    # -- drain bookkeeping (the HTTP handler reports in-flight work) ----
    def _http_enter(self) -> None:
        with self._lock:
            self._inflight_http += 1

    def _http_exit(self) -> None:
        with self._lock:
            self._inflight_http -= 1
            self._last_http = time.monotonic()

    # -- lifecycle ------------------------------------------------------
    def preload(self) -> "ModelServer":
        """Synchronously AOT-compile every registered model's full
        program set — all prefill buckets, the decode program, and the
        speculative ``verify`` program when a draft is attached —
        BEFORE :meth:`start` binds the port (``mxtpu-serve
        --preload``).  A replica started this way never answers
        ``/readyz`` 200 with a cold program cache: the router's
        cold-start drill asserts first-token latency matches steady
        state.  Idempotent; engines that are already warm are
        skipped."""
        with self._lock:
            batchers = sorted(self._models.items())
        for name, b in batchers:
            eng = getattr(b, "engine", None)
            wu = getattr(eng, "warmup", None)
            if wu is None or getattr(eng, "warm", False):
                continue
            wu()
        return self

    def start(self) -> "ModelServer":
        """Bind and serve in daemon threads; returns self.  ``port=0``
        binds an ephemeral port (see :attr:`port`).  Also starts the
        worker watchdog over the live registry."""
        if self._http is not None:
            return self
        srv = start_http_server(_Handler, self._port, self._host,
                                name="mxtpu-serve-http",
                                server_cls=_ServingHTTPServer)
        srv.model_server = self
        self._http = srv
        if self._watchdog is None:
            self._watchdog = _lc.Watchdog(supplier=self._batchers)
        self._watchdog.start()
        # flight recorder: hold a reference for the server's lifetime
        # (postmortems even when full telemetry is off) and contribute
        # the serving section of every dump
        _ring.recorder.start()
        _ring.recorder.register_provider("serving", self._flight_state)
        # background device-memory gauge sampler (no-op unless
        # MXNET_DEVICE_MEM_INTERVAL_SECONDS > 0 — scrapes refresh too)
        _telemetry_device.start_sampler()
        return self

    def _flight_state(self) -> dict:
        """Flight-dump provider: per-model lifecycle/breaker states and
        the request ids currently queued or in flight."""
        with self._lock:
            batchers = dict(self._models)
            draining = self._draining
        out = {"draining": draining, "models": {}}
        for n, b in sorted(batchers.items()):
            try:
                out["models"][n] = {
                    "state": self.model_state(n),
                    "breaker": b.breaker.state,
                    "restarts": b.restarts,
                    "requests": b.active_request_ids(),
                }
            except Exception as e:      # a sick model is itself data
                out["models"][n] = {"error": repr(e)}
        return out

    def _batchers(self):
        with self._lock:
            return list(self._models.values())

    @property
    def inflight_http(self) -> int:
        """HTTP requests currently inside a predict/generate handler."""
        with self._lock:
            return self._inflight_http

    def begin_drain(self) -> None:
        """Flip to DRAINING: ``/readyz`` answers 503 and new predict /
        load work is refused with 503 + ``Retry-After`` while in-flight
        requests keep going.  The port stays OPEN — the balancer needs
        the 503s, not a reset."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._last_http = time.monotonic()

    def end_drain(self) -> None:
        """Resume taking traffic after :meth:`begin_drain`
        (``POST /admin/undrain``): the second half of a rolling weight
        update — drain, swap weights, undrain — without a process
        restart.  A server already torn down by :meth:`stop` stays
        stopped; this only clears the drain gate."""
        with self._lock:
            self._draining = False

    def shutdown(self, drain_seconds: Optional[float] = None,
                 linger_seconds: float = 0.3) -> None:
        """The SIGTERM-safe teardown: :meth:`begin_drain`, wait (within
        ``MXNET_DRAIN_SECONDS``) until every batcher is idle, no predict
        handler is in flight, and traffic has been quiet for
        ``linger_seconds`` — then :meth:`stop`.  In-flight requests
        finish with 200; late arrivals see 503, never a reset."""
        if drain_seconds is None:
            drain_seconds = _lc.default_drain_seconds()
        self.begin_drain()
        deadline = time.monotonic() + max(0.0, float(drain_seconds))
        while time.monotonic() < deadline:
            with self._lock:
                inflight = self._inflight_http
                last = self._last_http
                batchers = list(self._models.values())
            if inflight == 0 and all(b.idle for b in batchers) \
                    and time.monotonic() - last >= linger_seconds:
                break
            time.sleep(0.02)
        self.stop(drain=True)

    def stop(self, drain: bool = True) -> None:
        """Stop the HTTP front-end, then close every batcher
        (``drain=True`` finishes queued work first)."""
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._http is not None:
            _ring.recorder.unregister_provider("serving")
            _ring.recorder.stop()
            _telemetry_device.stop_sampler()
        stop_http_server(self._http)
        self._http = None
        with self._lock:
            batchers = list(self._models.values())
            self._models.clear()
            self._warm_pending.clear()
            self._warm_errors.clear()
            _m.MODELS_LOADED.set(0)
        for b in batchers:
            b.close(drain=drain)

    @property
    def port(self) -> Optional[int]:
        """The bound port once :meth:`start` has run."""
        return self._http.server_address[1] if self._http else self._port

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
