"""ModelServer — the HTTP front-end of the serving subsystem.

A stdlib ``ThreadingHTTPServer`` (one handler thread per connection —
the threads ARE the concurrent clients the batcher coalesces) over a
multi-model registry of ``(InferenceEngine, DynamicBatcher)`` pairs.
HTTP plumbing is shared with the telemetry exporter via
:mod:`incubator_mxnet_tpu.http_util`.

Routes (JSON tensors everywhere):

* ``POST /v1/models/<name>:predict`` — ``{"inputs": [...]}``
  (positional, nested lists with a leading batch dim) or
  ``{"inputs": {"data": [...]}}`` (keyed by the engine's input names);
  responds ``{"outputs": [...], "shapes": [...]}``.  429 under
  backpressure, 404 for unknown models, 400 for malformed bodies.
* ``POST /v1/models/<name>:load`` — ``{"prefix": ..., "epoch": 0,
  "input_names": ["data"], "input_specs": [[784]]}`` loads an exported
  symbol+params artifact into the registry.
* ``POST /v1/models/<name>:unload`` — drain + remove.
* ``GET /v1/models`` — registry with per-model batcher stats.
* ``GET /healthz`` — liveness.
* ``GET /metrics`` — the SHARED telemetry registry in Prometheus text
  form; ``mxtpu_serve_*`` series ride along with every other runtime
  metric, no extra wiring.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as _np

from ..base import MXNetError, getenv_int
from ..http_util import BaseJSONHandler, HTTPServerBase, \
    start_http_server, stop_http_server
from .batcher import DynamicBatcher, QueueFullError
from .engine import InferenceEngine
from . import metrics as _m

__all__ = ["ModelServer"]


class _ServingHTTPServer(HTTPServerBase):
    model_server: "ModelServer" = None


class _Handler(BaseJSONHandler):
    server_version = "mxtpu-serve/1.0"

    def do_GET(self):  # noqa: N802 (http.server API)
        self.guard(self._get)

    def do_POST(self):  # noqa: N802
        self.guard(self._post)

    def _get(self):
        ms = self.server.model_server
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/healthz":
            self.send_json(200, {"status": "ok",
                                 "models": sorted(ms.models())})
        elif path == "/v1/models":
            self.send_json(200, {"models": ms.model_stats()})
        elif path in ("/metrics", "/"):
            from .. import telemetry
            self._send(200, telemetry.render_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
        else:
            self.send_text(404, "not found: try /v1/models /healthz "
                                "/metrics\n")

    def _post(self):
        ms = self.server.model_server
        path = self.path.split("?", 1)[0]
        if not path.startswith("/v1/models/") or ":" not in path:
            self.send_text(404,
                           "not found: POST /v1/models/<name>:predict\n")
            return
        name, _, verb = path[len("/v1/models/"):].rpartition(":")
        try:
            payload = self.read_json()
        except ValueError as e:
            self.send_json(400, {"error": str(e)})
            return
        try:
            if verb == "predict":
                self.send_json(200, ms.predict_json(name, payload))
            elif verb == "load":
                ms.load_model(name, payload)
                self.send_json(200, {"loaded": name})
            elif verb == "unload":
                ms.remove_model(name)
                self.send_json(200, {"unloaded": name})
            else:
                self.send_json(404, {"error": f"unknown verb {verb!r}; "
                                     "try :predict :load :unload"})
        except KeyError:
            self.send_json(404, {"error": f"model {name!r} is not "
                                 "loaded", "models": sorted(ms.models())})
        except QueueFullError as e:
            self.send_json(429, {"error": str(e)})
        except (ValueError, TypeError, MXNetError) as e:
            self.send_json(400, {"error": str(e)})


class ModelServer:
    """Multi-model inference server.  Programmatic use::

        srv = ModelServer(port=0)
        srv.add_model("mnist", engine)          # or engine kwargs
        srv.start()
        ... requests against srv.port ...
        srv.stop()                              # graceful drain

    Batcher knobs passed to :meth:`add_model` override the env defaults
    (``MXNET_SERVE_MAX_BATCH`` / ``MXNET_SERVE_MAX_DELAY_MS`` /
    ``MXNET_SERVE_QUEUE``); the port default is ``MXNET_SERVE_PORT``
    (8080)."""

    def __init__(self, port: Optional[int] = None, host: str = "0.0.0.0",
                 **batcher_defaults):
        self._port = getenv_int("MXNET_SERVE_PORT", 8080) \
            if port is None else int(port)
        self._host = host
        self._batcher_defaults = dict(batcher_defaults)
        self._models: Dict[str, DynamicBatcher] = {}
        self._lock = threading.Lock()
        self._http: Optional[_ServingHTTPServer] = None

    # -- registry -------------------------------------------------------
    def add_model(self, name: str, engine: InferenceEngine,
                  warmup: bool = False, **batcher_kw) -> DynamicBatcher:
        """Register ``engine`` under ``name`` behind a fresh
        :class:`DynamicBatcher`.  ``warmup=True`` AOT-compiles every
        declared bucket before the model takes traffic."""
        if warmup:
            engine.warmup()
        kw = dict(self._batcher_defaults)
        kw.update(batcher_kw)
        batcher = DynamicBatcher(engine, name=name, **kw)
        with self._lock:
            if name in self._models:
                batcher.close(drain=False)
                raise MXNetError(f"model {name!r} is already loaded")
            self._models[name] = batcher
            _m.MODELS_LOADED.set(len(self._models))
        return batcher

    def load_model(self, name: str, payload: dict) -> DynamicBatcher:
        """Registry ``:load`` verb — build an engine from an exported
        artifact described by the JSON payload."""
        if not isinstance(payload, dict) or "prefix" not in payload:
            raise ValueError(':load needs {"prefix": ..., "epoch": 0}')
        engine = InferenceEngine.from_export(
            str(payload["prefix"]), int(payload.get("epoch", 0)),
            input_names=payload.get("input_names", ("data",)),
            input_specs=payload.get("input_specs"),
            max_batch_size=payload.get("max_batch_size"),
            buckets=payload.get("buckets"), name=name)
        return self.add_model(name, engine,
                              warmup=bool(payload.get("warmup", False)))

    def remove_model(self, name: str) -> None:
        """Drain the model's batcher and drop it from the registry."""
        with self._lock:
            batcher = self._models.pop(name)   # KeyError → HTTP 404
            _m.MODELS_LOADED.set(len(self._models))
        batcher.close(drain=True)

    def get_model(self, name: str) -> DynamicBatcher:
        return self._models[name]

    def models(self):
        return list(self._models)

    def model_stats(self) -> dict:
        return {n: b.stats() for n, b in sorted(self._models.items())}

    # -- inference ------------------------------------------------------
    def predict_json(self, name: str, payload: dict) -> dict:
        """Decode JSON tensors, run them through the model's batcher,
        re-encode the per-request outputs."""
        batcher = self._models[name]            # KeyError → HTTP 404
        inputs = payload.get("inputs", payload) \
            if isinstance(payload, dict) else payload
        if isinstance(inputs, dict):
            names = batcher.engine.input_names
            missing = [n for n in names if n not in inputs]
            if missing:
                raise ValueError(f"missing inputs {missing}; "
                                 f"{name!r} takes {names}")
            inputs = [inputs[n] for n in names]
        if not isinstance(inputs, (list, tuple)) or not inputs:
            raise ValueError('"inputs" must be a non-empty list of '
                             "tensors or a {name: tensor} object")
        arrays = [_np.asarray(v, dtype=_np.float32) for v in inputs]
        for a in arrays:
            if a.ndim == 0:
                raise ValueError("each input needs a leading batch dim")
        outs = batcher.submit(arrays)
        outs = [_np.asarray(o) for o in outs]
        return {"outputs": [o.tolist() for o in outs],
                "shapes": [list(o.shape) for o in outs]}

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "ModelServer":
        """Bind and serve in daemon threads; returns self.  ``port=0``
        binds an ephemeral port (see :attr:`port`)."""
        if self._http is not None:
            return self
        srv = start_http_server(_Handler, self._port, self._host,
                                name="mxtpu-serve-http",
                                server_cls=_ServingHTTPServer)
        srv.model_server = self
        self._http = srv
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the HTTP front-end, then close every batcher
        (``drain=True`` finishes queued work first)."""
        stop_http_server(self._http)
        self._http = None
        with self._lock:
            batchers = list(self._models.values())
            self._models.clear()
            _m.MODELS_LOADED.set(0)
        for b in batchers:
            b.close(drain=drain)

    @property
    def port(self) -> Optional[int]:
        """The bound port once :meth:`start` has run."""
        return self._http.server_address[1] if self._http else self._port

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
