"""Serving metrics — registered on the SHARED telemetry registry at
import, so they ride every existing exporter (``/metrics`` Prometheus
scrape via ``telemetry_http``/the serving server, ``telemetry.snapshot``
JSON, ``mxtpu-stats``, profiler counter tracks) with no extra wiring.

Counters/gauges are labeled by ``model`` so a multi-model server stays
legible on one scrape; histograms are registry-wide (bounded reservoir,
p50/p95/max in the summary exposition).
"""
from __future__ import annotations

from .. import telemetry as _telemetry

# counters -----------------------------------------------------------------
REQUESTS = _telemetry.registry.counter(
    "mxtpu_serve_requests",
    "inference requests accepted into a DynamicBatcher queue")
BATCHES = _telemetry.registry.counter(
    "mxtpu_serve_batches",
    "coalesced batch dispatches (one compiled forward per batch)")
REJECTED = _telemetry.registry.counter(
    "mxtpu_serve_rejected",
    "requests rejected with QueueFullError (backpressure)")
FALLBACKS = _telemetry.registry.counter(
    "mxtpu_serve_fallbacks",
    "batched dispatches that failed after retries and fell back to "
    "single-request execution")
DEADLINE_EXCEEDED = _telemetry.registry.counter(
    "mxtpu_serve_deadline_exceeded",
    "requests shed because their end-to-end deadline expired "
    "(stage=admission|queue|wait|decode)")
GENERATE_TOKENS = _telemetry.registry.counter(
    "mxtpu_generate_tokens",
    "tokens emitted by the continuous-batching generation path")
CANCELLED = _telemetry.registry.counter(
    "mxtpu_serve_cancelled",
    "generation requests cancelled mid-decode (client disconnect); the "
    "slot frees on the next step boundary")
WATCHDOG_RESTARTS = _telemetry.registry.counter(
    "mxtpu_serve_watchdog_restarts",
    "batcher workers restarted by the serving watchdog (dead or hung)")
BREAKER_TRIPS = _telemetry.registry.counter(
    "mxtpu_serve_breaker_trips",
    "per-model circuit breaker CLOSED/HALF_OPEN -> OPEN transitions")
SLO_BAD = _telemetry.registry.counter(
    "mxtpu_slo_bad_requests",
    "requests that burned error budget (any failure surfaced to the "
    "caller: backpressure, breaker, deadline, abort, dispatch error)")
PREFIX_CACHE_HITS = _telemetry.registry.counter(
    "mxtpu_prefix_cache_hits",
    "KV blocks reused from the prefix cache instead of being "
    "re-prefilled (one increment per shared block)")
PREFIX_CACHE_EVICTIONS = _telemetry.registry.counter(
    "mxtpu_prefix_cache_evictions",
    "idle cached KV blocks evicted (LRU) to satisfy new allocations")
SPEC_DISPATCHES = _telemetry.registry.counter(
    "mxtpu_spec_verify_dispatches",
    "speculative-decoding verify dispatches (one k+1-wide target "
    "forward scoring all drafted positions at once)")
SPEC_DRAFT_TOKENS = _telemetry.registry.counter(
    "mxtpu_spec_draft_tokens",
    "tokens proposed by the draft model, per target model")
SPEC_ACCEPTED_TOKENS = _telemetry.registry.counter(
    "mxtpu_spec_accepted_tokens",
    "drafted tokens the target model accepted and emitted (excludes "
    "the guaranteed bonus token per dispatch)")
NONFINITE_GENERATIONS = _telemetry.registry.counter(
    "mxtpu_health_nonfinite_generations",
    "decode steps whose logits contained a non-finite value for at "
    "least one live slot (health plane, MXNET_HEALTH_PLANE=1)")

# sampling plane (serving/sampling.py; docs/serving.md "Sampling") ----------
SAMPLED_REQUESTS = _telemetry.registry.counter(
    "mxtpu_sample_requests",
    "generation requests admitted, by mode=greedy|sampled "
    "(sampled: temperature > 0)")
SAMPLE_TOKENS = _telemetry.registry.counter(
    "mxtpu_sample_tokens",
    "tokens emitted by stochastically sampled (temperature > 0) "
    "requests, per model")
SAMPLE_CONSTRAINED = _telemetry.registry.counter(
    "mxtpu_sample_constrained_requests",
    "generation requests decoded under a constrained-output grammar "
    "mask (json_mode), per model")
SAMPLE_STOP_HITS = _telemetry.registry.counter(
    "mxtpu_sample_stop_hits",
    "generation requests finished by a multi-token stop sequence at "
    "an emit boundary, per model")
SAMPLE_STOP_TRIMMED = _telemetry.registry.counter(
    "mxtpu_sample_stop_trimmed_tokens",
    "over-generated burst-tail tokens discarded host-side past a stop "
    "sequence (their K/V writes were already null-block-redirected)")

# router (serving/router.py; labeled by replica where it matters) ----------
ROUTER_REQUESTS = _telemetry.registry.counter(
    "mxtpu_router_requests",
    "client requests accepted by the mxtpu-router front tier")
ROUTER_RETRIES = _telemetry.registry.counter(
    "mxtpu_router_retries",
    "upstream attempts beyond the first (connect error / 503 / 429 "
    "re-routed under the per-request retry budget)")
ROUTER_FAILOVERS = _telemetry.registry.counter(
    "mxtpu_router_failovers",
    "requests that ultimately succeeded on a different replica than "
    "the first one tried")
ROUTER_EJECTIONS = _telemetry.registry.counter(
    "mxtpu_router_ejections",
    "replica ejections (health-loop breaker CLOSED/HALF_OPEN -> OPEN)")
ROUTER_AFFINITY = _telemetry.registry.counter(
    "mxtpu_router_affinity_routed",
    "generation requests routed to their rendezvous-hash prefix owner")
ROUTER_SPILLS = _telemetry.registry.counter(
    "mxtpu_router_spills",
    "generation requests spilled off their prefix owner because it was "
    "overloaded, draining, or ejected")
ROUTER_STREAM_ERRORS = _telemetry.registry.counter(
    "mxtpu_router_stream_errors",
    "streams terminated with an SSE error event after a mid-stream "
    "replica death (tokens already on the wire - no silent failover)")
ROUTER_REPLICA_STATE = _telemetry.registry.gauge(
    "mxtpu_router_replica_state",
    "per-replica router view (0 READY, 1 UNREADY, 2 DRAINING, "
    "3 EJECTED, 4 DOWN)")
ROUTER_REPLICAS_ELIGIBLE = _telemetry.registry.gauge(
    "mxtpu_router_replicas_eligible",
    "replicas currently eligible for new work")
ROUTER_INFLIGHT = _telemetry.registry.gauge(
    "mxtpu_router_inflight",
    "client requests in flight through the router, per replica")
ROUTER_INCIDENTS = _telemetry.registry.counter(
    "mxtpu_router_incidents",
    "correlated incident bundles written (ejection / "
    "failover-exhaustion / drain-timeout), by reason")
ROUTER_FEDERATION_STALE = _telemetry.registry.gauge(
    "mxtpu_router_federation_stale",
    "replicas whose cached metrics snapshot has aged past the "
    "staleness horizon and is excluded from fleet totals")
ROUTER_TRACE_FANOUT = _telemetry.registry.counter(
    "mxtpu_router_trace_fanout",
    "replica /trace fetches made while stitching fleet traces")
ROUTER_MEMBERSHIP = _telemetry.registry.counter(
    "mxtpu_router_membership_changes",
    "fleet membership changes (POST/DELETE /admin/replicas), by "
    "action=join|leave")

# supervisor + autoscaler (serving/supervisor.py; control-plane series,
# rendered once on the router /metrics — docs/observability.md) -----------
SUPERVISE_SPAWNS = _telemetry.registry.counter(
    "mxtpu_supervise_spawns",
    "replica processes spawned by mxtpu-supervise (first launches and "
    "restarts alike)")
SUPERVISE_RESTARTS = _telemetry.registry.counter(
    "mxtpu_supervise_restarts",
    "replica restarts after a detected crash or hang (exit, /healthz "
    "timeout), per replica slot")
SUPERVISE_QUARANTINES = _telemetry.registry.counter(
    "mxtpu_supervise_quarantines",
    "replica slots quarantined by the flap breaker "
    "(MXNET_SUPERVISE_MAX_RESTARTS within the window)")
SUPERVISE_REPLICAS = _telemetry.registry.gauge(
    "mxtpu_supervise_replicas",
    "supervised replica processes currently alive")
AUTOSCALE_EVENTS = _telemetry.registry.counter(
    "mxtpu_autoscale_events",
    "executed scale actions, by action=up|down (scale-down always "
    "routes through /admin/drain)")
AUTOSCALE_DECISIONS = _telemetry.registry.counter(
    "mxtpu_autoscale_decisions",
    "autoscale policy evaluations, by action=up|down|hold")
AUTOSCALE_TARGET = _telemetry.registry.gauge(
    "mxtpu_autoscale_target_replicas",
    "fleet size the autoscaler is currently steering toward")
AUTOSCALE_BURN = _telemetry.registry.gauge(
    "mxtpu_autoscale_burn_rate",
    "worst-model fleet SLO burn rate the last policy evaluation saw")
AUTOSCALE_QUEUE = _telemetry.registry.gauge(
    "mxtpu_autoscale_queue_depth",
    "fleet-summed serve queue depth the last policy evaluation saw")
AUTOSCALE_KV = _telemetry.registry.gauge(
    "mxtpu_autoscale_kv_utilization",
    "worst-replica KV-cache utilization the last policy evaluation saw")

# histograms ---------------------------------------------------------------
BATCH_SIZE = _telemetry.registry.histogram(
    "mxtpu_serve_batch_size",
    "rows per coalesced dispatch (before bucket padding)")
QUEUE_WAIT = _telemetry.registry.histogram(
    "mxtpu_serve_queue_wait_seconds",
    "seconds a request waited in the queue before its batch dispatched")
LATENCY = _telemetry.registry.histogram(
    "mxtpu_serve_latency_seconds",
    "end-to-end seconds from submit to scattered result")
TOKEN_LATENCY = _telemetry.registry.histogram(
    "mxtpu_generate_token_seconds",
    "seconds between consecutive emitted tokens of one generation "
    "request (first sample: submit -> first token)")
DECODE_STEP = _telemetry.registry.histogram(
    "mxtpu_generate_decode_step_seconds",
    "seconds per continuous-batching decode dispatch (all live slots "
    "advance one token)")
DECODE_BURST_TOKENS = _telemetry.registry.histogram(
    "mxtpu_decode_burst_tokens",
    "tokens emitted per scanned decode-burst dispatch, summed across "
    "live slots (ceiling is scan_steps x slots; a thin tail means "
    "in-program termination is cutting bursts short)")
SPEC_STEP = _telemetry.registry.histogram(
    "mxtpu_spec_step_seconds",
    "seconds per speculative step (k draft dispatches plus one verify; "
    "compare with mxtpu_generate_decode_step_seconds for the draft "
    "overhead per accepted-token burst)")
ROUTER_UPSTREAM = _telemetry.registry.histogram(
    "mxtpu_router_upstream_seconds",
    "seconds per upstream attempt (router -> replica), successful or "
    "not")

# gauges -------------------------------------------------------------------
QUEUE_DEPTH = _telemetry.registry.gauge(
    "mxtpu_serve_queue_depth",
    "requests currently queued, per model")
SLOTS_IN_USE = _telemetry.registry.gauge(
    "mxtpu_serve_cache_slots_in_use",
    "KV-cache slots occupied by live generation requests, per model")
KV_BLOCKS_TOTAL = _telemetry.registry.gauge(
    "mxtpu_kv_blocks_total",
    "allocatable KV-cache blocks in the paged BlockPool, per model")
KV_BLOCKS_IN_USE = _telemetry.registry.gauge(
    "mxtpu_kv_blocks_in_use",
    "KV-cache blocks held by live slots or pinned in the prefix "
    "cache with a nonzero refcount, per model")
MODELS_LOADED = _telemetry.registry.gauge(
    "mxtpu_serve_models_loaded",
    "models registered on the ModelServer")
BREAKER_STATE = _telemetry.registry.gauge(
    "mxtpu_serve_breaker_state",
    "per-model circuit breaker state (0 CLOSED, 1 HALF_OPEN, 2 OPEN)")
MODEL_STATE = _telemetry.registry.gauge(
    "mxtpu_serve_model_state",
    "per-model serving state (0 SERVING, 1 STARTING, 2 DEGRADED, "
    "3 UNHEALTHY, 4 DRAINING)")
SPEC_TOKENS_PER_DISPATCH = _telemetry.registry.gauge(
    "mxtpu_spec_accepted_tokens_per_dispatch",
    "tokens emitted per verify dispatch, cumulative per model "
    "(1.0 would mean the draft never helps; k+1 is the ceiling)")
SPEC_ACCEPT_RATE = _telemetry.registry.gauge(
    "mxtpu_spec_accept_rate",
    "fraction of drafted tokens the target accepted, cumulative per "
    "model and by mode=greedy|sampled (sampled: any live slot decoding "
    "at temperature > 0; tune MXNET_SPEC_K down when this drops)")
HEALTH_LOGIT_MAX = _telemetry.registry.gauge(
    "mxtpu_health_logit_max",
    "max final-position logit across live slots in the most recent "
    "decode dispatch (health plane; drifting up signals divergence)")
HEALTH_DECODE_ENTROPY = _telemetry.registry.gauge(
    "mxtpu_health_decode_entropy",
    "mean final-position softmax entropy (nats) across live slots in "
    "the most recent decode dispatch (health plane; near-zero = "
    "degenerate repetition, near log(vocab) = noise)")
DISPATCHES_PER_TOKEN = _telemetry.registry.gauge(
    "mxtpu_dispatches_per_token",
    "target-model dispatches per emitted token, cumulative per model "
    "(per-slot normalized: exactly 1.0 for per-step decode, <= "
    "1/scan_steps at steady state on the scanned burst path, and "
    "1/(accepted burst) when speculation amortizes the verify "
    "dispatch)")

# SLO plane (serving/slo.py; docs/observability.md) -------------------------
SLO_AVAILABILITY = _telemetry.registry.gauge(
    "mxtpu_slo_availability",
    "rolling-window availability SLI, per model")
SLO_P99 = _telemetry.registry.gauge(
    "mxtpu_slo_p99_seconds",
    "rolling-window p99 end-to-end latency SLI, per model")
SLO_BURN = _telemetry.registry.gauge(
    "mxtpu_slo_burn_rate",
    "error-budget burn rate (1.0 = spending exactly the budget the "
    "objective allows), per model")
SLO_BUDGET = _telemetry.registry.gauge(
    "mxtpu_slo_error_budget_remaining",
    "fraction of the error budget left in the rolling window "
    "(0 = exhausted -> readiness blocker), per model")
