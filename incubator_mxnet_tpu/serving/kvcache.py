"""Paged KV-cache block pool with prefix sharing.

The :class:`BlockPool` is the host-side allocator behind the paged
``GenerationEngine``: device KV storage is carved into fixed-size blocks
of ``block_size`` token positions, and each live slot holds an ordered
*block table* (a list of block ids) instead of a dense ``max_len`` strip.
Three properties fall out:

* **Fragmentation-free packing** — a request reserves only
  ``ceil((prompt + budget) / block_size)`` blocks, so short streams no
  longer pay for ``max_len`` worth of cache and many more of them fit in
  the same byte budget.
* **Prefix sharing** — every *full* block of a prompt is keyed by a
  chained blake2b digest (digest of the previous block's digest plus
  this block's tokens), so two requests with a common prefix map their
  leading blocks to the same physical storage. A digest match implies
  token-exact prefix equality — keys are 128-bit content digests, not
  Python ``hash()`` values, so distinct prompts cannot alias. Shared
  blocks are refcounted; the joiner skips prefill for the shared span
  entirely.
* **Copy-on-write** — a writer that needs to mutate a block with
  refcount > 1 asks :meth:`copy_on_write` for a private copy first. The
  serving flow never mutates shared blocks by construction (only *full*,
  immutable prompt blocks are ever registered for sharing), but the COW
  primitive is part of the pool contract and unit-tested so future
  writers (e.g. speculative-decode rollback) inherit it.

Block id 0 is the reserved **null block**: block tables are padded with
it and out-of-range scatter positions are redirected to it, so garbage
writes from padded prefill rows land in a sink nobody ever attends to.

**Burst write contract** (``GenerationEngine.decode_burst``): the
scanned multi-token decode advances a slot at most ``budget`` positions
past its current length, and every admit reserves
``blocks_for(prompt + budget)`` up front — so the burst's furthest KV
write (position ``prompt + budget - 1`` at the worst case) always lands
inside the slot's reserved table and **no extra headroom is needed for
any scan_steps**. Slots that finish mid-burst have their remaining
in-scan writes redirected to the null block, the same sink padded
prefill rows use.

Eviction: a cached block whose refcount drops to 0 is *not* returned to
the free list — it stays in the prefix cache, instantly reusable by the
next request with the same prefix, and is only reclaimed (LRU) when the
free list runs dry. ``mxtpu_prefix_cache_evictions`` counts reclaims.

All methods take an internal lock; the pool is shared between the
batcher worker thread and HTTP admission checks.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError
from . import metrics as _m

__all__ = ["BlockPool", "blocks_for", "NULL_BLOCK"]

NULL_BLOCK = 0


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` positions."""
    return max(0, -(-int(tokens) // int(block_size)))


class BlockPool:
    """Refcounted allocator over ``num_blocks`` fixed-size KV blocks.

    ``num_blocks`` includes the reserved null block, so ``num_blocks - 1``
    blocks are allocatable. ``prefix_cache=False`` disables sharing (every
    allocation takes fresh blocks) but keeps the same accounting.
    """

    def __init__(self, num_blocks: int, block_size: int, *,
                 prefix_cache: bool = True, model: str = "?"):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (null block + 1), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.prefix_cache = bool(prefix_cache)
        self._model = model
        # device bytes behind one block (set by the owning engine once
        # its cache arrays exist) — lets stats() speak bytes, the unit
        # the device-memory plane attributes in (telemetry_device)
        self.block_bytes = 0
        self._lock = threading.RLock()
        self.hits = 0            # blocks reused from the prefix cache
        self.evictions = 0       # idle cached blocks reclaimed (LRU)
        self.cow_copies = 0      # copy_on_write calls that actually copied
        self.rewinds = 0         # rewind() calls that had work to do
        self.reset()

    # -- state ------------------------------------------------------------
    def reset(self) -> None:
        """Drop every allocation AND the prefix cache (weight update /
        watchdog restart: cached K/V no longer matches the params)."""
        with self._lock:
            self._ref = [0] * self.num_blocks
            self._free: deque = deque(range(1, self.num_blocks))
            self._hash: List[Optional[bytes]] = [None] * self.num_blocks
            self._by_hash: Dict[bytes, int] = {}
            # cached blocks with refcount 0, in LRU order (oldest first)
            self._idle: "OrderedDict[int, None]" = OrderedDict()
            self._update_gauges()

    @property
    def free_blocks(self) -> int:
        """Blocks available to a new allocation (truly free + evictable)."""
        with self._lock:
            return len(self._free) + len(self._idle)

    @property
    def blocks_in_use(self) -> int:
        """Blocks pinned by a nonzero refcount."""
        with self._lock:
            return (self.num_blocks - 1) - len(self._free) - len(self._idle)

    @property
    def cached_blocks(self) -> int:
        """Blocks currently registered in the prefix cache (any refcount)."""
        with self._lock:
            return len(self._by_hash)

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref[block]

    def _update_gauges(self) -> None:
        _m.KV_BLOCKS_TOTAL.set(self.num_blocks - 1, model=self._model)
        _m.KV_BLOCKS_IN_USE.set(
            (self.num_blocks - 1) - len(self._free) - len(self._idle),
            model=self._model)

    # -- prefix hashing ---------------------------------------------------
    def chain_hashes(self, tokens: Sequence[int], limit: int) -> List[bytes]:
        """Chained blake2b digest per full block over ``tokens[:limit]``.

        ``hashes[i]`` commits to blocks ``0..i`` of the prompt, so a
        digest match implies the whole prefix matches, not just one
        block. 128-bit content digests make accidental aliasing of
        distinct prompts cryptographically impossible — unlike Python
        ``hash()``, where e.g. ``hash(-1) == hash(-2)`` collides.
        """
        bs = self.block_size
        out: List[bytes] = []
        h = ("mxtpu-kv:%d" % bs).encode()
        for i in range(int(limit) // bs):
            blk = b",".join(b"%d" % int(t)
                            for t in tokens[i * bs:(i + 1) * bs])
            h = hashlib.blake2b(h + b"|" + blk, digest_size=16).digest()
            out.append(h)
        return out

    def _match(self, hashes: Sequence[bytes], usable: int) -> List[int]:
        """Longest cached run of leading blocks, without increfing."""
        if not self.prefix_cache:
            return []
        shared: List[int] = []
        for i in range(min(usable, len(hashes))):
            b = self._by_hash.get(hashes[i])
            if b is None:
                break
            shared.append(b)
        return shared

    @staticmethod
    def _usable_prefix_blocks(n: int, block_size: int) -> int:
        # At least one prompt token must stay outside the shared span so
        # the suffix prefill has a row to read the first logits from.
        return max(0, (int(n) - 1) // block_size)

    # -- allocation -------------------------------------------------------
    def can_admit(self, tokens: Sequence[int], n: int, reserve_tokens: int,
                  reserved_blocks: int = 0) -> bool:
        """Would :meth:`allocate` succeed right now? ``reserved_blocks``
        discounts capacity already promised to earlier admits in the same
        scheduling step."""
        with self._lock:
            need = blocks_for(reserve_tokens, self.block_size)
            hashes = self.chain_hashes(tokens, (int(n) // self.block_size)
                                       * self.block_size)
            shared = self._match(
                hashes, self._usable_prefix_blocks(n, self.block_size))
            free = len(self._free) + len(self._idle) - int(reserved_blocks)
            # Idle blocks this request would share are pinned by the
            # share itself — they cannot double as reclaimable capacity
            # for the fresh tail.
            shared_idle = sum(1 for b in shared if self._ref[b] == 0)
            return free - shared_idle >= need - len(shared)

    def allocate(self, tokens: Sequence[int], n: int, reserve_tokens: int,
                 share: bool = True) -> Tuple[List[int], int]:
        """Reserve blocks for a request with prompt ``tokens[:n]`` and a
        worst-case total of ``reserve_tokens`` positions.

        Returns ``(table, shared_tokens)``: the ordered block table (length
        ``ceil(reserve_tokens / block_size)``) and how many leading token
        positions already hold valid K/V from the prefix cache (always a
        multiple of ``block_size``). Raises :class:`MXNetError` when the
        pool cannot satisfy the reservation. ``share=False`` skips both
        prefix matching and registration (warmup traffic must not poison
        the cache).
        """
        n = int(n)
        need = blocks_for(reserve_tokens, self.block_size)
        if need < 1:
            raise ValueError(f"reserve_tokens must be >= 1, got {reserve_tokens}")
        with self._lock:
            full = (n // self.block_size) * self.block_size
            hashes = self.chain_hashes(tokens, full) if share else []
            shared = self._match(
                hashes, self._usable_prefix_blocks(n, self.block_size))
            fresh_needed = need - len(shared)
            # Full capacity check BEFORE any mutation: idle blocks this
            # request shares are pinned by the share, so they must not
            # count toward the fresh tail — otherwise the shortfall
            # would only surface in _pop_free after refcounts were
            # already bumped, leaking the partial allocation.
            shared_idle = sum(1 for b in shared if self._ref[b] == 0)
            available = len(self._free) + len(self._idle) - shared_idle
            if available < fresh_needed:
                raise MXNetError(
                    f"kv pool exhausted: need {fresh_needed} blocks, "
                    f"{available} available "
                    f"({self.num_blocks - 1} total, block_size "
                    f"{self.block_size})")
            for b in shared:
                self._incref(b)
            table = list(shared)
            for _ in range(fresh_needed):
                b = self._pop_free()
                self._ref[b] = 1
                table.append(b)
            # Register this prompt's remaining full blocks so later
            # requests with the same prefix share them. The worker
            # prefills immediately after allocate() (same thread), so the
            # registered blocks hold valid K/V before any later lookup.
            if self.prefix_cache and share:
                for i in range(len(shared), len(hashes)):
                    if hashes[i] not in self._by_hash:
                        self._by_hash[hashes[i]] = table[i]
                        self._hash[table[i]] = hashes[i]
            if shared:
                self.hits += len(shared)
                _m.PREFIX_CACHE_HITS.inc(len(shared), model=self._model)
            self._update_gauges()
            return table, len(shared) * self.block_size

    def release(self, table: Sequence[int]) -> None:
        """Decref every block in ``table``. Blocks reaching refcount 0
        return to the free list, unless cached — those stay evictable in
        LRU order for future prefix hits."""
        with self._lock:
            for b in table:
                if b == NULL_BLOCK:
                    continue
                if self._ref[b] <= 0:
                    raise MXNetError(f"double free of kv block {b}")
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    if self._hash[b] is not None:
                        self._idle[b] = None
                        self._idle.move_to_end(b)
                    else:
                        self._free.append(b)
            self._update_gauges()

    def invalidate(self, blocks: Sequence[int]) -> None:
        """Unregister ``blocks`` from the prefix cache without touching
        refcounts. For blocks whose K/V never became valid — a prefill
        that failed after :meth:`allocate` had already registered them —
        so a later request with the same prefix prefills cold instead of
        "hitting" garbage. Unregistered blocks are a no-op."""
        with self._lock:
            for b in blocks:
                if b != NULL_BLOCK:
                    self._evict_hash(b)

    def copy_on_write(self, block: int) -> int:
        """Private handle for a block the caller wants to mutate. Returns
        ``block`` unchanged when exclusively owned; otherwise decrefs it,
        allocates a fresh block (refcount 1), and returns the new id — the
        caller must copy the device contents before writing."""
        with self._lock:
            if self._ref[block] <= 0:
                raise MXNetError(f"copy_on_write of unreferenced block {block}")
            if self._ref[block] == 1 and self._hash[block] is None:
                return block
            if self._ref[block] == 1:
                # Exclusively owned but published in the prefix cache:
                # unpublish instead of copying — readers arriving later
                # simply miss.
                self._evict_hash(block)
                return block
            if not self._free and not self._idle:
                raise MXNetError("kv pool exhausted during copy_on_write")
            self._ref[block] -= 1
            new = self._pop_free()
            self._ref[new] = 1
            self.cow_copies += 1
            self._update_gauges()
            return new

    def rewind(self, table: Sequence[int], keep_tokens: int) -> List[int]:
        """Prepare ``table`` for overwriting every position
        ``>= keep_tokens`` (speculative-decode rollback: rejected draft
        positions will be re-written by the next dispatch).

        No block is ever freed — the reservation stays intact, and blocks
        holding only kept positions (the shared prefix among them) are
        untouched.  Blocks in the dirty span that are shared (refcount
        > 1) or published in the prefix cache get :meth:`copy_on_write`
        treatment so the overwrite cannot corrupt a neighbor's view;
        the returned table carries any replacement ids.

        The serving flow only ever writes past the prompt, and only full
        immutable prompt blocks are shared/published, so the COW branch
        is a contract guard rather than a hot path.  A shared block that
        also holds kept positions cannot be rolled back on the host alone
        (the private copy would lose the kept K/V) — that state is
        unreachable through the engine and raises.
        """
        keep_tokens = max(0, int(keep_tokens))
        bs = self.block_size
        with self._lock:
            out = list(table)
            first = keep_tokens // bs   # first block with a dirty position
            touched = False
            for i in range(first, len(out)):
                b = out[i]
                if b == NULL_BLOCK:
                    continue
                if self._ref[b] <= 1 and self._hash[b] is None:
                    continue
                if i * bs < keep_tokens:
                    raise MXNetError(
                        f"rewind would copy-on-write block {b} holding "
                        f"kept positions (keep={keep_tokens}); decode "
                        f"writes must never land in shared prompt blocks")
                out[i] = self.copy_on_write(b)
                touched = True
            if touched:
                self.rewinds += 1
                self._update_gauges()
            return out

    # -- internals --------------------------------------------------------
    def _incref(self, b: int) -> None:
        self._ref[b] += 1
        if self._ref[b] == 1:
            self._idle.pop(b, None)

    def _pop_free(self) -> int:
        if self._free:
            return self._free.popleft()
        if self._idle:
            b, _ = self._idle.popitem(last=False)  # LRU: oldest idle first
            self._evict_hash(b)
            self.evictions += 1
            _m.PREFIX_CACHE_EVICTIONS.inc(model=self._model)
            return b
        raise MXNetError("kv pool exhausted")

    def _evict_hash(self, b: int) -> None:
        h = self._hash[b]
        if h is not None and self._by_hash.get(h) == b:
            del self._by_hash[h]
        self._hash[b] = None

    def stats(self) -> Dict[str, object]:
        with self._lock:
            total = self.num_blocks - 1
            in_use = total - len(self._free) - len(self._idle)
            out = {
                "kv_block_size": self.block_size,
                "kv_blocks_total": total,
                "kv_blocks_in_use": in_use,
                "kv_blocks_cached_idle": len(self._idle),
                "kv_utilization": (in_use / total) if total else 0.0,
                "prefix_cache": self.prefix_cache,
                "prefix_cache_hits": self.hits,
                "prefix_cache_evictions": self.evictions,
                "rewinds": self.rewinds,
            }
            if self.block_bytes:
                out["kv_bytes_total"] = total * self.block_bytes
                out["kv_bytes_in_use"] = in_use * self.block_bytes
            return out
