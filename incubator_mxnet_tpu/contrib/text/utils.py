"""Text utilities (reference: python/mxnet/contrib/text/utils.py)."""
from __future__ import annotations

import re
from collections import Counter

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency Counter from a delimited string (reference:
    utils.count_tokens_from_str)."""
    source_str = re.split(f"{token_delim}|{seq_delim}", source_str)
    tokens = [t for t in source_str if t]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    counter = (counter_to_update if counter_to_update is not None
               else Counter())
    counter.update(tokens)
    return counter
