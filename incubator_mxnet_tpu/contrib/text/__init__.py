"""``mx.contrib.text`` — text vocabulary + token-embedding utilities
(reference: python/mxnet/contrib/text/{vocab,embedding,utils}.py)."""
from . import utils
from . import vocab
from . import embedding
from .vocab import Vocabulary
from .embedding import TokenEmbedding, CustomEmbedding, CompositeEmbedding

__all__ = ["utils", "vocab", "embedding", "Vocabulary", "TokenEmbedding",
           "CustomEmbedding", "CompositeEmbedding"]
