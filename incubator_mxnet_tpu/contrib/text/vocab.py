"""Vocabulary (reference: python/mxnet/contrib/text/vocab.py
Vocabulary — frequency-ordered indexing with reserved tokens and an
unknown-token slot at index 0)."""
from __future__ import annotations

from collections import Counter
from typing import List, Optional

from ...base import MXNetError

__all__ = ["Vocabulary"]


class Vocabulary:
    """Indexes tokens by frequency (reference semantics: index 0 is the
    unknown token; then reserved tokens; then corpus tokens sorted by
    descending frequency, ties broken alphabetically)."""

    def __init__(self, counter: Optional[Counter] = None,
                 most_freq_count: Optional[int] = None, min_freq: int = 1,
                 unknown_token: str = "<unk>",
                 reserved_tokens: Optional[List[str]] = None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown_token must not be reserved")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved_tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens or None
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t
                              in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq or tok in self._token_to_idx:
                    continue
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) → index(es); unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError(f"index {i} out of vocabulary range")
            out.append(self._idx_to_token[i])
        return out[0] if single else out
