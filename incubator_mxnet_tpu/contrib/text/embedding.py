"""Token embeddings (reference: python/mxnet/contrib/text/embedding.py —
TokenEmbedding base + GloVe/FastText loaders + CustomEmbedding +
CompositeEmbedding).

Zero-egress environment note: the reference's pretrained downloads cannot
run here; loaders read the same text format (``token v1 v2 ... vD`` per
line) from LOCAL files via ``CustomEmbedding`` / ``from_file``.  The
vector store is a numpy matrix on host — lookup results are NDArrays, so
they enter the device path only when used.
"""
from __future__ import annotations

import io
import os
from typing import List, Optional

import numpy as _np

from ...base import MXNetError
from ...ndarray import ndarray as _ndmod
from .vocab import Vocabulary

__all__ = ["TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "get_pretrained_file_names"]


def get_pretrained_file_names(embedding_name=None):
    """reference: embedding.get_pretrained_file_names.  Downloads are
    unavailable in this environment — documented, not silently empty."""
    raise MXNetError(
        "pretrained embedding downloads are unavailable (zero-egress "
        "environment); load a local file with "
        "CustomEmbedding(pretrained_file_path=...)")


class TokenEmbedding:
    """Indexed token→vector store (reference: embedding.TokenEmbedding).

    idx 0 is the unknown token, initialized by ``init_unknown_vec``
    (zeros by default, matching the reference)."""

    def __init__(self, unknown_token="<unk>", init_unknown_vec=None):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec or _np.zeros
        self._idx_to_token: List[str] = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec: Optional[_np.ndarray] = None

    # ------------------------------------------------------------------
    def _load_embedding_txt(self, path, elem_delim=" ", encoding="utf8"):
        if not os.path.isfile(path):
            raise MXNetError(f"embedding file not found: {path}")
        vecs = []
        dim = None
        with io.open(path, "r", encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue   # blank/malformed line
                if lineno == 0 and len(parts) == 2 and \
                        all(p.isdigit() for p in parts):
                    continue   # fastText-style "N D" header
                token, elems = parts[0], parts[1:]
                try:
                    vec = _np.asarray([float(e) for e in elems],
                                      _np.float32)
                except ValueError:
                    continue
                if dim is None:
                    dim = len(vec)
                elif len(vec) != dim:
                    raise MXNetError(
                        f"inconsistent embedding dim at line {lineno} "
                        f"of {path}: {len(vec)} vs {dim}")
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vecs.append(vec)
        if dim is None:
            raise MXNetError(f"no vectors parsed from {path}")
        unk = self._init_unknown_vec((dim,)).astype(_np.float32)
        self._idx_to_vec = _np.vstack([unk[None, :]] + [v[None, :]
                                                        for v in vecs])

    # ------------------------------------------------------------------
    @property
    def vec_len(self) -> int:
        self._check_loaded()
        return self._idx_to_vec.shape[1]

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def idx_to_vec(self):
        self._check_loaded()
        return _ndmod.array(self._idx_to_vec)

    def __len__(self):
        return len(self._idx_to_token)

    def _check_loaded(self):
        if self._idx_to_vec is None:
            raise MXNetError("embedding vectors not loaded")

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Token(s) → vector(s); unknown tokens get the unk vector
        (reference: TokenEmbedding.get_vecs_by_tokens)."""
        self._check_loaded()
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idxs.append(0 if i is None else i)
        mat = self._idx_to_vec[_np.asarray(idxs, _np.int64)]
        return _ndmod.array(mat[0] if single else mat)

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors for known tokens (reference:
        update_token_vectors)."""
        self._check_loaded()
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        new = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else _np.asarray(new_vectors, _np.float32)
        if single:
            new = new.reshape(1, -1)
        if new.shape != (len(toks), self.vec_len):
            raise MXNetError(
                f"new_vectors shape {new.shape} != "
                f"({len(toks)}, {self.vec_len})")
        for t, v in zip(toks, new):
            if t not in self._token_to_idx:
                raise MXNetError(f"token {t!r} is not in the embedding")
            self._idx_to_vec[self._token_to_idx[t]] = v


class CustomEmbedding(TokenEmbedding):
    """Load embeddings from a local ``token v1 ... vD`` text file
    (reference: embedding.CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", vocabulary: Optional[Vocabulary] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_txt(pretrained_file_path, elem_delim,
                                 encoding)
        if vocabulary is not None:
            self._restrict_to_vocab(vocabulary)

    def _restrict_to_vocab(self, vocabulary: Vocabulary):
        """Re-index to a vocabulary's tokens (reference behavior when a
        vocabulary is supplied: indices follow the vocabulary)."""
        dim = self.vec_len
        vecs = _np.zeros((len(vocabulary), dim), _np.float32)
        for tok, i in vocabulary.token_to_idx.items():
            j = self._token_to_idx.get(tok)
            if j is not None:
                vecs[i] = self._idx_to_vec[j]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_vec = vecs


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (reference:
    embedding.CompositeEmbedding)."""

    def __init__(self, vocabulary: Vocabulary, token_embeddings):
        super().__init__(unknown_token=vocabulary.unknown_token)
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = []
        for emb in token_embeddings:
            emb._check_loaded()
            mat = _np.zeros((len(vocabulary), emb.vec_len), _np.float32)
            for tok, i in vocabulary.token_to_idx.items():
                j = emb._token_to_idx.get(tok)
                if j is not None:
                    mat[i] = emb._idx_to_vec[j]
            parts.append(mat)
        self._idx_to_vec = _np.concatenate(parts, axis=1)
