"""``mx.contrib.quantization`` — post-training INT8 quantization
(reference: python/mxnet/contrib/quantization.py quantize_model /
quantize_net; graph rewrite src/operator/quantization/
quantize_graph_pass.cc; calibration calibrate.py — the fork owner's
signature subsystem, built there on oneDNN INT8 kernels).

TPU-native re-design:

* Quantized compute lowers to int8 x int8 -> int32 ``lax.dot_general`` /
  ``lax.conv_general_dilated`` with ``preferred_element_type=int32`` —
  XLA maps these onto the MXU's native int8 path — followed by one fused
  rescale (the reference's requantize/dequantize pair collapses into a
  single fp multiplier since the output returns to fp32).
* Weights are quantized per-output-channel, activations per-tensor from
  calibration (reference: quantized_conv per-channel min/max).
* Calibration modes: 'naive' (min/max over the calibration set) and
  'entropy' (KL-optimal threshold over a 2048-bin histogram, reference:
  calibrate.py _LayerHistogramCollector + _get_optimal_threshold).
* The rewrite operates on Gluon blocks (``quantize_net``): Dense/Conv2D
  children are swapped for Quantized* equivalents in place.  The
  symbol-era ``quantize_model`` wraps the same machinery for
  (sym, arg_params, aux_params) inputs via SymbolBlock import/export.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _invoke
from ..gluon import nn as _gnn
from ..gluon.block import HybridBlock

__all__ = ["quantize_net", "quantize_model", "CalibrationCollector",
           "QuantizedDense", "QuantizedConv2D"]


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# quantization math
# ---------------------------------------------------------------------------
def _quantize_weight_per_channel(w: _np.ndarray):
    """int8 weight + per-output-channel fp32 scale (reference:
    quantized ops' channel-wise min/max).  w: (out_ch, ...)."""
    flat = _np.abs(w.reshape(w.shape[0], -1))
    absmax = _np.maximum(flat.max(axis=1), 1e-12)
    scale = (absmax / 127.0).astype(_np.float32)
    q = _np.clip(_np.round(w / scale.reshape((-1,) + (1,) * (w.ndim - 1))),
                 -127, 127).astype(_np.int8)
    return q, scale


def _entropy_threshold(hist: _np.ndarray, edges: _np.ndarray,
                       num_quantized_bins: int = 255) -> float:
    """KL-optimal |x| clipping threshold (reference: calibrate.py
    _get_optimal_threshold, the TensorRT-style entropy calibration)."""
    total = hist.sum()
    if total == 0:
        return float(edges[-1])
    best_kl, best_t = _np.inf, float(edges[-1])
    nbins = len(hist)
    # candidate thresholds: every bin edge beyond the quantized bin count
    for i in range(num_quantized_bins, nbins + 1, 8):
        base = hist[:i].astype(_np.float64)
        p = base.copy()
        p[i - 1] += hist[i:].sum()   # reference P: outliers clip to edge
        # candidate Q: the UNCLIPPED in-range mass quantized to
        # num_quantized_bins levels and expanded back — clipping error
        # then shows up as P-mass Q cannot express (TensorRT-style KL,
        # reference: calibrate.py _get_optimal_threshold)
        factor = i / num_quantized_bins
        idx = _np.minimum((_np.arange(i) / factor).astype(_np.int64),
                          num_quantized_bins - 1)
        q_small = _np.zeros(num_quantized_bins)
        _np.add.at(q_small, idx, base)
        counts = _np.zeros(num_quantized_bins)
        _np.add.at(counts, idx, (base > 0))
        ratio = _np.divide(q_small, _np.maximum(counts, 1))
        q = _np.where(base > 0, ratio[idx], 0.0)
        p_sum, q_sum = p.sum(), q.sum()
        if p_sum <= 0 or q_sum <= 0:
            continue
        eps = 1e-10   # smoothing so P-mass with zero Q is penalized
        pn = p / p_sum
        qn = _np.maximum(q / q_sum, eps)
        mask = pn > 0
        kl = float(_np.sum(pn[mask] * _np.log(pn[mask] / qn[mask])))
        if kl < best_kl:
            best_kl, best_t = kl, float(edges[i])
    return best_t


class CalibrationCollector:
    """Collects per-layer activation statistics during calibration
    forwards (reference: _LayerOutputMinMaxCollector /
    _LayerHistogramCollector)."""

    NBINS = 2048

    def __init__(self, mode="naive"):
        if mode not in ("naive", "entropy"):
            raise MXNetError(f"unknown calib_mode {mode!r}")
        self.mode = mode
        self.absmax: Dict[str, float] = {}
        self.hists: Dict[str, _np.ndarray] = {}

    def collect(self, name: str, arr: _np.ndarray):
        amax = float(_np.abs(arr).max()) if arr.size else 0.0
        self.absmax[name] = max(self.absmax.get(name, 0.0), amax)
        if self.mode == "entropy":
            h, _ = _np.histogram(_np.abs(arr), bins=self.NBINS,
                                 range=(0, max(self.absmax[name], 1e-12)))
            prev = self.hists.get(name)
            # histograms over growing ranges are merged approximately by
            # accumulating counts (range drift is second-order for calib)
            self.hists[name] = h if prev is None else prev + h

    def threshold(self, name: str) -> float:
        amax = max(self.absmax.get(name, 0.0), 1e-12)
        if self.mode == "naive" or name not in self.hists:
            return amax
        edges = _np.linspace(0, amax, self.NBINS + 1)
        return _entropy_threshold(self.hists[name], edges)


# ---------------------------------------------------------------------------
# quantized layers
# ---------------------------------------------------------------------------
_SUPPORTED_ACTS = (None, "relu", "sigmoid", "tanh", "softrelu",
                   "softsign")


def _apply_act(out, act_type):
    import jax
    import jax.numpy as jnp
    if act_type is None:
        return out
    return {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
            "tanh": jnp.tanh, "softrelu": jax.nn.softplus,
            "softsign": jax.nn.soft_sign}[act_type](out)


class _QuantizedBase(HybridBlock):
    """Shared int8 machinery: frozen int8 weights + scales as constants.

    The whole quantize → int8 compute → requantize chain runs as ONE
    compiled call per layer (``jax.jit``, built lazily on first forward
    and cached per input shape/dtype by jit itself) instead of an eager
    op round trip per stage — the weights/scales are passed as runtime
    arguments so they are not baked into the executable as constants."""

    def __init__(self, w_q: _np.ndarray, w_scale: _np.ndarray,
                 bias: Optional[_np.ndarray], act_scale: float, **kwargs):
        super().__init__(**kwargs)
        jnp = _jnp()
        # frozen inference constants (not Parameters: no grads, no init)
        self._wq = jnp.asarray(w_q)
        self._wscale = jnp.asarray(w_scale, jnp.float32)
        self._bias = None if bias is None else jnp.asarray(
            bias, jnp.float32)
        self._xscale = float(max(act_scale, 1e-12)) / 127.0
        self._kernel = None

    def _quantize_input(self, x):
        jnp = _jnp()
        q = jnp.clip(jnp.round(x / self._xscale), -127, 127)
        return q.astype(jnp.int8)


class QuantizedDense(_QuantizedBase):
    """int8 FullyConnected (reference: quantized_fully_connected op).
    y = (x_q @ w_q^T) * (s_x * s_w[c]) + b, accumulated in int32."""

    def __init__(self, dense: "_gnn.Dense", act_scale: float, **kwargs):
        w = dense.weight.data().asnumpy()
        b = None if dense.bias is None else dense.bias.data().asnumpy()
        w_q, w_scale = _quantize_weight_per_channel(w)
        super().__init__(w_q, w_scale, b, act_scale, **kwargs)
        self._units = dense._units
        self._flatten = dense._flatten
        if dense._act_type not in _SUPPORTED_ACTS:
            raise MXNetError(
                f"cannot quantize Dense with activation "
                f"{dense._act_type!r}; exclude the layer instead")
        self._act_type = dense._act_type

    def _build_kernel(self):
        import jax
        import jax.numpy as jnp
        from .. import telemetry as _telemetry
        xscale, flatten = self._xscale, self._flatten
        act_type, has_bias = self._act_type, self._bias is not None

        def kernel(xv, wq, wscale, *bias):
            orig_dtype = xv.dtype
            xf = xv.astype(jnp.float32)
            if flatten and xf.ndim > 2:
                xf = xf.reshape(xf.shape[0], -1)
            xq = jnp.clip(jnp.round(xf / xscale), -127,
                          127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, wq, (((xf.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (xscale * wscale)
            if has_bias:
                out = out + bias[0]
            out = _apply_act(out, act_type)
            return out.astype(orig_dtype)
        self._kernel = _telemetry.instrument_jit("quantized_dense",
                                                 jax.jit(kernel))
        return self._kernel

    def hybrid_forward(self, F, x):
        kern = self._kernel or self._build_kernel()

        def run(xv):
            args = (self._bias,) if self._bias is not None else ()
            return kern(xv, self._wq, self._wscale, *args)
        return _invoke(run, [x], name="quantized_dense",
                       differentiable=False)


class QuantizedConv2D(_QuantizedBase):
    """int8 Convolution (reference: quantized_conv op — the oneDNN INT8
    conv is the fork's flagship kernel; here XLA's int8 conv path)."""

    def __init__(self, conv: "_gnn.Conv2D", act_scale: float, **kwargs):
        w = conv.weight.data().asnumpy()
        b = None if conv.bias is None else conv.bias.data().asnumpy()
        w_q, w_scale = _quantize_weight_per_channel(w)
        super().__init__(w_q, w_scale, b, act_scale, **kwargs)
        if conv._act_type not in _SUPPORTED_ACTS:
            raise MXNetError(
                f"cannot quantize Conv2D with activation "
                f"{conv._act_type!r}; exclude the layer instead")
        self._strides = conv._strides
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._act_type = conv._act_type

    def _build_kernel(self):
        import jax
        import jax.numpy as jnp
        from .. import telemetry as _telemetry
        xscale, act_type = self._xscale, self._act_type
        strides, padding = self._strides, self._padding
        dilation, groups = self._dilation, self._groups
        has_bias = self._bias is not None
        # XLA:CPU has no fast s8xs8 conv kernels (an order of magnitude
        # SLOWER than f32); the quantized values are integers in
        # [-127, 127], exactly representable in f32, so on CPU the conv
        # runs on the quantized values in f32 at full speed.  TPU/GPU
        # keep the int8 x int8 -> int32 MXU path.
        int8_compute = jax.default_backend() != "cpu"
        # hoist the weight representation the backend computes in — the
        # CPU path would otherwise recast the full weight tensor every
        # forward
        self._wrun = self._wq if int8_compute \
            else self._wq.astype(jnp.float32)

        def kernel(xv, wq, wscale, *bias):
            orig_dtype = xv.dtype
            xf = xv.astype(jnp.float32)
            xq = jnp.clip(jnp.round(xf / xscale), -127, 127)
            if int8_compute:
                lhs, pref = xq.astype(jnp.int8), jnp.int32
            else:
                lhs, pref = xq, jnp.float32
            acc = jax.lax.conv_general_dilated(
                lhs, wq,
                window_strides=strides,
                padding=[(p, p) for p in padding],
                rhs_dilation=dilation,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=groups,
                preferred_element_type=pref)
            out = acc.astype(jnp.float32) * (
                xscale * wscale.reshape(1, -1, 1, 1))
            if has_bias:
                out = out + bias[0].reshape(1, -1, 1, 1)
            out = _apply_act(out, act_type)
            return out.astype(orig_dtype)
        self._kernel = _telemetry.instrument_jit("quantized_conv2d",
                                                 jax.jit(kernel))
        return self._kernel

    def hybrid_forward(self, F, x):
        kern = self._kernel or self._build_kernel()

        def run(xv):
            args = (self._bias,) if self._bias is not None else ()
            return kern(xv, self._wrun, self._wscale, *args)
        return _invoke(run, [x], name="quantized_conv2d",
                       differentiable=False)


# ---------------------------------------------------------------------------
# calibration + rewrite
# ---------------------------------------------------------------------------
def _quantizable_children(block, prefix=""):
    for name, child in block._children.items():
        full = f"{prefix}{name}"
        if isinstance(child, (_gnn.Dense, _gnn.Conv2D)):
            yield block, name, full, child
        else:
            yield from _quantizable_children(child, prefix=full + ".")


def _calibrate(net, calib_data, collector, num_calib_batches=None,
               names=None):
    """Run fp32 forwards capturing each quantizable layer's INPUT
    statistics via forward hooks."""
    from .. import autograd as _ag
    handles = []
    try:
        for _, _, full, child in _quantizable_children(net):
            if names is not None and full not in names:
                continue

            def hook(blk, inputs, _out, _full=full):
                x = inputs[0]
                collector.collect(_full, x.asnumpy())
            child.register_forward_hook(hook)
            handles.append((child, hook))
        with _ag.pause():
            for i, batch in enumerate(calib_data):
                if num_calib_batches is not None \
                        and i >= num_calib_batches:
                    break
                from ..io.io import DataBatch as _DataBatch
                if isinstance(batch, _DataBatch):  # legacy io.DataBatch
                    x = batch.data[0]
                elif isinstance(batch, (tuple, list)):
                    x = batch[0]
                else:
                    x = batch
                if not isinstance(x, NDArray):
                    from ..ndarray import ndarray as _ndmod
                    x = _ndmod.array(_np.asarray(x))
                net(x)
    finally:
        # remove only the calibration hooks; user hooks stay registered
        for child, hook in handles:
            child._forward_hooks.remove(hook)


def quantize_net(network, quantized_dtype="int8", calib_data=None,
                 calib_mode="naive", num_calib_batches=None,
                 exclude_layers=None, exclude_layers_match=None,
                 logger=None):
    """Post-training INT8 quantization of a Gluon network IN PLACE
    (reference: quantization.quantize_net).  Dense/Conv2D children are
    replaced with int8 equivalents using activation scales calibrated
    over ``calib_data`` (iterable of batches or (x, y) tuples).  Returns
    the network."""
    if quantized_dtype not in ("int8", "auto"):
        raise MXNetError("only int8 quantization is supported (uint8 "
                         "offers no advantage on TPU's signed MXU path)")
    if calib_mode not in ("naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    if calib_data is None:
        raise MXNetError("calib_data is required: post-training "
                         "quantization needs activation ranges")
    exclude = set(exclude_layers or [])

    targets = [(parent, name, full, child)
               for parent, name, full, child in
               _quantizable_children(network)
               if full not in exclude
               and not any(m in full for m in (exclude_layers_match or []))]
    if not targets:
        raise MXNetError("no quantizable (Dense/Conv2D) layers found")

    collector = CalibrationCollector(calib_mode)
    _calibrate(network, calib_data, collector,
               num_calib_batches=num_calib_batches,
               names={t[2] for t in targets})

    for parent, name, full, child in targets:
        thresh = collector.threshold(full)
        if isinstance(child, _gnn.Conv2D):
            q = QuantizedConv2D(child, thresh, prefix=child.prefix)
        else:
            q = QuantizedDense(child, thresh, prefix=child.prefix)
        parent._children[name] = q
        # keep the attribute view in sync when the child was set by name
        if getattr(parent, "__dict__", {}).get(name) is child:
            object.__setattr__(parent, name, q)
    return network


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=None, calib_mode="naive",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=None):
    """Symbol-era API (reference: quantization.quantize_model).  Wraps the
    gluon rewrite: the symbol+params are imported into a SymbolBlock-style
    net, quantized, and returned as a callable block (our symbolic
    executor runs gluon blocks natively, so the (sym, args, aux) triple
    round-trip is unnecessary)."""
    from ..gluon.block import SymbolBlock
    from ..symbol import var as _svar
    inputs = [_svar(n) for n in data_names]
    net = SymbolBlock(sym, inputs)
    params = net.collect_params()
    for k, v in {**(arg_params or {}), **(aux_params or {})}.items():
        for name, p in params.items():
            if name == k or name.endswith(k):
                p.set_data(v)
    if num_calib_examples is not None and calib_data is not None:
        calib_data = _limit_examples(calib_data, num_calib_examples)
    return quantize_net(net, quantized_dtype=quantized_dtype,
                        calib_data=calib_data, calib_mode=calib_mode,
                        exclude_layers=excluded_sym_names)


def _limit_examples(data, n):
    """Yield batches until ~n EXAMPLES were seen (reference:
    num_calib_examples counts examples, not batches)."""
    seen = 0
    for b in data:
        yield b
        x = b[0] if isinstance(b, (tuple, list)) else b
        seen += int(x.shape[0])
        if seen >= n:
            break
