"""Horovod-shaped shim (SURVEY §2.4: the reference integrates Horovod at
the Trainer level — hvd.init/rank/size + hvd.DistributedTrainer +
broadcast_parameters, example/distributed_training-horovod/).  Code
written against that surface runs here unchanged: the MPI/NCCL allreduce
becomes the same XLA-collective path the dist KVStore uses.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "allreduce", "allgather", "broadcast_parameters",
           "DistributedTrainer"]


def init():
    """hvd.init() — bootstrap the multi-process runtime (DMLC/OMPI env
    vars both work; single-process is a no-op)."""
    import os
    from ..parallel import distributed as dist
    if "OMPI_COMM_WORLD_RANK" in os.environ and \
            "DMLC_WORKER_ID" not in os.environ:
        # accept Open MPI's env the way horovod's launcher sets it
        os.environ.setdefault("DMLC_WORKER_ID",
                              os.environ["OMPI_COMM_WORLD_RANK"])
        os.environ.setdefault("DMLC_NUM_WORKER",
                              os.environ.get("OMPI_COMM_WORLD_SIZE", "1"))
    dist.initialize()


def shutdown():
    from ..parallel import distributed as dist
    dist.shutdown()


def rank() -> int:
    import jax
    return jax.process_index()


def size() -> int:
    import jax
    return jax.process_count()


def local_rank() -> int:
    return 0     # one process per host in the SPMD model


def local_size() -> int:
    import jax
    return jax.local_device_count()


def allreduce(tensor, average=True, name=None):
    """Sum (or mean) a tensor across processes (hvd.allreduce)."""
    if not isinstance(tensor, NDArray):
        raise MXNetError("hvd.allreduce expects an NDArray")
    n = size()
    if n == 1:
        return tensor.copy()
    from jax.experimental import multihost_utils
    out = multihost_utils.process_allgather(tensor._data).sum(axis=0)
    if average:
        out = out / n
    return NDArray(out, ctx=tensor.ctx)


def allgather(tensor, name=None):
    if not isinstance(tensor, NDArray):
        raise MXNetError("hvd.allgather expects an NDArray")
    if size() == 1:
        return tensor.copy()
    from jax.experimental import multihost_utils
    return NDArray(
        multihost_utils.process_allgather(tensor._data, tiled=True),
        ctx=tensor.ctx)


def broadcast_parameters(params, root_rank=0):
    """Everyone adopts root's parameter values (hvd.broadcast_parameters;
    same DCN path as KVStore init)."""
    if size() == 1:
        return
    from jax.experimental import multihost_utils
    items = params.items() if hasattr(params, "items") else params
    for _, p in items:
        data = p.data() if hasattr(p, "data") and callable(p.data) else p
        gathered = multihost_utils.process_allgather(data._data)
        data._set_data(gathered[root_rank])


class DistributedTrainer:
    """hvd.DistributedTrainer workalike: gluon Trainer + pre-update
    gradient allreduce (the reference subclass lives in the horovod repo;
    here dist aggregation is the 'dist_sync' KVStore path)."""

    def __new__(cls, params, optimizer, optimizer_params=None, **kwargs):
        from ..gluon.trainer import Trainer
        optimizer_params = dict(optimizer_params or {})
        # horovod semantics: grads are AVERAGED over workers
        scale = optimizer_params.get("rescale_grad", 1.0)
        optimizer_params["rescale_grad"] = scale / max(size(), 1)
        return Trainer(params, optimizer, optimizer_params,
                       kvstore="dist_sync", **kwargs)
