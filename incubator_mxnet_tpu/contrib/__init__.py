"""``mx.contrib`` — experimental / auxiliary subsystems (reference:
python/mxnet/contrib/__init__.py)."""
from . import amp
from . import quantization

__all__ = ["amp", "quantization"]
