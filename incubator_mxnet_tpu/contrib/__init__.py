"""``mx.contrib`` — experimental / auxiliary subsystems (reference:
python/mxnet/contrib/__init__.py)."""
from . import amp
from . import quantization
from . import text
from . import svrg_optimization
from . import hvd
from . import onnx

__all__ = ["amp", "quantization", "text", "svrg_optimization", "hvd",
           "onnx"]
