"""``mx.contrib`` — experimental / auxiliary subsystems (reference:
python/mxnet/contrib/__init__.py)."""
from . import amp

__all__ = ["amp"]
