"""Dynamic loss scaler (reference:
python/mxnet/contrib/amp/loss_scaler.py).

Needed for float16 training; bfloat16 shares fp32's exponent range so it
trains unscaled — the scaler then stays at 1.0 and never skips.
"""
from __future__ import annotations

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (reference:
        LossScaler.has_overflow via multi_all_finite)."""
        import jax.numpy as jnp
        for p in params:
            if p.grad_req == "null" or p.grad() is None:
                continue
            if not bool(jnp.isfinite(p.grad()._data).all()):
                return True
        return False

    def update_scale(self, overflow: bool):
        """Halve on overflow; double every scale_window clean steps
        (reference: LossScaler.update_scale)."""
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
