"""Dynamic loss scaler (reference:
python/mxnet/contrib/amp/loss_scaler.py).

Needed for float16 training; bfloat16 shares fp32's exponent range so it
trains unscaled — the scaler then stays at 1.0 and never skips.
"""
from __future__ import annotations

__all__ = ["LossScaler", "all_finite", "all_finite_flag"]


def all_finite_flag(arrays):
    """Device-side all-finite reduction over many arrays (reference:
    multi_all_finite) WITHOUT the host sync: returns a 0-d bool array
    (or ``None`` when no array has an inexact dtype — integer grads are
    always finite).  Accepts NDArrays or raw jax arrays, and is safe to
    call under a jit trace — the fused optimizer step folds this exact
    reduction into its compiled program so the non-finite guard costs no
    dispatch boundary at all."""
    import jax.numpy as jnp
    flag = None
    for a in arrays:
        data = getattr(a, "_data", a)
        if not jnp.issubdtype(data.dtype, jnp.inexact):
            continue
        f = jnp.isfinite(data).all()
        flag = f if flag is None else jnp.logical_and(flag, f)
    return flag


def all_finite(arrays) -> bool:
    """One fused all-finite check over many arrays.  Per-array finite
    flags are combined device-side with logical_and
    (:func:`all_finite_flag`), so the whole sweep costs a SINGLE blocking
    host sync — the per-param ``bool(isfinite(...).all())`` loop it
    replaces paid one sync per parameter."""
    flag = all_finite_flag(arrays)
    return True if flag is None else bool(flag)   # the one sync


class LossScaler:
    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = float(scale_factor)
        self._scale_window = int(scale_window)
        self._unskipped = 0

    def has_overflow(self, params):
        """True if any gradient is non-finite (reference:
        LossScaler.has_overflow via multi_all_finite)."""
        grads = [p.grad() for p in params
                 if p.grad_req != "null" and p.grad() is not None]
        return not all_finite(grads)

    def update_scale(self, overflow: bool):
        """Halve on overflow; double every scale_window clean steps
        (reference: LossScaler.update_scale)."""
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0

    # checkpoint/resume: the scale and the clean-step counter ARE the
    # scaler — losing them on preemption restarts the warmup from 2^16
    # and skips the first post-resume steps for nothing
    def get_state(self) -> dict:
        return {"loss_scale": self.loss_scale,
                "unskipped": self._unskipped}

    def set_state(self, state: dict) -> None:
        self.loss_scale = float(state["loss_scale"])
        self._unskipped = int(state.get("unskipped", 0))
