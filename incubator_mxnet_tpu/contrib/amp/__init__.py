"""``mx.contrib.amp`` — automatic mixed precision (reference:
python/mxnet/contrib/amp/amp.py).

TPU-first stance: the native low-precision type is **bfloat16** — fp32
exponent range, so no loss scaling is required and ``amp.init()`` defaults
to it.  ``float16`` is also supported with the reference's dynamic
loss-scaling workflow:

    amp.init()                       # bf16 by default
    net = ...; trainer = gluon.Trainer(...)
    amp.init_trainer(trainer)
    with autograd.record():
        loss = loss_fn(net(x), y)
    with amp.scale_loss(loss, trainer) as scaled:
        scaled.backward()
    trainer.step(batch)              # skips the update on overflow
"""
from __future__ import annotations

from contextlib import contextmanager

from ...base import MXNetError
from . import lists
from .loss_scaler import LossScaler, all_finite

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "convert_model", "lists", "LossScaler",
           "all_finite"]

_state = {"initialized": False, "target_dtype": None}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP (reference: amp.init).  target_dtype: 'bfloat16'
    (recommended on TPU) or 'float16'.

    Initialization patches the op namespaces with input-cast wrappers per
    the curated lists (the imperative analog of the reference's amp_cast
    graph rewrite, reference: amp.init → _initialize wrapping generated op
    functions): TARGET_DTYPE_OPS cast float inputs down to the AMP dtype,
    FP32_OPS cast low-precision inputs up to fp32, WIDEST_TYPE_CASTS align
    all float inputs to the widest present dtype.  ``target_precision_ops``
    / ``fp32_ops`` extend the respective lists (reference kwargs)."""
    import numpy as _np
    if isinstance(target_dtype, type) and target_dtype is _np.float16:
        target_dtype = "float16"
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("amp.init: target_dtype must be 'bfloat16' or "
                         "'float16'")
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype
    # reference conditional_fp32_ops entries are (op, arg, values) tuples
    # — the op runs fp32 when arg takes one of the values; here the whole
    # op is pinned fp32 (conservative superset, documented divergence)
    cond_names = [t[0] if isinstance(t, (tuple, list)) else t
                  for t in (conditional_fp32_ops or [])]
    _patch_namespaces(extra_low=target_precision_ops,
                      extra_fp32=list(fp32_ops or []) + cond_names)


# ---------------------------------------------------------------------------
# cast-insertion machinery (reference: amp.py _initialize / amp_cast nodes)
# ---------------------------------------------------------------------------
_patched = {}   # (module id, name) -> original fn


def _np_target_dtype():
    import numpy as _np
    if _state["target_dtype"] == "bfloat16":
        import ml_dtypes
        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(_np.float16)


def _is_float_dtype(dt):
    import numpy as _np
    dt = _np.dtype(dt)
    if dt.kind == "f":
        return True
    # ml_dtypes types (bfloat16, fp8...) register as numpy kind 'V'
    import ml_dtypes
    return dt == _np.dtype(ml_dtypes.bfloat16)


def _is_float_nd(x):
    from ...ndarray.ndarray import NDArray
    from ...ndarray.sparse import BaseSparseNDArray
    return (isinstance(x, NDArray)
            and not isinstance(x, BaseSparseNDArray)
            and _is_float_dtype(x.dtype))


def _cast_tree(x, dtype):
    from ...ndarray.ndarray import NDArray
    if isinstance(x, (list, tuple)):
        return type(x)(_cast_tree(e, dtype) for e in x)
    if _is_float_nd(x) and x.dtype != dtype:
        return x.astype(dtype)
    return x


def _widest_float(args):
    import numpy as _np

    def rank(dt):
        dt = _np.dtype(dt)
        if dt.itemsize >= 4:
            return dt.itemsize
        return 2

    found = []

    def walk(x):
        if isinstance(x, (list, tuple)):
            for e in x:
                walk(e)
        elif _is_float_nd(x):
            found.append(_np.dtype(x.dtype))
    walk(list(args))
    if not found:
        return None
    widest = max(found, key=rank)
    if any(rank(d) == rank(widest) and d != widest for d in found):
        return _np.dtype(_np.float32)  # e.g. bf16 mixed with fp16
    return widest


def _wrap_op(fn, rule):
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if not _state["initialized"]:
            return fn(*args, **kwargs)
        import numpy as _np
        if rule == "low":
            dtype = _np_target_dtype()
        elif rule == "fp32":
            dtype = _np.dtype(_np.float32)
        else:  # widest — consider keyword tensors too
            dtype = _widest_float(list(args) + list(kwargs.values()))
        if dtype is not None:
            if rule == "fp32":
                # only widen low-precision floats; leave fp32/fp64 alone
                def up(x):
                    if isinstance(x, (list, tuple)):
                        return type(x)(up(e) for e in x)
                    if _is_float_nd(x) and _np.dtype(x.dtype).itemsize < 4:
                        return x.astype(dtype)
                    return x
                args = [up(a) for a in args]
                kwargs = {k: up(v) for k, v in kwargs.items()}
            else:
                args = [_cast_tree(a, dtype) for a in args]
                kwargs = {k: _cast_tree(v, dtype)
                          for k, v in kwargs.items()}
        return fn(*args, **kwargs)

    wrapped._amp_original = fn
    return wrapped


def _patch_namespaces(extra_low=None, extra_fp32=None):
    """Install cast wrappers into ndarray.ops / ndarray.nn and the mx.nd
    package namespace (gluon layers dispatch F=the package).  Idempotent."""
    from ... import ndarray as nd_pkg
    from ...ndarray import ops as ops_mod, nn as nn_mod
    plan = ([(n, "low") for n in list(lists.TARGET_DTYPE_OPS)
             + list(extra_low or [])]
            + [(n, "fp32") for n in list(lists.FP32_OPS)
               + list(extra_fp32 or [])]
            + [(n, "widest") for n in lists.WIDEST_TYPE_CASTS])
    for name, rule in plan:
        for mod in (ops_mod, nn_mod, nd_pkg):
            fn = getattr(mod, name, None)
            if fn is None or getattr(fn, "_amp_original", None) is not None:
                continue
            key = (mod, name)
            if key not in _patched:
                _patched[key] = fn
            setattr(mod, name, _wrap_op(fn, rule))


def _check_initialized():
    if not _state["initialized"]:
        raise MXNetError("AMP is not initialized: call amp.init() first")


def _reset():
    """Undo init(): restore original op functions (test isolation aid —
    the reference has no off-switch, so this stays private)."""
    for (mod, name), fn in _patched.items():
        setattr(mod, name, fn)
    _patched.clear()
    _state["initialized"] = False
    _state["target_dtype"] = None


def target_dtype():
    return _state["target_dtype"]


def init_trainer(optimizer_or_trainer):
    """Attach a dynamic loss scaler to a Trainer (reference:
    amp.init_trainer).  With bfloat16 the scaler idles at scale 1.0."""
    _check_initialized()
    from ...gluon.trainer import Trainer
    if not isinstance(optimizer_or_trainer, Trainer):
        raise MXNetError("amp.init_trainer expects a gluon Trainer")
    trainer = optimizer_or_trainer
    scaler = LossScaler(
        init_scale=2.0 ** 16 if _state["target_dtype"] == "float16" else 1.0,
        scale_window=2000)
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        # idempotent: swap the scaler; the existing _update wrapper reads
        # it through the attribute, so re-wrapping would double-advance
        # the scale window
        trainer._amp_loss_scaler = scaler
        return trainer
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale

    orig_update = trainer._update

    def _amp_update(ignore_stale_grad=False):
        scaler = trainer._amp_loss_scaler
        overflow = (scaler.has_overflow(trainer._params)
                    if _state["target_dtype"] == "float16" else False)
        scaler.update_scale(overflow)
        if overflow:   # skip the step, like the reference's skip-on-overflow
            for p in trainer._params:
                if p.grad_req != "null":
                    p.zero_grad()
            return
        orig_update(ignore_stale_grad)

    trainer._update = _amp_update
    return trainer


@contextmanager
def scale_loss(loss, trainer):
    """Scale the loss up and the gradient rescale down (reference:
    amp.scale_loss).  Use as ``with amp.scale_loss(loss, t) as l: l.backward()``."""
    _check_initialized()
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        init_trainer(trainer)
        scaler = trainer._amp_loss_scaler
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    from ... import autograd as _ag

    def _scaled():
        if isinstance(loss, (list, tuple)):
            return [l * scaler.loss_scale for l in loss]
        return loss * scaler.loss_scale

    if _ag.is_recording():
        yield _scaled()
    else:
        # reference usage keeps scale_loss inside record(); support the
        # outside-record spelling by extending the tape here
        with _ag.record():
            yield _scaled()


def unscale(optimizer_or_trainer):
    """Divide gradients by the current loss scale in place (reference:
    amp.unscale).  Also restores the trainer's rescale factor so the
    subsequent ``step()``/``update()`` does not divide by the loss scale a
    second time (the reference resets the trainer scale the same way)."""
    _check_initialized()
    trainer = optimizer_or_trainer
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    for p in trainer._params:
        if p.grad_req != "null" and p.grad() is not None:
            g = p.grad()
            g._set_data(g._data / scaler.loss_scale)
    if getattr(trainer, "_amp_original_scale", None) is not None:
        trainer._scale = trainer._amp_original_scale


def convert_hybrid_block(block, target_dtype=None):
    """Cast a (Hybrid)Block's parameters to the AMP dtype, keeping
    normalization layers in fp32 (reference: amp.convert_hybrid_block,
    which rewrites the symbol with amp_cast nodes; here the array IS the
    graph input so casting params is the whole rewrite — XLA handles the
    mixed-dtype promotion in the fused program)."""
    _check_initialized()
    import numpy as _np
    from ...gluon import nn as gnn
    dtype = target_dtype or _state["target_dtype"]
    if dtype == "bfloat16":
        import ml_dtypes
        dtype = ml_dtypes.bfloat16   # numpy proper has no 'bfloat16' name
    fp32_types = tuple(getattr(gnn, name) for name in
                       lists.FP32_PARAM_LAYERS if hasattr(gnn, name))

    def _cast(b):
        if isinstance(b, fp32_types):
            return
        for child in b._children.values():
            _cast(child)
        for p in b.params.values():
            if p._data is not None and _np.dtype(p.dtype).kind == "f":
                p.cast(dtype)

    _cast(block)
    return block


convert_model = convert_hybrid_block
