"""``mx.contrib.amp`` — automatic mixed precision (reference:
python/mxnet/contrib/amp/amp.py).

TPU-first stance: the native low-precision type is **bfloat16** — fp32
exponent range, so no loss scaling is required and ``amp.init()`` defaults
to it.  ``float16`` is also supported with the reference's dynamic
loss-scaling workflow:

    amp.init()                       # bf16 by default
    net = ...; trainer = gluon.Trainer(...)
    amp.init_trainer(trainer)
    with autograd.record():
        loss = loss_fn(net(x), y)
    with amp.scale_loss(loss, trainer) as scaled:
        scaled.backward()
    trainer.step(batch)              # skips the update on overflow
"""
from __future__ import annotations

from contextlib import contextmanager

from ...base import MXNetError
from . import lists
from .loss_scaler import LossScaler

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "convert_model", "lists", "LossScaler"]

_state = {"initialized": False, "target_dtype": None}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP (reference: amp.init).  target_dtype: 'bfloat16'
    (recommended on TPU) or 'float16'."""
    import numpy as _np
    if isinstance(target_dtype, type) and target_dtype is _np.float16:
        target_dtype = "float16"
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("amp.init: target_dtype must be 'bfloat16' or "
                         "'float16'")
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype


def _check_initialized():
    if not _state["initialized"]:
        raise MXNetError("AMP is not initialized: call amp.init() first")


def target_dtype():
    return _state["target_dtype"]


def init_trainer(optimizer_or_trainer):
    """Attach a dynamic loss scaler to a Trainer (reference:
    amp.init_trainer).  With bfloat16 the scaler idles at scale 1.0."""
    _check_initialized()
    from ...gluon.trainer import Trainer
    if not isinstance(optimizer_or_trainer, Trainer):
        raise MXNetError("amp.init_trainer expects a gluon Trainer")
    trainer = optimizer_or_trainer
    scaler = LossScaler(
        init_scale=2.0 ** 16 if _state["target_dtype"] == "float16" else 1.0,
        scale_window=2000)
    if getattr(trainer, "_amp_loss_scaler", None) is not None:
        # idempotent: swap the scaler; the existing _update wrapper reads
        # it through the attribute, so re-wrapping would double-advance
        # the scale window
        trainer._amp_loss_scaler = scaler
        return trainer
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale

    orig_update = trainer._update

    def _amp_update(ignore_stale_grad=False):
        scaler = trainer._amp_loss_scaler
        overflow = (scaler.has_overflow(trainer._params)
                    if _state["target_dtype"] == "float16" else False)
        scaler.update_scale(overflow)
        if overflow:   # skip the step, like the reference's skip-on-overflow
            for p in trainer._params:
                if p.grad_req != "null":
                    p.zero_grad()
            return
        orig_update(ignore_stale_grad)

    trainer._update = _amp_update
    return trainer


@contextmanager
def scale_loss(loss, trainer):
    """Scale the loss up and the gradient rescale down (reference:
    amp.scale_loss).  Use as ``with amp.scale_loss(loss, t) as l: l.backward()``."""
    _check_initialized()
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        init_trainer(trainer)
        scaler = trainer._amp_loss_scaler
    trainer._scale = trainer._amp_original_scale / scaler.loss_scale
    from ... import autograd as _ag

    def _scaled():
        if isinstance(loss, (list, tuple)):
            return [l * scaler.loss_scale for l in loss]
        return loss * scaler.loss_scale

    if _ag.is_recording():
        yield _scaled()
    else:
        # reference usage keeps scale_loss inside record(); support the
        # outside-record spelling by extending the tape here
        with _ag.record():
            yield _scaled()


def unscale(optimizer_or_trainer):
    """Divide gradients by the current loss scale in place (reference:
    amp.unscale).  Also restores the trainer's rescale factor so the
    subsequent ``step()``/``update()`` does not divide by the loss scale a
    second time (the reference resets the trainer scale the same way)."""
    _check_initialized()
    trainer = optimizer_or_trainer
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None or scaler.loss_scale == 1.0:
        return
    for p in trainer._params:
        if p.grad_req != "null" and p.grad() is not None:
            g = p.grad()
            g._set_data(g._data / scaler.loss_scale)
    if getattr(trainer, "_amp_original_scale", None) is not None:
        trainer._scale = trainer._amp_original_scale


def convert_hybrid_block(block, target_dtype=None):
    """Cast a (Hybrid)Block's parameters to the AMP dtype, keeping
    normalization layers in fp32 (reference: amp.convert_hybrid_block,
    which rewrites the symbol with amp_cast nodes; here the array IS the
    graph input so casting params is the whole rewrite — XLA handles the
    mixed-dtype promotion in the fused program)."""
    _check_initialized()
    import numpy as _np
    from ...gluon import nn as gnn
    dtype = target_dtype or _state["target_dtype"]
    if dtype == "bfloat16":
        import ml_dtypes
        dtype = ml_dtypes.bfloat16   # numpy proper has no 'bfloat16' name
    fp32_types = tuple(getattr(gnn, name) for name in
                       lists.FP32_PARAM_LAYERS if hasattr(gnn, name))

    def _cast(b):
        if isinstance(b, fp32_types):
            return
        for child in b._children.values():
            _cast(child)
        for p in b.params.values():
            if p._data is not None and _np.dtype(p.dtype).kind == "f":
                p.cast(dtype)

    _cast(block)
    return block


convert_model = convert_hybrid_block
