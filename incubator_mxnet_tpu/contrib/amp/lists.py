"""AMP op classification lists (reference:
python/mxnet/contrib/amp/lists/symbol_fp16.py).

On TPU the low-precision type is bfloat16: same exponent range as fp32, so
the reference's fp16 overflow machinery (loss scaling) is unnecessary for
bf16 — but the op classification still decides where low precision is
numerically safe vs where fp32 accumulate/compute must be kept.
"""

# Ops whose math is dominated by MXU matmul/conv — run in low precision
LOW_PRECISION_OPS = [
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "matmul", "RNN", "linalg_gemm2",
]

# Numerically sensitive — keep fp32 compute (reference FP32_FUNCS)
FP32_OPS = [
    "softmax", "log_softmax", "softmax_cross_entropy", "SoftmaxOutput",
    "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "l2_normalization",
    "norm", "mean", "sum", "exp", "log", "log2", "log10", "log1p", "expm1",
    "power", "cumsum", "erf", "erfinv", "gamma", "smooth_l1",
]

# Run in the widest input dtype (reference WIDEST_TYPE_CASTS)
WIDEST_TYPE_CASTS = [
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "where", "concat", "stack", "add_n",
]

# Layer classes whose *parameters* stay fp32 under convert_hybrid_block
FP32_PARAM_LAYERS = ["BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm"]
