"""AMP op classification lists, curated over the full op corpus
(reference: python/mxnet/contrib/amp/lists/symbol_fp16.py — FP16_FUNCS /
FP16_FP32_FUNCS / FP32_FUNCS / WIDEST_TYPE_CASTS).

On TPU the low-precision type is bfloat16: same exponent range as fp32, so
the reference's fp16 overflow machinery (loss scaling) is unnecessary for
bf16 — but the op classification still decides where low precision is
numerically safe vs where fp32 compute must be kept.  These lists are
load-bearing: ``amp.init()`` wraps every listed op with the corresponding
input-cast rule (the imperative analog of the reference's amp_cast graph
rewrite).  tests/test_amp.py asserts the four lists exactly cover the
``mx.nd`` + nn op corpus with no overlaps.
"""

# ---------------------------------------------------------------------------
# Ops whose math is dominated by MXU matmul/conv — cast inputs DOWN to the
# AMP target dtype (reference: FP16_FUNCS)
# ---------------------------------------------------------------------------
TARGET_DTYPE_OPS = [
    "FullyConnected", "fully_connected",
    "Convolution", "convolution", "Convolution_v1",
    "Deconvolution", "deconvolution",
    "RNN", "rnn",
    "dot", "batch_dot", "matmul", "linalg_gemm2", "khatri_rao",
]
LOW_PRECISION_OPS = TARGET_DTYPE_OPS  # back-compat alias

# ---------------------------------------------------------------------------
# Numerically sensitive — cast low-precision inputs UP to fp32
# (reference: FP32_FUNCS)
# ---------------------------------------------------------------------------
FP32_OPS = [
    # softmax / loss heads
    "softmax", "log_softmax", "softmax_cross_entropy",
    "SoftmaxOutput", "softmax_output", "SVMOutput", "svm_output",
    "LinearRegressionOutput", "LogisticRegressionOutput",
    "MAERegressionOutput", "make_loss", "smooth_l1",
    # normalization (fp32 statistics)
    "BatchNorm", "batch_norm", "LayerNorm", "layer_norm",
    "InstanceNorm", "instance_norm", "GroupNorm", "group_norm",
    "L2Normalization", "l2_normalization", "norm", "linalg_norm",
    # reductions (fp32 accumulate)
    "sum", "sum_axis", "nansum", "mean", "prod", "nanprod", "cumsum",
    # exp/log/power family
    "exp", "expm1", "log", "log1p", "log2", "log10",
    "power", "broadcast_power", "reciprocal", "rsqrt", "rcbrt",
    "softplus", "softrelu",
    # special functions
    "erf", "erfinv", "gamma", "gammaln", "digamma",
]

# ---------------------------------------------------------------------------
# Multi-input elementwise — cast every float input to the WIDEST input
# dtype (reference: WIDEST_TYPE_CASTS)
# ---------------------------------------------------------------------------
WIDEST_TYPE_CASTS = [
    "add", "subtract", "multiply", "divide", "mod", "floor_divide",
    "maximum", "minimum", "hypot", "arctan2",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "broadcast_add", "broadcast_plus", "broadcast_sub", "broadcast_minus",
    "broadcast_mul", "broadcast_div", "broadcast_mod",
    "broadcast_maximum", "broadcast_minimum", "broadcast_hypot",
    "equal", "not_equal", "greater", "greater_equal", "lesser",
    "lesser_equal",
    "broadcast_equal", "broadcast_not_equal", "broadcast_greater",
    "broadcast_greater_equal", "broadcast_lesser", "broadcast_lesser_equal",
    "broadcast_logical_and", "broadcast_logical_or",
    "broadcast_logical_xor",
    "add_n", "ElementWiseSum", "where", "concat", "Concat", "stack",
]

# ---------------------------------------------------------------------------
# Safe in either dtype — run in the input's dtype, no cast inserted
# (reference: FP16_FP32_FUNCS)
# ---------------------------------------------------------------------------
TARGET_SAFE_OPS = [
    # activations
    "Activation", "relu", "sigmoid", "tanh", "gelu", "erf_gelu",
    "LeakyReLU", "leaky_relu", "softsign",
    # trig / rounding / unary arithmetic
    "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "sinh", "cosh", "arcsinh", "arccosh", "arctanh",
    "abs", "sign", "negative", "floor", "ceil", "round", "rint", "trunc",
    "fix", "sqrt", "cbrt", "square", "clip", "degrees", "radians",
    # shape / layout / views
    "reshape", "reshape_like", "Flatten", "flatten", "transpose",
    "SwapAxis", "swapaxes", "expand_dims", "squeeze", "broadcast_to",
    "broadcast_like", "broadcast_axes", "broadcast_axis",
    "Pad", "pad", "tile", "repeat", "flip", "reverse",
    "slice", "slice_axis", "slice_like", "SliceChannel", "split", "Crop",
    "split_v2", "diag", "shape_array", "size_array",
    # indexing / gather / scatter
    "take", "batch_take", "pick", "gather_nd", "scatter_nd",
    "boolean_mask", "one_hot", "Embedding", "embedding",
    # ordering
    "sort", "argsort", "topk", "argmax", "argmin", "argmax_channel",
    "max", "max_axis", "min", "min_axis",
    # sequence
    "SequenceLast", "sequence_last", "SequenceMask", "sequence_mask",
    "SequenceReverse", "sequence_reverse",
    # logical / predicates (dtype-preserving or bool-valued)
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "isfinite", "isinf", "isnan",
    # misc / identity / dtype plumbing
    "identity", "copy", "Cast", "cast", "BlockGrad", "stop_gradient",
    "zeros_like", "ones_like", "full_like", "Dropout", "dropout",
    "Pooling", "pooling", "UpSampling", "rnn_param_size",
]

# Layer classes whose *parameters* stay fp32 under convert_hybrid_block
FP32_PARAM_LAYERS = ["BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm"]
