"""SVRG optimization (reference:
python/mxnet/contrib/svrg_optimization/svrg_module.py + svrg_optimizer.py
— Stochastic Variance-Reduced Gradient, Johnson & Zhang 2013).

The update uses a control variate built from a periodic full-dataset
gradient snapshot: ``g_svrg = g(w) - g_snap(w_snap) + mu`` where ``mu`` is
the full gradient at the snapshot weights.  The reference composes two
Modules (live + snapshot) bound to the same symbol; the same composition
works here — the snapshot module re-runs each batch at the frozen weights
to get ``g_snap(w_snap)`` per batch.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as _np

from ..base import MXNetError
from ..module.module import Module
from ..ndarray.ndarray import NDArray

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG updates (reference: SVRGModule).

    update_freq: take a new full-gradient snapshot every this many
    epochs (call :meth:`update_full_grads` accordingly — ``fit`` does it
    automatically).
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), context=None,
                 update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, context=context,
                         **kwargs)
        if update_freq < 1:
            raise MXNetError("update_freq must be >= 1")
        self.update_freq = int(update_freq)
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, context=context)
        self._mu: Dict[str, _np.ndarray] = {}
        self._has_snapshot = False

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, **kwargs):
        super().bind(data_shapes, label_shapes=label_shapes,
                     for_training=for_training,
                     inputs_need_grad=inputs_need_grad, **kwargs)
        self._mod_aux.bind(data_shapes, label_shapes=label_shapes,
                           for_training=True)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        self._sync_aux_params()

    def _sync_aux_params(self):
        # deep-copy: the snapshot must FREEZE the weights — sharing the
        # live buffers would make g_snap track the live gradient and the
        # control variate collapse to the stale full gradient
        arg, aux = self.get_params()
        self._mod_aux.init_params(
            arg_params={k: v.copy() for k, v in arg.items()},
            aux_params={k: v.copy() for k, v in aux.items()},
            force_init=True, allow_missing=False)

    # ------------------------------------------------------------------
    def update_full_grads(self, train_data):
        """Snapshot the current weights into the aux module and compute
        the full-dataset gradient ``mu`` at them (reference:
        SVRGModule.update_full_grads)."""
        self._sync_aux_params()
        acc: Dict[str, _np.ndarray] = {}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            for name, grad in self._mod_aux._exec.grad_dict.items():
                if grad is None:
                    continue
                g = grad.asnumpy()
                acc[name] = g if name not in acc else acc[name] + g
            nbatch += 1
        if nbatch == 0:
            raise MXNetError("update_full_grads: empty data iterator")
        self._mu = {k: v / nbatch for k, v in acc.items()}
        self._has_snapshot = True
        train_data.reset()

    def forward_backward(self, data_batch):
        """Batch grads on BOTH modules: live weights and snapshot weights
        (the latter feeds the control variate in :meth:`update`)."""
        self.forward(data_batch, is_train=True)
        self.backward()
        if self._has_snapshot:
            self._mod_aux.forward(data_batch, is_train=True)
            self._mod_aux.backward()

    def update(self):
        """Apply the SVRG-corrected gradient through the optimizer
        (reference: _SVRGOptimizer rewrites the grad before the base
        update)."""
        if self._has_snapshot:
            import jax.numpy as jnp
            for name, grad in self._exec.grad_dict.items():
                if grad is None or name not in self._mu:
                    continue
                g_snap = self._mod_aux._exec.grad_dict.get(name)
                if g_snap is None:
                    continue
                corrected = (grad._data - g_snap._data
                             + jnp.asarray(self._mu[name]))
                grad._set_data(corrected)
        super().update()

    # ------------------------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            initializer=None, num_epoch=1, batch_end_callback=None,
            epoch_end_callback=None, **kwargs):
        """Training loop with automatic periodic snapshots (reference:
        SVRGModule.fit)."""
        from .. import metric as metric_mod
        if not self.binded:
            raise MXNetError("fit: bind() the module first")
        if not self.params_initialized:
            self.init_params(initializer=initializer)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        eval_metric = metric_mod.create(eval_metric) \
            if isinstance(eval_metric, str) else eval_metric
        for epoch in range(num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for nbatch, batch in enumerate(train_data):
                self.forward_backward(batch)
                self.update()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    batch_end_callback(epoch=epoch, nbatch=nbatch)
            if epoch_end_callback is not None:
                epoch_end_callback(epoch=epoch)
        return eval_metric
