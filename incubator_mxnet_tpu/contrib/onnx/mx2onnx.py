"""Symbol+params → ONNX export (reference:
python/mxnet/contrib/onnx/mx2onnx/export_model.py + _op_translations.py).

Node-by-node translation of the Symbol DAG into an ONNX GraphProto
(opset 13): variables with param values become initializers, the rest
become graph inputs.  Unsupported ops raise with the op name — the same
fail-loudly contract the reference's converter has.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as _np

from ...base import MXNetError
from . import serde

__all__ = ["export_model"]

_OPSET = 13


def _tuplize(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _Ctx:
    def __init__(self, pb, graph):
        self.pb = pb
        self.graph = graph
        self._uid = 0

    def tmp(self, base):
        self._uid += 1
        return f"{base}__tmp{self._uid}"

    def node(self, op_type, inputs, outputs, name, **attrs):
        n = self.graph.node.add()
        n.op_type = op_type
        n.input.extend(inputs)
        n.output.extend(outputs)
        n.name = name
        for k, v in attrs.items():
            a = n.attribute.add()
            a.name = k
            AT = self.pb.AttributeProto
            if isinstance(v, float):
                a.type = AT.FLOAT
                a.f = v
            elif isinstance(v, bool) or isinstance(v, int):
                a.type = AT.INT
                a.i = int(v)
            elif isinstance(v, str):
                a.type = AT.STRING
                a.s = v.encode()
            elif isinstance(v, (tuple, list)):
                a.type = AT.INTS
                a.ints.extend(int(x) for x in v)
            else:
                raise MXNetError(f"unsupported attribute value {v!r}")
        return n

    def const_i64(self, name, values):
        t = self.graph.initializer.add()
        t.name = name
        t.data_type = self.pb.TensorProto.INT64
        t.dims.extend([len(values)])
        t.raw_data = _np.asarray(values, _np.int64).tobytes()
        return name


# ---------------------------------------------------------------------------
# per-op translators: (ctx, node, in_names, out_name) -> None
# ---------------------------------------------------------------------------
def _conv(ctx, n, ins, out):
    a = n.attrs
    kernel = _tuplize(a["kernel"])
    pad = _tuplize(a.get("pad", 0), len(kernel))
    ctx.node("Conv", ins, [out], n.name,
             kernel_shape=kernel,
             strides=_tuplize(a.get("stride", 1), len(kernel)),
             pads=pad + pad,
             dilations=_tuplize(a.get("dilate", 1), len(kernel)),
             group=int(a.get("num_group", 1)))


def _fc(ctx, n, ins, out):
    a = n.attrs
    x = ins[0]
    if a.get("flatten", True):
        flat = ctx.tmp(n.name)
        ctx.node("Flatten", [x], [flat], f"{n.name}_flatten", axis=1)
        x = flat
    if len(ins) == 3:
        ctx.node("Gemm", [x, ins[1], ins[2]], [out], n.name,
                 alpha=1.0, beta=1.0, transA=0, transB=1)
    else:
        ctx.node("Gemm", [x, ins[1]], [out], n.name,
                 alpha=1.0, beta=1.0, transA=0, transB=1)


def _batchnorm(ctx, n, ins, out):
    a = n.attrs
    # mx order: data, gamma, beta, moving_mean, moving_var — same as ONNX
    ctx.node("BatchNormalization", ins[:5], [out], n.name,
             epsilon=float(a.get("eps", 1e-5)),
             momentum=float(a.get("momentum", 0.9)))


def _activation(ctx, n, ins, out):
    act = n.attrs.get("act_type", "relu")
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softsign": "Softsign", "softrelu": "Softplus"}
    if act not in table:
        raise MXNetError(f"ONNX export: unsupported act_type {act!r}")
    ctx.node(table[act], [ins[0]], [out], n.name)


def _pooling(ctx, n, ins, out):
    a = n.attrs
    ptype = a.get("pool_type", "max")
    if a.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.node(op, [ins[0]], [out], n.name)
        return
    kernel = _tuplize(a["kernel"])
    pad = _tuplize(a.get("pad", 0), len(kernel))
    kwargs = dict(kernel_shape=kernel,
                  strides=_tuplize(a.get("stride", 1), len(kernel)),
                  pads=pad + pad)
    if ptype == "max":
        ctx.node("MaxPool", [ins[0]], [out], n.name, **kwargs)
    elif ptype == "avg":
        ctx.node("AveragePool", [ins[0]], [out], n.name,
                 count_include_pad=int(bool(
                     a.get("count_include_pad", True))), **kwargs)
    else:
        raise MXNetError(f"ONNX export: pool_type {ptype!r} unsupported")


def _reshape(ctx, n, ins, out):
    shape = n.attrs.get("shape")
    if shape is None:
        raise MXNetError("ONNX export: reshape needs a static shape attr")
    cname = ctx.const_i64(ctx.tmp(n.name), list(shape))
    ctx.node("Reshape", [ins[0], cname], [out], n.name)


def _simple(op_type, **fixed):
    def f(ctx, n, ins, out):
        ctx.node(op_type, ins, [out], n.name, **fixed)
    return f


def _softmax(ctx, n, ins, out):
    ctx.node("Softmax", [ins[0]], [out], n.name,
             axis=int(n.attrs.get("axis", -1)))


def _log_softmax(ctx, n, ins, out):
    ctx.node("LogSoftmax", [ins[0]], [out], n.name,
             axis=int(n.attrs.get("axis", -1)))


def _transpose(ctx, n, ins, out):
    axes = n.attrs.get("axes")
    if axes:
        ctx.node("Transpose", [ins[0]], [out], n.name,
                 perm=tuple(axes))
    else:
        ctx.node("Transpose", [ins[0]], [out], n.name)


def _concat(ctx, n, ins, out):
    ctx.node("Concat", ins, [out], n.name,
             axis=int(n.attrs.get("dim", n.attrs.get("axis", 1))))


def _dropout(ctx, n, ins, out):
    ctx.node("Dropout", [ins[0]], [out], n.name)


def _embedding(ctx, n, ins, out):
    # mx: (data, weight) ; ONNX Gather: (weight, indices)
    ctx.node("Gather", [ins[1], ins[0]], [out], n.name, axis=0)


_TRANSLATORS = {
    "Convolution": _conv,
    "FullyConnected": _fc,
    "BatchNorm": _batchnorm,
    "Activation": _activation,
    "Pooling": _pooling,
    "Flatten": _simple("Flatten", axis=1),
    "flatten": _simple("Flatten", axis=1),
    "reshape": _reshape,
    "Reshape": _reshape,
    "transpose": _transpose,
    "softmax": _softmax,
    "log_softmax": _log_softmax,
    "relu": _simple("Relu"),
    "sigmoid": _simple("Sigmoid"),
    "tanh": _simple("Tanh"),
    "elemwise_add": _simple("Add"),
    "broadcast_add": _simple("Add"),
    "add": _simple("Add"),
    "elemwise_sub": _simple("Sub"),
    "broadcast_sub": _simple("Sub"),
    "subtract": _simple("Sub"),
    "elemwise_mul": _simple("Mul"),
    "broadcast_mul": _simple("Mul"),
    "multiply": _simple("Mul"),
    "elemwise_div": _simple("Div"),
    "broadcast_div": _simple("Div"),
    "divide": _simple("Div"),
    "Concat": _concat,
    "concat": _concat,
    "Dropout": _dropout,
    "dropout": _dropout,
    "Embedding": _embedding,
    "exp": _simple("Exp"),
    "log": _simple("Log"),
    "sqrt": _simple("Sqrt"),
    "abs": _simple("Abs"),
    "negative": _simple("Neg"),
    "identity": _simple("Identity"),
}


def _np_to_tensor(pb, t, name, arr: _np.ndarray):
    t.name = name
    t.dims.extend(arr.shape)
    dt = {_np.dtype(_np.float32): pb.TensorProto.FLOAT,
          _np.dtype(_np.float64): pb.TensorProto.DOUBLE,
          _np.dtype(_np.int32): pb.TensorProto.INT32,
          _np.dtype(_np.int64): pb.TensorProto.INT64,
          _np.dtype(_np.int8): pb.TensorProto.INT8,
          _np.dtype(_np.uint8): pb.TensorProto.UINT8,
          _np.dtype(_np.bool_): pb.TensorProto.BOOL}.get(arr.dtype)
    if dt is None:
        raise MXNetError(f"ONNX export: unsupported dtype {arr.dtype}")
    t.data_type = dt
    t.raw_data = _np.ascontiguousarray(arr).tobytes()


def export_model(sym, params: Dict, input_shapes,
                 input_types=_np.float32, onnx_file_path="model.onnx",
                 verbose=False):
    """Export a Symbol + params dict to an ONNX file (reference:
    onnx_mxnet.export_model).  ``params`` maps arg/aux names (optionally
    'arg:'/'aux:'-prefixed) to NDArray/numpy values; variables without a
    param value become graph inputs, in ``list_arguments`` order matched
    against ``input_shapes``."""
    pb = serde.pb()
    model = pb.ModelProto()
    model.ir_version = 8
    model.producer_name = "incubator_mxnet_tpu"
    model.producer_version = "0.1"
    opset = model.opset_import.add()
    opset.version = _OPSET
    graph = model.graph
    graph.name = getattr(sym, "name", "graph") or "graph"

    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}
    ctx = _Ctx(pb, graph)

    # name each node output; multi-output nodes get :k suffixes
    def out_name(node, k=0):
        return node.name if k == 0 else f"{node.name}_out{k}"

    in_shapes = list(input_shapes) if isinstance(
        input_shapes[0], (tuple, list)) else [tuple(input_shapes)]
    in_types = (list(input_types) if isinstance(input_types, (list, tuple))
                else [input_types] * len(in_shapes))
    if len(in_types) != len(in_shapes):
        raise MXNetError("input_types must match input_shapes")
    elem_types = []
    for t in in_types:
        dt = {_np.dtype(_np.float32): pb.TensorProto.FLOAT,
              _np.dtype(_np.float64): pb.TensorProto.DOUBLE,
              _np.dtype(_np.int32): pb.TensorProto.INT32,
              _np.dtype(_np.int64): pb.TensorProto.INT64}.get(_np.dtype(t))
        if dt is None:
            raise MXNetError(f"ONNX export: unsupported input type {t}")
        elem_types.append(dt)
    next_input = iter(zip(in_shapes, elem_types))

    # fail loudly on edges from secondary outputs: no translator emits
    # output k>0, so such an edge would serialize as a dangling name
    for node in sym._topo():
        for src, k in node.inputs:
            if k > 0 and not src.is_variable:
                raise MXNetError(
                    f"ONNX export: node {node.name!r} consumes output "
                    f"{k} of {src.name!r}; multi-output ops are "
                    "unsupported")

    for node in sym._topo():
        if node.is_variable:
            if node.name in params:
                arr = params[node.name]
                arr = arr.asnumpy() if hasattr(arr, "asnumpy") \
                    else _np.asarray(arr)
                _np_to_tensor(pb, graph.initializer.add(), node.name, arr)
            else:
                vi = graph.input.add()
                vi.name = node.name
                tt = vi.type.tensor_type
                try:
                    shape, et = next(next_input)
                except StopIteration:
                    raise MXNetError(
                        f"no input_shape given for graph input "
                        f"{node.name!r}")
                tt.elem_type = et
                for d in shape:
                    tt.shape.dim.add().dim_value = int(d)
            continue
        fn = _TRANSLATORS.get(node.op)
        if fn is None:
            raise MXNetError(
                f"ONNX export: operator {node.op!r} has no translator "
                f"(node {node.name!r})")
        ins = [out_name(src, k) for src, k in node.inputs]
        fn(ctx, node, ins, out_name(node))

    for out_node, k in sym._outputs:
        vo = graph.output.add()
        vo.name = out_name(out_node, k)
        vo.type.tensor_type.elem_type = pb.TensorProto.FLOAT

    data = model.SerializeToString()
    with open(onnx_file_path, "wb") as f:
        f.write(data)
    if verbose:
        print(f"exported {len(graph.node)} nodes -> {onnx_file_path}")
    return onnx_file_path
