"""``mx.contrib.onnx`` — ONNX export/import (reference:
python/mxnet/contrib/onnx: mx2onnx export_model + onnx2mx
import_model/import_to_gluon).  Self-contained: the IR schema lives
in-tree and compiles with protoc on demand (the image has no onnx
package)."""
from .mx2onnx import export_model
from .onnx2mx import import_model, import_to_gluon

__all__ = ["export_model", "import_model", "import_to_gluon"]
