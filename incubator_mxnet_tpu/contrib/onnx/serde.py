"""ONNX protobuf serde: compiles the in-tree IR schema with protoc on
first use (same on-demand pattern as the native recordio core; the image
has protoc + the protobuf runtime but no onnx package)."""
from __future__ import annotations

import importlib.util
import os
import subprocess
import threading

from ...base import MXNetError

_DIR = os.path.dirname(os.path.abspath(__file__))
_PROTO = os.path.join(_DIR, "onnx_ir.proto")
_PB2 = os.path.join(_DIR, "onnx_ir_pb2.py")

_lock = threading.Lock()
_mod = None


def _compile() -> bool:
    # generate into a per-pid temp dir, then atomic-replace: concurrent
    # processes never exec a half-written module (same pattern as the
    # native recordio build)
    import shutil
    import tempfile
    tmpdir = tempfile.mkdtemp(prefix="onnx_pb2_", dir=_DIR)
    try:
        out = subprocess.run(
            ["protoc", f"--proto_path={_DIR}", f"--python_out={tmpdir}",
             _PROTO],
            capture_output=True, text=True, timeout=120)
        gen = os.path.join(tmpdir, os.path.basename(_PB2))
        if out.returncode != 0 or not os.path.isfile(gen):
            return False
        os.replace(gen, _PB2)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def pb():
    """The generated protobuf module (onnx_ir_pb2)."""
    global _mod
    with _lock:
        if _mod is not None:
            return _mod
        need = (not os.path.isfile(_PB2)
                or os.path.getmtime(_PB2) < os.path.getmtime(_PROTO))
        if need and not _compile():
            raise MXNetError(
                "ONNX support needs protoc (and the protobuf runtime) to "
                "compile the IR schema; protoc compilation failed")
        spec = importlib.util.spec_from_file_location("onnx_ir_pb2", _PB2)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _mod = mod
        return _mod
