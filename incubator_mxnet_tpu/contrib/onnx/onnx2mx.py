"""ONNX → Symbol+params import (reference:
python/mxnet/contrib/onnx/onnx2mx/import_model.py + _op_translations.py).

Builds our Symbol DAG from a GraphProto; initializers become arg_params.
Supports the same opset-13 subset mx2onnx emits, so exported models
round-trip — the validation strategy this environment allows (no onnx
package to checker-validate against, but the protobuf schema guarantees
wire compatibility).
"""
from __future__ import annotations

from typing import Dict

import numpy as _np

from ...base import MXNetError
from . import serde

__all__ = ["import_model", "import_to_gluon"]


def _attr_map(pb, node):
    out = {}
    AT = pb.AttributeProto
    for a in node.attribute:
        if a.type == AT.FLOAT:
            out[a.name] = a.f
        elif a.type == AT.INT:
            out[a.name] = int(a.i)
        elif a.type == AT.STRING:
            out[a.name] = a.s.decode()
        elif a.type == AT.INTS:
            out[a.name] = tuple(int(x) for x in a.ints)
        elif a.type == AT.FLOATS:
            out[a.name] = tuple(float(x) for x in a.floats)
        elif a.type == AT.TENSOR:
            out[a.name] = _tensor_to_np(pb, a.t)
        else:
            raise MXNetError(
                f"ONNX import: attribute type {a.type} unsupported "
                f"({node.op_type}.{a.name})")
    return out


def _tensor_to_np(pb, t) -> _np.ndarray:
    TP = pb.TensorProto
    dt = {TP.FLOAT: _np.float32, TP.DOUBLE: _np.float64,
          TP.INT32: _np.int32, TP.INT64: _np.int64, TP.INT8: _np.int8,
          TP.UINT8: _np.uint8, TP.BOOL: _np.bool_}.get(t.data_type)
    if dt is None:
        raise MXNetError(f"ONNX import: tensor dtype {t.data_type} "
                         "unsupported")
    if t.raw_data:
        arr = _np.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        arr = _np.asarray(list(t.float_data), dt)
    elif t.int64_data:
        arr = _np.asarray(list(t.int64_data), dt)
    elif t.int32_data:
        arr = _np.asarray(list(t.int32_data), dt)
    else:
        arr = _np.zeros(0, dt)
    return arr.reshape(tuple(t.dims))


def _halve_pads(attrs):
    pads = attrs.get("pads")
    if not pads:
        return (0, 0)
    n = len(pads) // 2
    begin, end = pads[:n], pads[n:]
    if tuple(begin) != tuple(end):
        raise MXNetError("ONNX import: asymmetric pads unsupported")
    return tuple(begin)


def import_model(model_file):
    """ONNX file → (sym, arg_params, aux_params) (reference:
    onnx_mxnet.import_model)."""
    sym, arg_params, aux_params, _ = _import(model_file)
    return sym, arg_params, aux_params


def _import(model_file):
    from ... import symbol as S
    from ...ndarray import ndarray as _ndmod

    pb = serde.pb()
    model = pb.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph

    arg_params: Dict = {}
    env: Dict[str, object] = {}     # onnx value name -> Symbol

    for t in g.initializer:
        arr = _tensor_to_np(pb, t)
        arg_params[t.name] = _ndmod.array(
            arr, dtype=_np.float32 if arr.dtype == _np.float64
            else arr.dtype)
        env[t.name] = S.var(t.name)
    graph_inputs = []          # declared order, initializers excluded
    for vi in g.input:
        if vi.name not in env:
            env[vi.name] = S.var(vi.name)
            graph_inputs.append(vi.name)
    consumed = set()
    for node in g.node:
        consumed.update(i for i in node.input if i)
    consumed.update(o.name for o in g.output)

    def ins(node):
        return [env[i] for i in node.input if i]

    shape_consts = set()       # Reshape shape initializers to drop later

    for node in g.node:
        op = node.op_type
        attrs = _attr_map(pb, node)
        i = ins(node)
        name = node.name or node.output[0]
        if op == "Conv":
            kwargs = dict(kernel=attrs["kernel_shape"],
                          stride=attrs.get("strides", 1),
                          pad=_halve_pads(attrs),
                          dilate=attrs.get("dilations", 1),
                          num_group=attrs.get("group", 1),
                          num_filter=0, name=name)
            out = S.Convolution(*i, **kwargs) if len(i) == 3 else \
                S.Convolution(i[0], i[1], no_bias=True, **kwargs)
        elif op == "Gemm":
            if attrs.get("transA", 0) or not attrs.get("transB", 0):
                raise MXNetError("ONNX import: only Gemm(transB=1) maps "
                                 "to FullyConnected")
            if attrs.get("alpha", 1.0) != 1.0 or \
                    attrs.get("beta", 1.0) != 1.0:
                raise MXNetError("ONNX import: Gemm alpha/beta != 1 "
                                 "unsupported")
            out = S.FullyConnected(*i, num_hidden=0, flatten=False,
                                   no_bias=len(i) == 2, name=name)
        elif op == "MatMul":
            out = S.dot(i[0], i[1], name=name)
        elif op == "BatchNormalization":
            out = S.BatchNorm(*i, eps=attrs.get("epsilon", 1e-5),
                              momentum=attrs.get("momentum", 0.9),
                              fix_gamma=False, name=name)
        elif op in ("Relu", "Sigmoid", "Tanh", "Softsign", "Softplus"):
            act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
                   "Softsign": "softsign", "Softplus": "softrelu"}[op]
            out = S.Activation(i[0], act_type=act, name=name)
        elif op in ("MaxPool", "AveragePool"):
            out = S.Pooling(
                i[0], kernel=attrs["kernel_shape"],
                stride=attrs.get("strides", 1), pad=_halve_pads(attrs),
                pool_type="max" if op == "MaxPool" else "avg", name=name)
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            out = S.Pooling(
                i[0], kernel=(1, 1), global_pool=True,
                pool_type="max" if op == "GlobalMaxPool" else "avg",
                name=name)
        elif op == "Flatten":
            if attrs.get("axis", 1) != 1:
                raise MXNetError("ONNX import: Flatten axis != 1")
            out = S.Flatten(i[0], name=name)
        elif op == "Reshape":
            shape_name = node.input[1]
            shape_arr = arg_params.get(shape_name)
            if shape_arr is None:
                raise MXNetError(
                    "ONNX import: Reshape needs a constant shape")
            shape_consts.add(shape_name)
            out = S.reshape(i[0],
                            shape=tuple(int(x) for x in
                                        shape_arr.asnumpy()), name=name)
        elif op == "Transpose":
            out = S.transpose(i[0], axes=attrs.get("perm"), name=name)
        elif op == "Softmax":
            out = S.softmax(i[0], axis=attrs.get("axis", -1), name=name)
        elif op == "LogSoftmax":
            out = S.log_softmax(i[0], axis=attrs.get("axis", -1),
                                name=name)
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": S.broadcast_add, "Sub": S.broadcast_sub,
                  "Mul": S.broadcast_mul, "Div": S.broadcast_div}[op]
            out = fn(i[0], i[1], name=name)
        elif op == "Concat":
            out = S.concat(*i, dim=attrs.get("axis", 1), name=name)
        elif op in ("Dropout", "Identity"):
            out = S.identity(i[0], name=name)
        elif op == "Gather":
            if attrs.get("axis", 0) != 0:
                raise MXNetError("ONNX import: Gather axis != 0")
            out = S.take(i[0], i[1], name=name)
        elif op in ("Exp", "Log", "Sqrt", "Abs", "Neg"):
            fn = {"Exp": S.exp, "Log": S.log, "Sqrt": S.sqrt,
                  "Abs": S.abs, "Neg": S.negative}[op]
            out = fn(i[0], name=name)
        else:
            raise MXNetError(
                f"ONNX import: operator {op!r} has no translator")
        outs = out if isinstance(out, list) else [out]
        for k, oname in enumerate(node.output):
            if k >= len(outs):
                # secondary ONNX output this op doesn't produce (e.g.
                # Dropout mask): fine if nothing reads it, wrong otherwise
                if oname in consumed:
                    raise MXNetError(
                        f"ONNX import: secondary output {oname!r} of "
                        f"{op} is consumed but unsupported")
                continue
            env[oname] = outs[k]

    for sc in shape_consts:
        uses = sum(1 for node in g.node for i in node.input if i == sc)
        reshape_uses = sum(1 for node in g.node
                           if node.op_type == "Reshape"
                           and len(node.input) > 1 and node.input[1] == sc)
        if uses == reshape_uses and sc not in (o.name for o in g.output):
            arg_params.pop(sc, None)

    out_syms = [env[o.name] for o in g.output]
    sym = out_syms[0] if len(out_syms) == 1 else \
        __import__("incubator_mxnet_tpu.symbol",
                   fromlist=["Group"]).Group(out_syms)
    return sym, arg_params, {}, graph_inputs


def import_to_gluon(model_file, ctx=None):
    """ONNX file → runnable SymbolBlock (reference:
    onnx_mxnet.import_to_gluon)."""
    from ...gluon.block import SymbolBlock
    from ... import symbol as S

    # input order follows the ONNX graph's DECLARED input order, not
    # topo order — callers bind positionally per the ONNX contract
    sym, arg_params, aux_params, input_names = _import(model_file)
    inputs = [S.var(n) for n in input_names]
    net = SymbolBlock(sym, inputs)
    net._attach_params({**arg_params, **aux_params})
    return net
