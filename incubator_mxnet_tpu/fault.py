"""Deterministic fault injection + retry with jittered exponential
backoff (the resilience layer; see docs/robustness.md).

Pod-scale TPU training meets transient failures as a matter of course —
preempted hosts, flaky DCN links, slow storage.  The reference's answer
is restart-from-epoch-checkpoint; this module makes failure a
first-class, *testable* runtime concept instead:

* **Fault plan** — an env/API-configurable schedule of injected faults at
  named sites (``MXNET_FAULT_PLAN``).  Sites are plain strings; the
  instrumented ones are ``kvstore.push`` / ``kvstore.pull`` /
  ``kvstore.pushpull`` (transport), ``dataloader.fetch`` and
  ``prefetch.h2d`` (input pipeline: upstream fetch and the prefetcher's
  host-to-device staging), ``checkpoint.write`` (storage),
  ``trainer.grad`` (numerics), the serving pair ``serving.queue`` /
  ``serving.infer``, and ``router.upstream`` (one poll per
  router→replica attempt, so a plan can kill exactly the Nth upstream
  try and drill the failover path).  Kinds: ``ioerror`` (raise a transient
  :class:`FaultInjected`), ``latency`` (sleep), ``nonfinite`` (poison a
  gradient — consumed by the trainer's guard via :func:`take`), and
  ``hang`` (a long stall, default 3600 s, modeling a wedged dispatch —
  the serving watchdog drill injects it at ``serving.infer`` to prove
  hung-worker detection and recovery; docs/robustness.md), and ``crash``
  (hard process death via ``os._exit`` with a configurable exit code,
  modeling a preempted host or OOM-killed replica — the supervisor
  drill injects it inside ``mxtpu-serve`` children to prove
  restart-with-backoff without cooperating with the victim).
  Injection is deterministic: each site keeps a call counter and a rule
  names the 1-based call indices it fires on, so a test or CI run can
  say "the 2nd kvstore push fails" and get exactly that.

  Plan syntax (``;``-separated rules)::

      rule  := site ":" kind [":" arg] ["@" calls]
      calls := N | N-M | "every=" K          (default: 1)

      MXNET_FAULT_PLAN="kvstore.push:ioerror@2;dataloader.fetch:latency:0.05@1-3"

* **Retry** — :func:`retry_call` wraps a callable in retries with
  jittered exponential backoff under a wall-clock deadline
  (:class:`RetryPolicy`; knobs ``MXNET_RETRY_MAX``,
  ``MXNET_RETRY_BASE_SECONDS``, ``MXNET_RETRY_DEADLINE_SECONDS``).  The
  kvstore transport and checkpoint storage writes run through it, so a
  transient failure (injected or real) costs a retry, not the run.

Every injection, retry, give-up, skipped step, and dataloader fallback
is published on the telemetry ``FAULT`` topic and lands in the
``mxtpu_faults_injected`` / ``mxtpu_retries`` / ``mxtpu_giveups`` /
``mxtpu_skipped_steps`` / ``mxtpu_dataloader_fallbacks`` counters
(docs/observability.md).
"""
from __future__ import annotations

import random as _pyrandom
import threading
import time as _time
from typing import Dict, List, Optional

from .base import MXNetError, getenv
from . import telemetry as _telemetry

__all__ = [
    "FaultInjected", "FaultRule", "FaultPlan", "RetryPolicy",
    "install_plan", "clear_plan", "current_plan", "active",
    "inject", "take", "site_calls", "retry_call", "retry_after_hint",
    "TRANSIENT",
]

KINDS = ("ioerror", "latency", "nonfinite", "hang", "crash")

#: Exit code an injected ``crash`` dies with unless the rule names one —
#: distinctive on purpose so a supervisor log line or waitpid status is
#: attributable to the plan rather than to a real SIGKILL/OOM.
CRASH_EXIT_CODE = 86


class FaultInjected(IOError):
    """Raised by an injected ``ioerror`` fault.  An :class:`IOError`
    subclass so the retry layer (and any caller handling real transient
    storage/transport failures) treats it identically."""

    def __init__(self, site: str, rule: "FaultRule"):
        msg = rule.message or f"injected fault at {site} ({rule})"
        super().__init__(msg)
        self.site = site


class FaultRule:
    """One parsed plan rule: which ``kind`` fires at ``site`` on which
    1-based call indices."""

    __slots__ = ("site", "kind", "seconds", "message", "exit_code",
                 "every", "lo", "hi")

    def __init__(self, site: str, kind: str, arg: Optional[str],
                 calls: str):
        if kind not in KINDS:
            raise MXNetError(
                f"fault rule {site!r}: unknown kind {kind!r} "
                f"(expected one of {KINDS})")
        self.site = site
        self.kind = kind
        self.seconds = None
        self.message = None
        self.exit_code = None
        if kind in ("latency", "hang"):
            try:
                self.seconds = float(arg) if arg \
                    else (3600.0 if kind == "hang" else 0.05)
            except ValueError:
                raise MXNetError(
                    f"fault rule {site!r}: {kind} arg {arg!r} is not a "
                    f"number of seconds")
        elif kind == "ioerror":
            self.message = arg
        elif kind == "crash":
            try:
                self.exit_code = int(arg) if arg else CRASH_EXIT_CODE
            except ValueError:
                raise MXNetError(
                    f"fault rule {site!r}: crash arg {arg!r} is not an "
                    f"integer exit code")
        self.every = None
        self.lo = self.hi = None
        try:
            if calls.startswith("every="):
                self.every = int(calls[len("every="):])
                if self.every <= 0:
                    raise ValueError
            elif "-" in calls:
                lo, hi = calls.split("-", 1)
                self.lo, self.hi = int(lo), int(hi)
            else:
                self.lo = self.hi = int(calls)
        except ValueError:
            raise MXNetError(
                f"fault rule {site!r}: bad call spec {calls!r} "
                f"(expected N, N-M, or every=K)")

    def fires(self, n: int) -> bool:
        if self.every is not None:
            return n % self.every == 0
        return self.lo <= n <= self.hi

    def __repr__(self):
        calls = f"every={self.every}" if self.every is not None else (
            str(self.lo) if self.lo == self.hi else f"{self.lo}-{self.hi}")
        if self.seconds is not None:
            arg = f":{self.seconds}"
        elif self.exit_code is not None:
            arg = f":{self.exit_code}"
        else:
            arg = ""
        return f"{self.site}:{self.kind}{arg}@{calls}"


class FaultPlan:
    """Rules grouped by site + thread-safe deterministic call counters."""

    def __init__(self, rules: List[FaultRule]):
        self.rules: Dict[str, List[FaultRule]] = {}
        for r in rules:
            self.rules.setdefault(r.site, []).append(r)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def fire(self, site: str) -> List[FaultRule]:
        """Count one call at ``site``; return the rules that fire on it."""
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
        return [r for r in self.rules.get(site, ()) if r.fires(n)]

    def calls(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def __repr__(self):
        return "FaultPlan(%s)" % "; ".join(
            repr(r) for rs in self.rules.values() for r in rs)


def _parse_plan(spec: str) -> FaultPlan:
    rules = []
    for chunk in spec.split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        body, _, calls = chunk.partition("@")
        parts = body.split(":")
        if len(parts) < 2 or not parts[0].strip():
            raise MXNetError(
                f"fault rule {chunk!r}: expected site:kind[:arg][@calls]")
        site = parts[0].strip()
        kind = parts[1].strip().lower()
        arg = ":".join(parts[2:]).strip() or None
        rules.append(FaultRule(site, kind, arg, calls.strip() or "1"))
    return FaultPlan(rules)


_plan: Optional[FaultPlan] = None


def install_plan(spec) -> FaultPlan:
    """Install a fault plan (a spec string or a :class:`FaultPlan`);
    replaces any current plan and resets call counters."""
    global _plan
    _plan = spec if isinstance(spec, FaultPlan) else _parse_plan(spec)
    return _plan


def clear_plan() -> None:
    global _plan
    _plan = None


def current_plan() -> Optional[FaultPlan]:
    return _plan


def active() -> bool:
    return _plan is not None


def site_calls(site: str) -> int:
    """How many times ``site`` has been polled (0 without a plan)."""
    plan = _plan
    return plan.calls(site) if plan is not None else 0


def inject(site: str, **ctx) -> None:
    """Poll ``site`` against the plan: sleep for ``latency`` rules, raise
    :class:`FaultInjected` for ``ioerror`` rules, die hard
    (``os._exit``) for ``crash`` rules.  A single attribute check when
    no plan is installed — safe on hot paths.  Extra ``ctx`` kwargs
    (``model=``, ``request_id=``, ...) ride along on the FAULT event so
    an injected failure is attributable to the request that hit it
    (docs/observability.md)."""
    plan = _plan
    if plan is None:
        return
    for r in plan.fire(site):
        if r.kind in ("latency", "hang"):
            _telemetry.FAULT.publish(site=site, event="injected",
                                     kind=r.kind, **ctx)
            _time.sleep(r.seconds)
        elif r.kind == "ioerror":
            _telemetry.FAULT.publish(site=site, event="injected",
                                     kind=r.kind, **ctx)
            raise FaultInjected(site, r)
        elif r.kind == "crash":
            # Process death must be ungraceful by design: no atexit, no
            # finally blocks, no flushing of higher layers — exactly what
            # a preempted host looks like to a supervisor.  The FAULT
            # event is published best-effort first so a same-process
            # subscriber (e.g. the flight recorder) can see it before
            # the lights go out.
            _telemetry.FAULT.publish(site=site, event="injected",
                                     kind=r.kind, exit_code=r.exit_code,
                                     **ctx)
            import os as _os
            import sys as _sys
            try:
                _sys.stderr.write(
                    f"fault: injected crash at {site} "
                    f"(exit {r.exit_code})\n")
                _sys.stderr.flush()
            except Exception:
                pass
            _os._exit(r.exit_code)
        # 'nonfinite' rules are consumed via take() at numeric sites


def take(site: str, kind: str, **ctx) -> bool:
    """Poll ``site``; True when a rule of ``kind`` fires on this call.
    Used for faults the *caller* realizes (e.g. the trainer poisons a
    gradient when a ``nonfinite`` rule fires)."""
    plan = _plan
    if plan is None:
        return False
    hit = False
    for r in plan.fire(site):
        if r.kind == kind:
            _telemetry.FAULT.publish(site=site, event="injected",
                                     kind=r.kind, **ctx)
            hit = True
    return hit


# ---------------------------------------------------------------------------
# Retry with jittered exponential backoff + deadline
# ---------------------------------------------------------------------------
# What counts as transient: OS/storage/transport errors (FaultInjected is
# an IOError == OSError).  Framework errors (MXNetError) are NOT retried —
# a missing kvstore key will not fix itself.
TRANSIENT = (OSError, TimeoutError)


class RetryPolicy:
    """Backoff schedule: delay(attempt) = min(max_delay, base *
    multiplier^(attempt-1)), jittered DOWNWARD by up to ``jitter`` so
    synchronized workers de-correlate.  Jitter draws from a seeded
    generator — deterministic per policy instance, reproducible in CI."""

    def __init__(self, max_retries: Optional[int] = None,
                 base_seconds: Optional[float] = None,
                 multiplier: float = 2.0,
                 max_delay_seconds: float = 2.0,
                 deadline_seconds: Optional[float] = None,
                 jitter: float = 0.5, seed: int = 0x5EED):
        self.max_retries = int(getenv("MXNET_RETRY_MAX", 4)) \
            if max_retries is None else int(max_retries)
        self.base_seconds = float(getenv("MXNET_RETRY_BASE_SECONDS", 0.05)) \
            if base_seconds is None else float(base_seconds)
        self.multiplier = float(multiplier)
        self.max_delay_seconds = float(max_delay_seconds)
        self.deadline_seconds = float(
            getenv("MXNET_RETRY_DEADLINE_SECONDS", 30.0)) \
            if deadline_seconds is None else float(deadline_seconds)
        self.jitter = float(jitter)
        self._rng = _pyrandom.Random(seed)

    def delay(self, attempt: int) -> float:
        d = min(self.max_delay_seconds,
                self.base_seconds * (self.multiplier ** (attempt - 1)))
        return d * (1.0 - self.jitter * self._rng.random())


def retry_after_hint(err: BaseException) -> Optional[float]:
    """The default server-provided backoff extractor: a non-negative
    ``retry_after`` attribute on the error (the convention every
    transport error in this codebase follows — ``BreakerOpen``,
    ``QueueFullError``, the router's upstream errors)."""
    hint = getattr(err, "retry_after", None)
    if hint is None:
        return None
    try:
        hint = float(hint)
    except (TypeError, ValueError):
        return None
    return hint if hint >= 0.0 else None


def retry_call(fn, *args, site: str = "?",
               policy: Optional[RetryPolicy] = None,
               retry_on=TRANSIENT, retry_after_hint=None, **kwargs):
    """Call ``fn(*args, **kwargs)``, absorbing up to
    ``policy.max_retries`` transient failures with backoff, under a
    wall-clock deadline.  Each retry publishes a ``FAULT`` ``retry``
    event (→ ``mxtpu_retries``); exhaustion publishes ``giveup``
    (→ ``mxtpu_giveups``) and re-raises the last error.  The success
    path costs one try/except frame — no policy object is built unless
    something actually fails.

    ``retry_after_hint`` is an optional ``error -> Optional[float]``
    extractor for server-provided backoff: when it yields a delay for
    the caught error, that delay replaces the exponential schedule for
    the next attempt (capped at the policy's ``max_delay_seconds`` so a
    hostile upstream cannot park the caller, still counted against the
    retry budget and the wall-clock deadline).  Pass
    :func:`fault.retry_after_hint` to honor the ``retry_after``
    attribute convention used across the serving transport errors."""
    try:
        return fn(*args, **kwargs)
    except retry_on as e:
        err = e
    if policy is None:
        policy = RetryPolicy()
    deadline = _time.monotonic() + policy.deadline_seconds
    attempt = 0
    while True:
        attempt += 1
        delay = policy.delay(attempt)
        hinted = retry_after_hint(err) if retry_after_hint else None
        if hinted is not None:
            delay = min(hinted, policy.max_delay_seconds)
        if attempt > policy.max_retries \
                or _time.monotonic() + delay > deadline:
            _telemetry.FAULT.publish(site=site, event="giveup",
                                     kind=type(err).__name__)
            raise err
        _telemetry.FAULT.publish(site=site, event="retry",
                                 kind=type(err).__name__,
                                 attempt=attempt, seconds=delay,
                                 hinted=hinted is not None)
        _time.sleep(delay)
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            err = e


# env-configured plan (reference-style config plane; docs/env_var.md)
_spec = getenv("MXNET_FAULT_PLAN")
if _spec:
    try:
        install_plan(_spec)
    except MXNetError as _e:
        import warnings
        warnings.warn(f"MXNET_FAULT_PLAN ignored: {_e}")
del _spec
