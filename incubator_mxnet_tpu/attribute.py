"""AttrScope (reference: python/mxnet/attribute.py) — a context manager
that stamps attributes onto every symbol created inside it; the symbol-era
spelling of layer metadata (ctx_group for manual placement, lr_mult /
wd_mult hints, profiler scopes).

TPU note: ``ctx_group``/``__ctx_group__`` is recorded for graph-JSON
fidelity but does not drive placement — SPMD sharding rules replaced the
reference's PlaceDevice pass (SURVEY §2.4)."""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["AttrScope", "current"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


class AttrScope:
    """``with AttrScope(ctx_group='dev1', lr_mult='0.1'):`` — all symbols
    created in the scope carry these attributes (reference: AttrScope).
    Scopes nest; inner values win."""

    def __init__(self, **attrs):
        for v in attrs.values():
            if not isinstance(v, str):
                raise TypeError(
                    "AttrScope values must be strings (reference "
                    "restriction; got %r)" % (v,))
        self._attrs = attrs

    def get(self, attrs=None) -> Dict[str, str]:
        merged = dict(self._attrs)
        if attrs:
            merged.update(attrs)
        return merged

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def current() -> Dict[str, str]:
    """The merged attrs of all active scopes (inner wins)."""
    merged: Dict[str, str] = {}
    for scope in _stack():
        merged.update(scope._attrs)
    return merged
