"""``mx.io`` — data iterators + the RecordIO container (reference:
python/mxnet/io/, python/mxnet/recordio.py, src/io/)."""
from .io import (DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter,
                 PrefetchingIter)
from . import recordio
from .recordio import (MXRecordIO, MXIndexedRecordIO, IndexedRecordIO,
                       IRHeader, pack, unpack, pack_img, unpack_img)
from .image_iter import ImageRecordIter
from .text_iters import CSVIter, LibSVMIter, MNISTIter
from .prefetch import DevicePrefetcher

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter",
           "ResizeIter", "PrefetchingIter", "recordio", "MXRecordIO",
           "MXIndexedRecordIO", "IndexedRecordIO", "IRHeader", "pack",
           "unpack", "pack_img", "unpack_img", "ImageRecordIter",
           "CSVIter", "LibSVMIter", "MNISTIter", "DevicePrefetcher"]
