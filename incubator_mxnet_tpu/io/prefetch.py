"""Double-buffered device-side prefetch for the training loop.

``DevicePrefetcher`` wraps any batch iterable (a gluon ``DataLoader``, a
module-1 ``DataIter`` adapter, a generator) with a background thread
that runs fetch AND the h2d transfer ahead of the consumer, so batch
i+1 is already on device while batch i computes.  The depth (number of
staged batches) defaults to ``MXNET_PREFETCH_BUFFERS`` (2 — classic
double buffering).

Fault semantics (``fault.py`` sites ``dataloader.fetch`` and
``prefetch.h2d``): transient errors — injected or real — are absorbed by
``retry_call``; when retries exhaust, the pipeline DEGRADES to blocking
in-order fetch on the consumer thread instead of deadlocking or dropping
a batch.  The invariant making that safe: the worker polls the
fault-injection site BEFORE consuming from the upstream iterator, so a
failed attempt never loses a batch, and the degrade marker rides the
same FIFO queue as the data, so order is preserved to the batch.

This is the reference's ``io.PrefetchingIter`` / dataloader pin_memory
idea rebuilt for an accelerator runtime: what is staged ahead is not a
host tensor but the DEVICE-resident (optionally mesh-sharded, via
``placement=``) batch.
"""
from __future__ import annotations

import queue as _queue
import threading
import time as _time

from ..base import getenv_int
from .. import fault as _fault
from .. import telemetry as _telemetry

__all__ = ["DevicePrefetcher"]

_OK, _END, _ERR, _DEGRADE = 0, 1, 2, 3


class _UpstreamError(Exception):
    """An error raised from INSIDE the upstream iterator (as opposed to
    the prefetcher's own injection poll).  Deliberately NOT transient: a
    generator that raised is dead, so a retry would ``next()`` a dead
    iterator and silently truncate the stream — propagate to the
    consumer instead (the in-process DataLoader's documented
    behavior)."""

    def __init__(self, orig):
        super().__init__(str(orig))
        self.orig = orig


def _unwrap(a):
    from ..ndarray.ndarray import NDArray
    return a._data if isinstance(a, NDArray) else a


class DevicePrefetcher:
    """Iterate ``source`` with fetch + h2d staged ``buffers`` deep.

    ``placement`` is an optional callable applied to every array of a
    batch (e.g. ``SPMDTrainer._shard_batch`` for mesh sharding); default
    is a plain ``jax.device_put``.  Close (or exhaust) the iterator to
    join the worker; it is also a context manager.
    """

    def __init__(self, source, placement=None, buffers=None,
                 fetch_site="dataloader.fetch", h2d_site="prefetch.h2d"):
        self._it = iter(source)
        self._placement = placement
        self._buffers = int(buffers) if buffers is not None \
            else max(getenv_int("MXNET_PREFETCH_BUFFERS", 2), 1)
        self._fetch_site = fetch_site
        self._h2d_site = h2d_site
        self._q = _queue.Queue(maxsize=self._buffers)
        self._stop = threading.Event()
        self._degraded = False
        self._batches = 0
        self._wait_seconds = 0.0
        self._thread = threading.Thread(
            target=self._worker, name="mxtpu-prefetch", daemon=True)
        self._thread.start()

    # -------------------------------------------------- worker side
    def _fetch_upstream(self):
        # poll the injection site BEFORE touching the iterator: a raised
        # fault then costs a retry, never a batch
        _fault.inject(self._fetch_site)
        try:
            return next(self._it)
        except StopIteration:
            raise
        except Exception as e:
            raise _UpstreamError(e) from e

    def _place(self, batch):
        single = not isinstance(batch, (tuple, list))
        arrs = (batch,) if single else tuple(batch)
        if self._placement is not None:
            placed = tuple(self._placement(a) for a in arrs)
        else:
            import jax
            placed = tuple(jax.device_put(_unwrap(a)) for a in arrs)
        return placed[0] if single else placed

    def _transfer(self, batch):
        _fault.inject(self._h2d_site)
        placed = self._place(batch)
        if _telemetry.TRANSFER.subscribers:
            arrs = placed if isinstance(placed, tuple) else (placed,)
            nbytes = sum(int(getattr(a, "nbytes", 0)) for a in arrs)
            _telemetry.TRANSFER.publish(direction="h2d", nbytes=nbytes)
        return placed

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _worker(self):
        while not self._stop.is_set():
            try:
                batch = _fault.retry_call(self._fetch_upstream,
                                          site=self._fetch_site)
            except StopIteration:
                self._put((_END, None))
                return
            except _UpstreamError as e:
                self._put((_ERR, e.orig))
                return
            except _fault.TRANSIENT:
                # retries exhausted: hand the iterator back to the
                # consumer for blocking in-order fetch (no batch was
                # consumed — inject precedes next())
                self._put((_DEGRADE, None))
                return
            except Exception as e:          # real upstream bug
                self._put((_ERR, e))
                return
            try:
                placed = _fault.retry_call(self._transfer, batch,
                                           site=self._h2d_site)
            except _fault.TRANSIENT:
                # the batch IS fetched but not transferred — ship it raw
                # so the consumer places it synchronously, in order
                self._put((_DEGRADE, batch))
                return
            except Exception as e:
                self._put((_ERR, e))
                return
            if not self._put((_OK, placed)):
                return

    # -------------------------------------------------- consumer side
    def __iter__(self):
        return self

    def __next__(self):
        if self._degraded:
            return self._fetch_blocking()
        t0 = _time.perf_counter()
        with _telemetry.trace_span("prefetch.wait", cat="dataloader"):
            while True:
                try:
                    tag, payload = self._q.get(timeout=0.5)
                    break
                except _queue.Empty:
                    if self._thread is None or \
                            not self._thread.is_alive():
                        # worker died without a terminal marker —
                        # degrade rather than deadlock
                        tag, payload = _DEGRADE, None
                        break
        wait = _time.perf_counter() - t0
        self._wait_seconds += wait
        if _telemetry.DATALOADER.subscribers:
            _telemetry.DATALOADER.publish(seconds=wait)
        if tag == _OK:
            self._batches += 1
            return payload
        if tag == _END:
            self.close()
            raise StopIteration
        if tag == _ERR:
            self.close()
            raise payload
        # _DEGRADE: continue synchronously on this thread, in order
        self._degraded = True
        _telemetry.FAULT.publish(
            site=self._h2d_site if payload is not None
            else self._fetch_site, event="fallback")
        if payload is not None:
            # the worker's fetched-but-untransferred batch: transfer it
            # here — through the same fault site + TRANSFER telemetry as
            # every other batch — so nothing is lost or reordered and
            # h2d byte accounting stays exact for the batch that
            # triggered the degrade
            placed = _fault.retry_call(self._transfer, payload,
                                       site=self._h2d_site)
            self._batches += 1
            return placed
        return self._fetch_blocking()

    def _fetch_blocking(self):
        try:
            batch = _fault.retry_call(self._fetch_upstream,
                                      site=self._fetch_site)
        except _UpstreamError as e:
            raise e.orig
        placed = _fault.retry_call(self._transfer, batch,
                                   site=self._h2d_site)
        self._batches += 1
        return placed

    # -------------------------------------------------- lifecycle
    def close(self):
        """Stop the worker and join it; idempotent."""
        self._stop.set()
        # drain so a worker blocked on put() observes the stop flag
        try:
            while True:
                self._q.get_nowait()
        except _queue.Empty:
            pass
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def stats(self):
        """{'batches', 'wait_seconds', 'degraded', 'buffers'} — consumer
        wait_seconds ≈ 0 means fetch+h2d fully overlapped compute."""
        return {"batches": self._batches,
                "wait_seconds": round(self._wait_seconds, 6),
                "degraded": self._degraded,
                "buffers": self._buffers}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
