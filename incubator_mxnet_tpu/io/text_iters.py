"""File-backed legacy iterators: CSVIter, LibSVMIter, MNISTIter
(reference: src/io/iter_csv.cc, iter_libsvm.cc, iter_mnist.cc — the
C++-backed DataIters exposed as mx.io.*).

TPU-native re-design: parsing happens once into numpy at construction
(these formats are small-data-era; the packed RecordIO path is the
scale path), batching reuses NDArrayIter's padded round-robin
semantics.  LibSVMIter emits CSRNDArray batches like the reference.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as _np

from ..base import MXNetError
from .io import DataBatch, DataDesc, DataIter, NDArrayIter

__all__ = ["CSVIter", "LibSVMIter", "MNISTIter"]


class CSVIter(NDArrayIter):
    """Batches from a CSV of floats (reference: io.CSVIter).

    data_csv/label_csv: paths; data_shape/label_shape: per-sample
    shapes (rows are reshaped)."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=1, round_batch=True,
                 **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32,
                           ndmin=2)
        n = data.shape[0]
        data = data.reshape((n,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",",
                                dtype=_np.float32, ndmin=2)
            if label.shape[0] != n:
                raise MXNetError(
                    f"CSVIter: label file has {label.shape[0]} rows but "
                    f"data file has {n}")
            label = label.reshape((n,) + tuple(label_shape))
        # reference semantics: round_batch pads/rolls the last partial
        # batch; round_batch=0 discards it
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="roll_over" if round_batch
                         else "discard")


class LibSVMIter(DataIter):
    """Batches of CSRNDArray from a libsvm-format file (reference:
    io.LibSVMIter): lines ``label idx:val idx:val ...`` with ZERO-based
    feature indices (the reference's convention); ``data_shape`` fixes
    the feature dimension.  ``label_libsvm`` optionally reads labels
    from a separate libsvm file (first column per line).  The trailing
    partial batch is padded with wrap-around samples and reported via
    ``getpad()`` like NDArrayIter."""

    @staticmethod
    def _parse(path):
        labels, indptr, indices, values = [], [0], [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    idx, val = tok.split(":")
                    indices.append(int(idx))
                    values.append(float(val))
                indptr.append(len(indices))
        return (_np.asarray(labels, _np.float32),
                _np.asarray(indptr, _np.int64),
                _np.asarray(indices, _np.int64),
                _np.asarray(values, _np.float32))

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, **kwargs):
        self._dim = int(data_shape[0] if isinstance(
            data_shape, (tuple, list)) else data_shape)
        self._label, self._indptr, self._indices, self._values = \
            self._parse(data_libsvm)
        if label_libsvm is not None:
            lab, _, _, _ = self._parse(label_libsvm)
            if len(lab) != len(self._label):
                raise MXNetError(
                    f"LibSVMIter: label file has {len(lab)} rows but "
                    f"data file has {len(self._label)}")
            self._label = lab
        if len(self._indices) and self._indices.max() >= self._dim:
            raise MXNetError(
                f"LibSVMIter: feature index {self._indices.max()} "
                f">= data_shape {self._dim} (indices are zero-based)")
        super().__init__(batch_size)
        self._n = len(self._label)
        if self._n < batch_size:
            raise MXNetError("LibSVMIter: fewer samples than batch_size")
        self._cursor = 0
        self._pad = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._dim))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0
        self._pad = 0

    def iter_next(self):
        return self._cursor < self._n

    def getpad(self):
        return self._pad

    def _rows(self, idx):
        """CSR pieces for sample rows ``idx`` (list of ints)."""
        vals, inds, ptr = [], [], [0]
        for i in idx:
            lo, hi = self._indptr[i], self._indptr[i + 1]
            vals.append(self._values[lo:hi])
            inds.append(self._indices[lo:hi])
            ptr.append(ptr[-1] + (hi - lo))
        return (_np.concatenate(vals) if vals else
                _np.zeros(0, _np.float32),
                _np.concatenate(inds) if inds else
                _np.zeros(0, _np.int64),
                _np.asarray(ptr, _np.int64))

    def next(self):
        if not self.iter_next():
            raise StopIteration
        from ..ndarray import sparse as _sp
        from ..ndarray.ndarray import array as _array
        s = self._cursor
        e = min(s + self.batch_size, self._n)
        self._pad = self.batch_size - (e - s)
        # pad wraps around to the start (reference pad semantics)
        rows = list(range(s, e)) + list(range(self._pad))
        self._cursor = s + self.batch_size
        vals, inds, ptr = self._rows(rows)
        csr = _sp.csr_matrix((vals, inds, ptr),
                             shape=(self.batch_size, self._dim))
        label = _array(self._label[rows])
        return DataBatch(data=[csr], label=[label], pad=self._pad)


def _read_idx(path):
    """Read an MNIST idx file (optionally .gz)."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return _np.frombuffer(f.read(), _np.uint8).reshape(shape)


class MNISTIter(NDArrayIter):
    """Batches from raw MNIST idx files (reference: io.MNISTIter).

    image/label: paths to ``train-images-idx3-ubyte``-style files
    (``.gz`` accepted); ``flat=True`` yields (B, 784) else
    (B, 1, 28, 28); pixel values scaled to [0, 1] like the reference."""

    def __init__(self, image, label, batch_size=1, shuffle=False,
                 flat=False, seed=0, **kwargs):
        for p in (image, label):
            if not os.path.exists(p):
                raise MXNetError(f"MNISTIter: file not found: {p}")
        imgs = _read_idx(image).astype(_np.float32) / 255.0
        labs = _read_idx(label).astype(_np.float32)
        if imgs.shape[0] != labs.shape[0]:
            raise MXNetError("MNISTIter: image/label count mismatch")
        imgs = imgs.reshape(imgs.shape[0], -1) if flat \
            else imgs.reshape(imgs.shape[0], 1, *imgs.shape[1:])
        if shuffle:
            order = _np.random.RandomState(seed).permutation(
                imgs.shape[0])
            imgs, labs = imgs[order], labs[order]
        super().__init__(imgs, labs, batch_size=batch_size)
