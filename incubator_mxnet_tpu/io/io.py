"""Legacy data iterators (reference: python/mxnet/io/io.py).

``DataIter``/``DataBatch``/``DataDesc`` plus ``NDArrayIter`` and
``ResizeIter`` — the Module-era input pipeline.  The reference's C++-backed
``ImageRecordIter`` lives in ``incubator_mxnet_tpu.recordio`` once built;
Gluon ``DataLoader`` is the modern path.
"""
from __future__ import annotations

from collections import namedtuple
from typing import Dict, List, Optional, Sequence, Union

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter",
           "ResizeIter", "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout: Optional[str]) -> int:
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """One mini-batch (reference: io.DataBatch)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [getattr(d, "shape", None) for d in (self.data or [])]
        return f"DataBatch: data shapes: {shapes} pad: {self.pad}"


class DataIter:
    """Iterator base (reference: io.DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty, default_name):
    """Normalize to list of (name, numpy array) (reference: io._init_data)."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data must be provided")
        return []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        if not allow_empty and len(data) == 0:
            raise MXNetError("data cannot be empty")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, _np.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.NDArrayIter): dict or
    list or single array for data/label; pad/discard/roll_over last-batch
    handling; optional shuffle per reset."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        for k, v in self.data + self.label:
            if v.shape[0] != self.num_data:
                raise MXNetError(
                    f"all arrays must have the same length; '{k}' has "
                    f"{v.shape[0]} != {self.num_data}")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) \
                // batch_size
        self._order = _np.arange(self.num_data)
        self.reset()

    @property
    def provide_data(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self) -> List[DataDesc]:
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            _np.random.shuffle(self._order)

    def hard_reset(self):
        self.reset()

    def iter_next(self) -> bool:
        self.cursor += self.batch_size
        return self.cursor < self.num_data and not (
            self.last_batch_handle == "discard"
            and self.cursor + self.batch_size > self.num_data)

    def _slice(self, arrays):
        out = []
        start = self.cursor
        stop = min(start + self.batch_size, self.num_data)
        idx = self._order[start:stop]
        pad = self.batch_size - len(idx)
        if pad and self.last_batch_handle in ("pad", "roll_over"):
            idx = _np.concatenate([idx, self._order[:pad]])
        for _, v in arrays:
            out.append(nd.array(v[idx]))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self) -> int:
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        stop = min(self.cursor + self.batch_size, self.num_data)
        return self._order[self.cursor:stop].copy()


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch (reference:
    io.ResizeIter — used to stretch small datasets)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iters (reference:
    io.PrefetchingIter; the heavy lifting the reference does in C++
    PrefetcherIter happens here with a Python thread — device transfers
    are already async under jax)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import threading
        import queue
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter supports a single iter")
        self.data_iter = iters[0]
        super().__init__(self.data_iter.batch_size)
        self._queue: "queue.Queue" = queue.Queue(maxsize=4)
        self._thread = None
        self._threading = threading
        self._queue_mod = queue
        self._start()

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def _start(self):
        def worker():
            try:
                for batch in self.data_iter:
                    self._queue.put(batch)
            finally:
                self._queue.put(None)
        self._thread = self._threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        if self._thread is not None:
            self._thread.join()
        self.data_iter.reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        try:
            self._peek = self.next()
            return True
        except StopIteration:
            return False
