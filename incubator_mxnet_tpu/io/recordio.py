"""RecordIO: the dmlc packed-record container format.

Byte-compatible with the reference's dmlc-core RecordIO (reference:
3rdparty/dmlc-core/include/dmlc/recordio.h, python/mxnet/recordio.py) so
``.rec``/``.idx`` datasets packed by the reference's ``im2rec`` tools load
here unchanged and vice versa:

* stream = sequence of records, each ``[kMagic u32le][lrec u32le][payload]
  [pad to 4B]`` where ``lrec`` packs ``cflag`` in the top 3 bits and the
  payload length in the low 29 bits;
* payloads longer than 2^29-1 are split into continuation records with
  cflag 1 (start) / 2 (middle) / 3 (end); cflag 0 = whole record;
* ``IndexedRecordIO`` adds a text ``.idx`` sidecar of ``key\\tposition``
  lines for random access;
* ``pack``/``unpack`` add the MXNet image-record header ``IRHeader``
  (struct ``IfQQ``: flag, label, id, id2) with multi-label payloads
  inlined after the header (flag = label count).

TPU-first note: this is deliberately plain Python file IO — the decode /
augment compute happens in DataLoader workers (gluon.data) or the
ImageRecordIter thread pool; the arrays XLA sees are already batched.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as _np

from ..base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IndexedRecordIO",
           "IRHeader", "pack", "unpack", "pack_img", "unpack_img"]

_kMagic = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", _kMagic)
_LEN_MASK = (1 << 29) - 1
_CFLAG_WHOLE, _CFLAG_START, _CFLAG_MIDDLE, _CFLAG_END = 0, 1, 2, 3


def _pad4(n: int) -> int:
    return (4 - n % 4) % 4


class MXRecordIO:
    """Sequential reader/writer over a RecordIO file (reference:
    python/mxnet/recordio.py MXRecordIO; dmlc RecordIOWriter/Reader)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.fp = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.fp = open(self.uri, "wb")
        elif self.flag == "r":
            self.fp = open(self.uri, "rb")
        else:
            raise MXNetError(f"Invalid flag {self.flag!r} (use 'r'/'w')")
        self.writable = self.flag == "w"

    def close(self):
        if self.fp is not None:
            self.fp.close()
            self.fp = None

    def __del__(self):
        self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # pickling support so DataLoader worker processes can reopen the file
    def __getstate__(self):
        d = dict(self.__dict__)
        d["fp"] = None
        if self.writable:
            raise MXNetError("cannot pickle a writable MXRecordIO")
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        """Rewind the read cursor."""
        self.close()
        self.open()

    def tell(self) -> int:
        return self.fp.tell()

    def write(self, buf: bytes):
        if not self.writable:
            raise MXNetError("not opened for writing")
        data = memoryview(buf)
        n = len(data)
        if n <= _LEN_MASK:
            chunks = [(_CFLAG_WHOLE, data)]
        else:
            chunks = []
            off = 0
            while off < n:
                size = min(_LEN_MASK, n - off)
                last = off + size >= n
                cflag = (_CFLAG_START if off == 0 else
                         (_CFLAG_END if last else _CFLAG_MIDDLE))
                chunks.append((cflag, data[off:off + size]))
                off += size
        for cflag, piece in chunks:
            lrec = (cflag << 29) | len(piece)
            self.fp.write(_MAGIC_BYTES)
            self.fp.write(struct.pack("<I", lrec))
            self.fp.write(piece)
            self.fp.write(b"\x00" * _pad4(len(piece)))

    def _read_one(self):
        head = self.fp.read(8)
        if len(head) < 8:
            return None, None
        magic, lrec = struct.unpack("<II", head)
        if magic != _kMagic:
            raise MXNetError(
                f"corrupt RecordIO: bad magic {magic:#x} at "
                f"{self.fp.tell() - 8} in {self.uri}")
        cflag, length = lrec >> 29, lrec & _LEN_MASK
        payload = self.fp.read(length)
        if len(payload) != length:
            raise MXNetError(f"corrupt RecordIO: truncated record in "
                             f"{self.uri}")
        self.fp.read(_pad4(length))
        return cflag, payload

    def read(self):
        """Read the next logical record; None at EOF."""
        if self.writable:
            raise MXNetError("not opened for reading")
        cflag, payload = self._read_one()
        if cflag is None:
            return None
        if cflag == _CFLAG_WHOLE:
            return payload
        if cflag != _CFLAG_START:
            raise MXNetError("corrupt RecordIO: continuation without start")
        parts = [payload]
        while True:
            cflag, payload = self._read_one()
            if cflag is None:
                raise MXNetError("corrupt RecordIO: unterminated record")
            parts.append(payload)
            if cflag == _CFLAG_END:
                return b"".join(parts)
            if cflag != _CFLAG_MIDDLE:
                raise MXNetError("corrupt RecordIO: bad continuation flag")


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO via a ``key\\tposition`` text index
    (reference: python/mxnet/recordio.py MXIndexedRecordIO)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    k, pos = line.split("\t")
                    k = key_type(k)
                    self.idx[k] = int(pos)
                    self.keys.append(k)

    def close(self):
        if self.fp is not None and self.writable:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def __getstate__(self):
        d = super().__getstate__()
        return d

    def seek(self, idx):
        """Position the read cursor at record ``idx`` (a key)."""
        if self.writable:
            raise MXNetError("seek on a writable IndexedRecordIO")
        self.fp.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf: bytes):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# reference also exposes the shorter alias
IndexedRecordIO = MXIndexedRecordIO


# ---------------------------------------------------------------------------
# image-record packing (reference: python/mxnet/recordio.py pack/unpack)
# ---------------------------------------------------------------------------
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Serialize header+payload.  Scalar label lives in the header; a
    label vector is inlined (float32) after it with flag = its length."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (int, float)):
        return struct.pack(_IR_FORMAT, header.flag, float(label),
                           header.id, header.id2) + s
    arr = _np.asarray(label, dtype=_np.float32).ravel()
    packed = struct.pack(_IR_FORMAT, len(arr), 0.0, header.id, header.id2)
    return packed + arr.tobytes() + s


def unpack(s: bytes):
    """Inverse of :func:`pack` → (IRHeader, payload bytes)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    payload = s[_IR_SIZE:]
    if flag > 0:
        n = flag * 4
        labels = _np.frombuffer(payload[:n], dtype=_np.float32)
        return IRHeader(flag, labels, id_, id2), payload[n:]
    return IRHeader(flag, label, id_, id2), payload


def pack_img(header: IRHeader, img, quality=95, img_fmt=".jpg") -> bytes:
    """Encode an HWC uint8 image (RGB) and pack it (reference: pack_img;
    codec is PIL here instead of cv2 — byte output is standard JPEG/PNG
    either way)."""
    import io as _io
    from PIL import Image
    arr = _np.asarray(img)
    if arr.ndim == 2:
        pil = Image.fromarray(arr, mode="L")
    else:
        pil = Image.fromarray(arr[..., :3].astype(_np.uint8))
    buf = _io.BytesIO()
    fmt = img_fmt.lower().lstrip(".")
    if fmt in ("jpg", "jpeg"):
        pil.save(buf, format="JPEG", quality=quality)
    elif fmt == "png":
        pil.save(buf, format="PNG")
    else:
        raise MXNetError(f"unsupported img_fmt {img_fmt!r}")
    return pack(header, buf.getvalue())


def unpack_img(s: bytes, iscolor=1):
    """Inverse of :func:`pack_img` → (IRHeader, HWC uint8 ndarray)."""
    import io as _io
    from PIL import Image
    header, payload = unpack(s)
    pil = Image.open(_io.BytesIO(payload))
    pil = pil.convert("RGB" if iscolor else "L")
    return header, _np.asarray(pil)
