"""ImageRecordIter: batched, augmented iteration over RecordIO image
packs (reference: src/io/iter_image_recordio_2.cc ImageRecordIter2 +
image_aug_default.cc, surfaced as mx.io.ImageRecordIter).

The reference decodes/augments on C++ threads; here a thread pool does
PIL JPEG decode (libjpeg releases the GIL) + numpy augmentation, and
batches are prefetched on a background thread so the accelerator step
never waits on input (SURVEY §2.1 Data IO).
"""
from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from .io import DataBatch, DataDesc, DataIter
from .recordio import MXIndexedRecordIO, MXRecordIO, unpack, unpack_img

__all__ = ["ImageRecordIter"]


class ImageRecordIter(DataIter):
    """Iterate (data, label) batches from a ``.rec`` image pack.

    Supported reference params: path_imgrec, path_imgidx, data_shape
    (C,H,W), batch_size, shuffle, rand_crop, rand_mirror, mean_r/g/b,
    std_r/g/b, scale, label_width, preprocess_threads, round_batch,
    resize (shortest edge), seed.
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, rand_crop=False,
                 rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 label_width=1, preprocess_threads=4, round_batch=True,
                 resize=-1, seed=0, **kwargs):
        super().__init__(batch_size)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (channels, H, W)")
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = int(label_width)
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mean = _np.array([mean_r, mean_g, mean_b],
                              _np.float32).reshape(3, 1, 1)
        self.std = _np.array([std_r, std_g, std_b],
                             _np.float32).reshape(3, 1, 1)
        self.scale = float(scale)
        self.resize = int(resize)
        self.round_batch = round_batch
        self._rng = _np.random.default_rng(seed)
        self._pool = ThreadPoolExecutor(max_workers=max(
            1, int(preprocess_threads)))

        from .. import native as _native
        if path_imgidx is None:
            guess = os.path.splitext(path_imgrec)[0] + ".idx"
            path_imgidx = guess if os.path.isfile(guess) else None
        if path_imgidx is not None:
            self._record = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self._positions = [self._record.idx[k]
                               for k in self._record.keys]
        else:
            # no sidecar index: build in-memory offsets — the native C
            # scanner when available, else one Python pass
            self._record = None
            self._positions = _native.scan_index(path_imgrec)
            if self._positions is None:
                self._record = MXRecordIO(path_imgrec, "r")
                self._positions = []
                while True:
                    pos = self._record.tell()
                    if self._record.read() is None:
                        break
                    self._positions.append(pos)
        self._path_imgrec = path_imgrec
        # one shared native reader (pread: thread-safe, no cursor) when
        # the C core builds; per-thread Python handles otherwise
        try:
            self._native_reader = _native.NativeRecordReader(path_imgrec)
        except OSError:
            self._native_reader = None
        self._tls = threading.local()   # per-thread fallback handles
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self._order = _np.arange(len(self._positions))
        if self.shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0

    def _read_at(self, pos):
        # native pread reader: one fd, lock-free across the decode pool
        # (the C-core analog of the reference's per-parser readers,
        # src/io/iter_image_recordio_2.cc)
        if self._native_reader is not None:
            return self._native_reader.read_at(pos)
        # fallback: per-thread Python file handles
        rec = getattr(self._tls, "record", None)
        if rec is None:
            rec = MXRecordIO(self._path_imgrec, "r")
            self._tls.record = rec
        rec.fp.seek(pos)
        return rec.read()

    def _decode_one(self, pos):
        rec = self._read_at(pos)
        header, img = unpack_img(rec, iscolor=1 if self.data_shape[0] == 3
                                 else 0)
        img = img.astype(_np.float32)
        if img.ndim == 2:
            img = img[:, :, None]
        C, H, W = self.data_shape
        if self.resize > 0:
            img = _resize_shorter(img, self.resize)
        img = self._crop(img, H, W)
        if self.rand_mirror and self._rng.random() < 0.5:
            img = img[:, ::-1, :]
        chw = img.transpose(2, 0, 1)[:C]
        chw = (chw - self.mean[:C]) / self.std[:C]
        if self.scale != 1.0:
            chw = chw * self.scale
        label = header.label
        if self.label_width == 1:
            lab = _np.float32(label if _np.isscalar(label) else
                              _np.asarray(label).ravel()[0])
        else:
            lab = _np.zeros((self.label_width,), _np.float32)
            arr = _np.atleast_1d(_np.asarray(label, _np.float32))
            lab[:min(self.label_width, arr.size)] = \
                arr[:self.label_width]
        return chw.astype(_np.float32), lab

    def _crop(self, img, H, W):
        h, w = img.shape[:2]
        if h < H or w < W:  # upscale small images so the crop fits
            img = _resize_shorter(img, max(H, W))
            h, w = img.shape[:2]
        if self.rand_crop:
            y0 = int(self._rng.integers(0, h - H + 1))
            x0 = int(self._rng.integers(0, w - W + 1))
        else:
            y0, x0 = (h - H) // 2, (w - W) // 2
        return img[y0:y0 + H, x0:x0 + W, :]

    def iter_next(self):
        return self._cursor < len(self._positions)

    def next(self):
        if not self.iter_next():
            raise StopIteration
        idx = self._order[self._cursor:self._cursor + self.batch_size]
        pad = self.batch_size - len(idx)
        if pad:
            if not self.round_batch:
                idx = idx  # partial batch
            else:  # wrap from the epoch start, reference round_batch=1
                idx = _np.concatenate([idx, self._order[:pad]])
        self._cursor += self.batch_size
        results = list(self._pool.map(
            self._decode_one, [self._positions[i] for i in idx]))
        data = _np.stack([r[0] for r in results])
        label = _np.stack([r[1] for r in results])
        return DataBatch(data=[nd.array(data)], label=[nd.array(label)],
                         pad=pad if self.round_batch else 0,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


def _resize_shorter(img, size):
    """Resize so the shorter edge equals ``size`` (PIL bilinear)."""
    from PIL import Image
    h, w = img.shape[:2]
    if h < w:
        new_h, new_w = size, max(1, int(round(w * size / h)))
    else:
        new_h, new_w = max(1, int(round(h * size / w))), size
    if (new_h, new_w) == (h, w):
        return img
    pil = Image.fromarray(img.astype(_np.uint8).squeeze()
                          if img.shape[2] == 1 else img.astype(_np.uint8))
    pil = pil.resize((new_w, new_h), Image.BILINEAR)
    out = _np.asarray(pil, _np.float32)
    if out.ndim == 2:
        out = out[:, :, None]
    return out
