"""Device-memory observability plane + on-demand profiler capture
(docs/observability.md "Device plane").

Three cooperating pieces, all riding the shared telemetry spine:

* **Per-owner HBM attribution** — the runtime already knew who owns
  device memory (the KV :class:`~.serving.kvcache.BlockPool`, engine
  parameters, the ZeRO-1 optimizer shard) but each exporter spoke its
  own dialect.  Owners register a byte-count callback here
  (:func:`register_owner`) and :func:`sample` folds them into one
  labeled gauge, ``mxtpu_device_owned_bytes{owner=...}``, next to the
  whole-process ``mx_device_*`` gauges telemetry already samples.  The
  remainder (live jax array bytes no owner claims) lands in
  ``mxtpu_device_unattributed_bytes`` — a growing unattributed share is
  the classic slow leak.
* **OOM forensics** — a ``RESOURCE_EXHAUSTED`` dispatch failure
  (detected by :func:`is_oom` at the engine dispatch funnel, or an
  injected ``serving.infer:ioerror:RESOURCE_EXHAUSTED...`` fault)
  publishes a FAULT ``event="oom"`` which triggers a debounced
  FlightRecorder dump (``telemetry_ring``).  This module registers the
  two providers that make such a dump actionable: ``device_memory``
  (:func:`memory_breakdown` — per-device stats + per-owner bytes) and
  ``programs`` (:func:`program_report` — the dispatch ledger plus every
  live engine's program inventory and per-slot KV occupancy).
* **Profiler capture** — :func:`capture_profile` wraps
  ``jax.profiler.start_trace``/``stop_trace`` with a single-capture
  guard, writing one artifact directory per capture under
  ``MXNET_PROFILE_DIR`` (default ``<tmpdir>/mxtpu_profile``).  Works on
  the CPU backend, so the serving route (``POST /debug/profile``) and
  the router fan-out round-trip in tests without a TPU.

A background sampler (:func:`start_sampler`) refreshes the memory
gauges every ``MXNET_DEVICE_MEM_INTERVAL_SECONDS`` (0 = disabled, the
default); exporters also refresh on scrape, so the sampler only matters
for processes nobody scrapes.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Callable, Dict, Optional

from .base import MXNetError, getenv, getenv_float
from . import telemetry as _telemetry
from . import telemetry_ring as _ring

__all__ = [
    "register_owner", "unregister_owner", "owned_bytes",
    "register_inventory", "unregister_inventory",
    "memory_breakdown", "program_report", "sample",
    "start_sampler", "stop_sampler",
    "is_oom", "report_oom",
    "CaptureBusy", "capture_profile", "capture_active",
    "default_profile_dir", "default_sample_interval",
]


def default_profile_dir() -> str:
    """``MXNET_PROFILE_DIR``: where profiler capture artifacts land."""
    return getenv("MXNET_PROFILE_DIR") \
        or os.path.join(tempfile.gettempdir(), "mxtpu_profile")


def default_sample_interval() -> float:
    """``MXNET_DEVICE_MEM_INTERVAL_SECONDS``: background memory-gauge
    sampling cadence (0 disables the sampler thread)."""
    return getenv_float("MXNET_DEVICE_MEM_INTERVAL_SECONDS", 0.0)


_g_owned = _telemetry.registry.gauge(
    "mxtpu_device_owned_bytes",
    "attributed device bytes, by owner (kv:<model>/params:<model>/"
    "optimizer)")
_g_unattributed = _telemetry.registry.gauge(
    "mxtpu_device_unattributed_bytes",
    "live jax array bytes no registered owner claims")
_c_captures = _telemetry.registry.counter(
    "mxtpu_profile_captures",
    "completed on-demand profiler captures")
_c_oom = _telemetry.registry.counter(
    "mxtpu_oom_failures",
    "RESOURCE_EXHAUSTED dispatch failures, by site")

_lock = threading.Lock()
_owners: Dict[str, Callable[[], float]] = {}
_inventories: Dict[str, Callable[[], dict]] = {}


# ---------------------------------------------------------------------------
# Per-owner attribution
# ---------------------------------------------------------------------------
def register_owner(owner: str, fn: Callable[[], float]) -> None:
    """Register (or replace) a device-memory owner: ``fn()`` returns the
    bytes currently attributed to ``owner``.  Conventional owner names:
    ``kv:<model>`` (BlockPool-backed KV cache), ``params:<model>``,
    ``optimizer`` (ZeRO-1 local shard)."""
    with _lock:
        _owners[owner] = fn


def unregister_owner(owner: str) -> None:
    with _lock:
        _owners.pop(owner, None)


def owned_bytes() -> Dict[str, float]:
    """owner → bytes for every registered owner (a failing callback
    reports 0 — attribution must never take the program down)."""
    with _lock:
        owners = dict(_owners)
    out = {}
    for name, fn in owners.items():
        try:
            out[name] = float(fn() or 0.0)
        except Exception:
            out[name] = 0.0
    return out


# ---------------------------------------------------------------------------
# Program inventory providers (engines register; flight dumps consume)
# ---------------------------------------------------------------------------
def register_inventory(name: str, fn: Callable[[], dict]) -> None:
    """Register (or replace) a per-engine program-inventory callback —
    ``fn()`` returns the engine's :meth:`program_inventory` dict
    (expected vs compiled programs, per-program dispatch counts,
    per-slot KV occupancy)."""
    with _lock:
        _inventories[name] = fn


def unregister_inventory(name: str) -> None:
    with _lock:
        _inventories.pop(name, None)


def program_report() -> dict:
    """The runtime program-set inventory: the global dispatch ledger
    plus every registered engine's own accounting.  This is the payload
    behind ``GET /programs`` and the ``programs`` flight provider."""
    with _lock:
        inventories = dict(_inventories)
    engines = {}
    for name, fn in inventories.items():
        try:
            engines[name] = fn()
        except Exception as e:      # a sick engine is itself data
            engines[name] = {"error": repr(e)}
    return {"sites": _telemetry.dispatch_ledger(), "engines": engines}


# ---------------------------------------------------------------------------
# Memory breakdown + gauges
# ---------------------------------------------------------------------------
def memory_breakdown() -> dict:
    """JSON-ready device-memory forensics: per-device bytes-in-use /
    peak watermarks (``memory_stats()`` where the backend has it), the
    live-array total, and the per-owner attribution.  Never raises."""
    out = {"devices": {}, "owners": owned_bytes(),
           "live_array_bytes": 0.0}
    try:
        import jax
    except Exception:
        out["error"] = "jax unavailable"
        return out
    try:
        out["live_array_bytes"] = float(sum(
            getattr(a, "nbytes", 0) or 0 for a in jax.live_arrays()))
    except Exception:
        pass
    try:
        for d in jax.devices():
            try:
                stats = d.memory_stats()
            except Exception:
                continue
            if not stats:
                continue
            out["devices"][f"{d.platform}:{d.id}"] = {
                k: stats[k] for k in
                ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
                if k in stats}
    except Exception:
        pass
    total_owned = sum(out["owners"].values())
    out["owned_bytes"] = total_owned
    out["unattributed_bytes"] = max(
        0.0, out["live_array_bytes"] - total_owned)
    return out


def sample() -> dict:
    """Refresh every device-memory gauge (the ``mx_device_*`` trio plus
    the per-owner attribution) and return the breakdown."""
    _telemetry.sample_device_memory()
    bd = memory_breakdown()
    for owner, nbytes in bd["owners"].items():
        _g_owned.set(nbytes, owner=owner)
    _g_unattributed.set(bd["unattributed_bytes"])
    return bd


# ---------------------------------------------------------------------------
# Background sampler
# ---------------------------------------------------------------------------
_sampler_stop: Optional[threading.Event] = None


def start_sampler(interval: Optional[float] = None) -> bool:
    """Start the background gauge sampler at ``interval`` seconds
    (default ``MXNET_DEVICE_MEM_INTERVAL_SECONDS``); returns False (and
    starts nothing) when the interval is 0 or a sampler already runs."""
    global _sampler_stop
    iv = default_sample_interval() if interval is None \
        else float(interval)
    if iv <= 0:
        return False
    with _lock:
        if _sampler_stop is not None:
            return False
        stop = _sampler_stop = threading.Event()

    def loop():
        while not stop.wait(iv):
            try:
                sample()
            except Exception:
                pass

    threading.Thread(target=loop, name="mxtpu-device-mem",
                     daemon=True).start()
    return True


def stop_sampler() -> None:
    global _sampler_stop
    with _lock:
        stop = _sampler_stop
        _sampler_stop = None
    if stop is not None:
        stop.set()


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------
def is_oom(err: BaseException) -> bool:
    """True when ``err`` is a device out-of-memory: jax surfaces these
    as ``XlaRuntimeError`` with a ``RESOURCE_EXHAUSTED:`` status prefix
    (message-matched so injected faults carrying the same marker drill
    the identical path)."""
    return "RESOURCE_EXHAUSTED" in f"{type(err).__name__}: {err}"


def report_oom(site: str, err: BaseException, **ctx) -> None:
    """Publish the FAULT ``oom`` event for a RESOURCE_EXHAUSTED dispatch
    failure.  The flight recorder's ``oom`` trigger turns it into one
    debounced postmortem dump whose ``device_memory`` and ``programs``
    providers carry the breakdown an operator needs; extra ``ctx``
    (``model=``, ``request_ids=``) rides along on the ring entry so the
    dump names the implicated requests."""
    _c_oom.inc(site=site)
    try:        # gauges first: the dump's metrics snapshot should show
        sample()        # the memory picture AT the failure, not stale
    except Exception:
        pass
    _telemetry.FAULT.publish(site=site, event="oom",
                             error=f"{type(err).__name__}: {err}"[:300],
                             **ctx)


# ---------------------------------------------------------------------------
# On-demand profiler capture
# ---------------------------------------------------------------------------
class CaptureBusy(MXNetError):
    """A profiler capture is already in flight (single-capture guard —
    ``jax.profiler`` supports one trace at a time per process)."""


_capture_lock = threading.Lock()
_capture_active = False
_capture_seq = 0

#: capture bounds: floor keeps a capture observable, ceiling keeps an
#: HTTP-triggered capture from parking a server thread for minutes
CAPTURE_MIN_SECONDS = 0.05
CAPTURE_MAX_SECONDS = 60.0


def capture_active() -> bool:
    return _capture_active


def capture_profile(seconds: float,
                    out_dir: Optional[str] = None) -> str:
    """Capture a ``jax.profiler`` trace for ``seconds`` (clamped to
    [0.05, 60]) into a fresh artifact directory under ``out_dir`` /
    ``MXNET_PROFILE_DIR`` and return its path.  Blocks for the capture
    window.  Raises :class:`CaptureBusy` while another capture runs —
    the serving route maps that to HTTP 409."""
    global _capture_active, _capture_seq
    import jax
    seconds = min(CAPTURE_MAX_SECONDS,
                  max(CAPTURE_MIN_SECONDS, float(seconds)))
    with _capture_lock:
        if _capture_active:
            raise CaptureBusy("profiler capture already in progress")
        _capture_active = True
        _capture_seq += 1
        seq = _capture_seq
    base = out_dir or default_profile_dir()
    path = os.path.join(base, f"capture_{os.getpid()}_{seq:03d}")
    os.makedirs(path, exist_ok=True)
    try:
        jax.profiler.start_trace(path)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        _c_captures.inc()
    finally:
        with _capture_lock:
            _capture_active = False
    return path


# the two providers every oom/watchdog/breaker flight dump should carry
_ring.recorder.register_provider("device_memory", memory_breakdown)
_ring.recorder.register_provider("programs", program_report)
