"""Legacy FeedForward model API (reference:
python/mxnet/model.py class FeedForward — deprecated in 1.x in favor of
Module, but still part of the public surface and of old tutorials).

Implemented as a thin veneer over Module (exactly how users were told to
migrate): ``fit`` binds a Module on the data iter's shapes and trains,
``predict``/``score`` evaluate, ``save``/``load`` use the shared
``prefix-symbol.json`` / ``prefix-NNNN.params`` checkpoint format.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .context import cpu
from . import model as model_mod
from .module.module import Module

__all__ = ["FeedForward"]


class FeedForward:
    """Legacy training façade.  ``FeedForward(symbol, ctx, num_epoch=N,
    optimizer='sgd', **opt_args)`` then ``.fit(train_iter)`` (reference:
    model.py FeedForward.fit)."""

    def __init__(self, symbol, ctx=None, num_epoch=None,
                 epoch_size=None, optimizer="sgd", initializer=None,
                 arg_params=None, aux_params=None, begin_epoch=0,
                 **kwargs):
        self.symbol = symbol
        self.ctx = ctx if ctx is not None else [cpu()]
        if not isinstance(self.ctx, (list, tuple)):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    @staticmethod
    def _as_iter(X, y=None, batch_size=128):
        """Accept the legacy call forms: a DataIter, or raw
        numpy/NDArray X (+ y) which get wrapped in an NDArrayIter
        (reference: model.py _init_iter)."""
        if hasattr(X, "provide_data"):
            return X
        from .io.io import NDArrayIter
        import numpy as _np
        from .ndarray.ndarray import NDArray
        if isinstance(X, NDArray):
            X = X.asnumpy()
        if isinstance(y, NDArray):
            y = y.asnumpy()
        X = _np.asarray(X)
        n = X.shape[0]
        bs = min(batch_size, n)
        data = {"data": X}
        label = None if y is None else {"softmax_label": _np.asarray(y)}
        return NDArrayIter(data, label, batch_size=bs)

    # -- training ------------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, work_load_list=None,
            monitor=None):
        """Train for ``num_epoch`` epochs over data iter ``X`` (or raw
        numpy ``X``/``y``, wrapped per the legacy API)
        (reference: FeedForward.fit -> module fit path)."""
        if self.num_epoch is None:
            raise MXNetError("FeedForward.fit requires num_epoch")
        X = self._as_iter(X, y)
        logger = logger or logging.getLogger(__name__)
        opt_params = dict(self.kwargs)
        if "rescale_grad" not in opt_params:
            # reference FeedForward.fit defaults rescale_grad to
            # 1/batch_size (model.py _init_iter era behavior)
            bs = getattr(X, "batch_size", None) \
                or X.provide_data[0][1][0]
            opt_params["rescale_grad"] = 1.0 / float(bs)
        mod = Module(self.symbol,
                     data_names=tuple(d[0] for d in X.provide_data),
                     label_names=tuple(l[0] for l in X.provide_label),
                     logger=logger, context=self.ctx)
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback,
                kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=opt_params,
                initializer=self.initializer,
                arg_params=self.arg_params, aux_params=self.aux_params,
                allow_missing=self.arg_params is not None,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def _require_trained(self, X=None):
        if self._module is None:
            if self.arg_params is not None and X is not None:
                # loaded-from-checkpoint path: bind on the iter's shapes
                # (reference: FeedForward.predict binds lazily)
                mod = Module(
                    self.symbol,
                    data_names=tuple(d[0] for d in X.provide_data),
                    label_names=tuple(l[0] for l in
                                      (X.provide_label or [])),
                    context=self.ctx)
                mod.bind(X.provide_data, X.provide_label,
                         for_training=False)
                mod.init_params(arg_params=self.arg_params,
                                aux_params=self.aux_params)
                self._module = mod
            else:
                raise MXNetError("model has not been trained or loaded; "
                                 "call fit() or FeedForward.load() first")
        return self._module

    def predict(self, X, num_batch=None):
        """Forward over an iter (or raw numpy X); returns outputs
        merged over batches."""
        X = self._as_iter(X)
        return self._require_trained(X).predict(X, num_batch=num_batch)

    def score(self, X, y=None, eval_metric="acc", num_batch=None):
        X = self._as_iter(X, y)
        mod = self._require_trained(X)
        res = mod.score(X, eval_metric, num_batch=num_batch)
        return res[0][1] if res else None

    # -- checkpointing -------------------------------------------------
    def save(self, prefix, epoch=None):
        """Write ``prefix-symbol.json`` + ``prefix-NNNN.params``
        (reference checkpoint format; see model.save_checkpoint)."""
        if epoch is None:
            epoch = self.num_epoch or 0
        if self._module is not None:
            arg_params, aux_params = self._module.get_params()
        elif self.arg_params is not None:
            # loaded-but-never-bound model: the stored params ARE the
            # checkpoint
            arg_params, aux_params = self.arg_params, self.aux_params or {}
        else:
            raise MXNetError("model has not been trained or loaded; "
                             "call fit() or FeedForward.load() first")
        model_mod.save_checkpoint(prefix, epoch, self.symbol, arg_params,
                                  aux_params)

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """Reload from a reference-format checkpoint pair; the result can
        ``predict``/``score`` immediately and ``fit`` to continue."""
        symbol, arg_params, aux_params = model_mod.load_checkpoint(
            prefix, epoch)
        ff = FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                         aux_params=aux_params, begin_epoch=epoch,
                         **kwargs)
        return ff

    def bind_for_inference(self, data_shapes, label_shapes=None):
        """Explicitly bind a Module holding the stored params (predict/
        score also bind lazily from the iter's shapes)."""
        from .module.module import _canon_shapes
        data_names = tuple(d.name for d in _canon_shapes(data_shapes))
        label_names = (tuple(l.name for l in _canon_shapes(label_shapes))
                       if label_shapes else ())
        mod = Module(self.symbol, data_names=data_names,
                     label_names=label_names, context=self.ctx)
        mod.bind(data_shapes, label_shapes, for_training=False)
        mod.init_params(arg_params=self.arg_params,
                        aux_params=self.aux_params)
        self._module = mod
        return self
