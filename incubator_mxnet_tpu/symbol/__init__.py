"""``mx.sym`` / ``mx.symbol`` — the symbolic graph API.

Every eager ``mx.nd`` op is available symbolically under the same name
(reference: both namespaces are generated from the same C-API op registry;
here the symbol wrappers resolve through ``op_registry`` into the same pure
functions, so eager and symbolic execution are numerically identical by
construction).
"""
from __future__ import annotations

from .symbol import (Symbol, var, Variable, Group, load, load_json,
                     apply_op)
from . import op_registry
from . import contrib

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "apply_op", "contrib"]


def __getattr__(name: str):
    if name.startswith("_"):
        raise AttributeError(name)
    try:
        op_registry.get(name)
    except Exception:
        raise AttributeError(f"module 'symbol' has no op '{name}'")

    def op(*args, **kwargs):
        return apply_op(name, *args, **kwargs)
    op.__name__ = name
    op.__qualname__ = name
    globals()[name] = op  # cache
    return op


def __dir__():
    return sorted(set(__all__) | set(op_registry.known_ops()))
