"""Symbol: the lazy graph-building API (reference:
python/mxnet/symbol/symbol.py + the nnvm Graph it drives).

TPU-native re-design: a Symbol is a lightweight Python DAG over the same
eager op corpus ``mx.nd`` uses.  There is no separate graph IR, op
registry, or C++ executor — binding a Symbol jit-compiles one pure function
over the graph (XLA owns memory planning, fusion, and scheduling, replacing
the reference's PlanMemory/AttachOpExecs passes; see
src/executor/graph_executor.cc).  Shape/type inference is ``jax.eval_shape``
over the same function instead of per-op FInferShape.

JSON serialization follows the nnvm schema (``nodes``/``arg_nodes``/
``heads``; reference: 3rdparty/tvm/nnvm/src/core/graph.cc SaveJSON +
src/nnvm/legacy_json_util.cc) so ``prefix-symbol.json`` checkpoints remain
interchangeable.
"""
from __future__ import annotations

import ast
import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from . import op_registry

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]

_counters = threading.local()


def _next_name(hint: str) -> str:
    from .. import name as name_mod
    mgr = name_mod.current()
    if mgr is not None:       # scoped NameManager/Prefix wins
        return mgr.get(None, hint)
    if not hasattr(_counters, "tbl"):
        _counters.tbl = {}
    n = _counters.tbl.get(hint, 0)
    _counters.tbl[hint] = n + 1
    return f"{hint}{n}"


class _SymNode:
    """One graph node.  ``op`` is None for variables (JSON op 'null')."""
    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs")

    def __init__(self, op: Optional[str], name: str, attrs: dict,
                 inputs: List[Tuple["_SymNode", int]], num_outputs: int = 1):
        self.op = op
        self.name = name
        self.attrs = attrs
        self.inputs = inputs
        self.num_outputs = num_outputs

    @property
    def is_variable(self) -> bool:
        return self.op is None


class Symbol:
    """A handle to one or more outputs of the symbolic graph."""

    def __init__(self, outputs: List[Tuple[_SymNode, int]]):
        self._outputs = list(outputs)

    # ------------------------------------------------------------------
    # graph structure
    # ------------------------------------------------------------------
    @property
    def name(self) -> str:
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return "grouped_symbol"

    def _topo(self) -> List[_SymNode]:
        seen, order = set(), []

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for src, _ in node.inputs:
                visit(src)
            order.append(node)
        for node, _ in self._outputs:
            visit(node)
        return order

    def _var_nodes(self):
        args, auxs = [], []
        for n in self._topo():
            if n.is_variable:
                (auxs if n.attrs.get("__is_aux__") else args).append(n)
        return args, auxs

    def list_arguments(self) -> List[str]:
        return [n.name for n in self._var_nodes()[0]]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._var_nodes()[1]]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.num_outputs == 1:
                names.append(f"{node.name}_output")
            else:
                names.append(f"{node.name}_output{idx}")
        return names

    def list_inputs(self) -> List[str]:
        return self.list_arguments() + self.list_auxiliary_states()

    @property
    def outputs(self) -> List["Symbol"]:
        return [Symbol([o]) for o in self._outputs]

    def get_internals(self) -> "Symbol":
        outs = []
        for n in self._topo():
            for i in range(n.num_outputs):
                outs.append((n, i))
        return Symbol(outs)

    def get_children(self) -> Optional["Symbol"]:
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    def __getitem__(self, index):
        if isinstance(index, str):
            matches = [i for i, nm in enumerate(self.list_outputs())
                       if nm == index or nm.rsplit("_output", 1)[0] == index]
            if not matches:
                raise MXNetError(f"no output named {index!r}")
            index = matches[0]
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return iter(self.outputs)

    # ------------------------------------------------------------------
    # attributes
    # ------------------------------------------------------------------
    def attr(self, key: str) -> Optional[str]:
        attrs = self._outputs[0][0].attrs
        v = attrs.get(key)
        if v is None:
            # AttrScope metadata is stored dunder-wrapped so it never
            # reaches kernel kwargs; surface it under the plain name
            v = attrs.get(f"__{key}__")
        return None if v is None else str(v)

    def list_attr(self) -> Dict[str, str]:
        return {k: str(v)
                for k, v in self._outputs[0][0].attrs.items()
                if not k.startswith("__input")}

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        out = {}
        for n in self._topo():
            if n.attrs:
                out[n.name] = {k: str(v) for k, v in n.attrs.items()
                               if not k.startswith("__input")}
        return out

    def _set_attr(self, **kwargs):
        self._outputs[0][0].attrs.update(kwargs)

    # ------------------------------------------------------------------
    # arithmetic sugar (maps onto the same elemwise ops as mx.nd)
    # ------------------------------------------------------------------
    def __add__(self, other):
        return _apply_binary("broadcast_add", "_plus_scalar", self, other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return _apply_binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _apply_binary("broadcast_sub", "_rminus_scalar", self, other,
                             reverse=True)

    def __mul__(self, other):
        return _apply_binary("broadcast_mul", "_mul_scalar", self, other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        return _apply_binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _apply_binary("broadcast_div", "_rdiv_scalar", self, other,
                             reverse=True)

    def __pow__(self, other):
        return _apply_binary("broadcast_power", "_power_scalar", self, other)

    def __neg__(self):
        return self.__mul__(-1.0)

    def __repr__(self):
        return f"<Symbol {self.name}>"

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        return load_json(self.tojson())

    # ------------------------------------------------------------------
    # inference
    # ------------------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, out_shapes, aux_shapes = self._infer(
            *args, partial=False, **kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer(*args, partial=True, **kwargs)

    def infer_type(self, *args, **kwargs):
        """Propagate dtypes through the graph.  Needs shapes to trace
        (dtype promotion can be shape-free, but we reuse the abstract
        evaluator); variables without a ``__shape__`` attr fall back to a
        scalar placeholder, which is dtype-accurate for every registered
        op."""
        dtypes = dict(kwargs)
        arg_names = self.list_arguments()
        if args:
            dtypes.update({n: d for n, d in zip(arg_names, args)
                           if d is not None})
        # shape placeholders: use declared shapes where present
        known = {}
        for n in self._topo():
            if n.is_variable and n.attrs.get("__shape__") is not None:
                known[n.name] = tuple(n.attrs["__shape__"])
        try:
            avals = _abstract_eval(self, known, dtypes, partial=True)
        except MXNetError:
            avals = None
        if avals is None:
            arg_dt = [dtypes.get(n, _np.float32) for n in arg_names]
            return arg_dt, None, None
        node_avals, var_avals = avals
        arg_nodes, aux_nodes = self._var_nodes()
        arg_dt = [var_avals.get(n.name, (None, dtypes.get(
            n.name, _np.float32)))[1] for n in arg_nodes]
        aux_dt = [var_avals.get(n.name, (None, _np.float32))[1]
                  for n in aux_nodes]
        out_dt = []
        for node, idx in self._outputs:
            na = node_avals.get(id(node))
            out_dt.append(None if na is None else na[idx][1])
        return arg_dt, out_dt, aux_dt

    def _infer(self, *args, partial=False, type_dict=None, **kwargs):
        arg_names = self.list_arguments()
        known: Dict[str, tuple] = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        for k, v in kwargs.items():
            if v is not None:
                known[k] = tuple(v)
        type_dict = type_dict or {}
        avals = _abstract_eval(self, known, type_dict, partial=partial)
        if avals is None:
            return None, None, None
        node_avals, var_avals = avals
        arg_nodes, aux_nodes = self._var_nodes()
        arg_shapes = [var_avals.get(n.name, (None, None))[0]
                      for n in arg_nodes]
        aux_shapes = [var_avals.get(n.name, (None, None))[0]
                      for n in aux_nodes]
        out_shapes = []
        for node, idx in self._outputs:
            na = node_avals.get(id(node))
            out_shapes.append(None if na is None else na[idx][0])
        if not partial and (any(s is None for s in arg_shapes)
                            or any(s is None for s in out_shapes)):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError(
                f"infer_shape: could not infer shapes for {missing}; "
                "provide them explicitly")
        return arg_shapes, out_shapes, aux_shapes

    # ------------------------------------------------------------------
    # serialization (nnvm JSON schema)
    # ------------------------------------------------------------------
    def tojson(self) -> str:
        nodes = self._topo()
        node_idx = {id(n): i for i, n in enumerate(nodes)}
        jnodes, arg_nodes = [], []
        for i, n in enumerate(nodes):
            if n.is_variable:
                arg_nodes.append(i)
            jn = {
                "op": "null" if n.is_variable else n.op,
                "name": n.name,
                "inputs": [[node_idx[id(src)], oi, 0]
                           for src, oi in n.inputs],
            }
            attrs = {k: _attr_str(v) for k, v in n.attrs.items()
                     if not k.startswith("__input")}
            if attrs:
                jn["attrs"] = attrs
            jnodes.append(jn)
        heads = [[node_idx[id(n)], oi, 0] for n, oi in self._outputs]
        return json.dumps({
            "nodes": jnodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(jnodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10700]},
        }, indent=2)

    def save(self, fname: str) -> None:
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------------
    # binding / evaluation
    # ------------------------------------------------------------------
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **_ignored):
        from ..executor import Executor
        return Executor(self, ctx, args=args, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    **shapes):
        from ..executor import Executor
        return Executor.simple_bind(self, ctx, grad_req=grad_req,
                                    type_dict=type_dict, **shapes)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, args=kwargs, grad_req="null")
        return ex.forward()

    def _compose_input_map(self):
        """name -> variable node, for graph evaluation."""
        return {n.name: n for n in self._topo() if n.is_variable}


# ---------------------------------------------------------------------------
# graph evaluation (shared by Executor.forward and shape inference)
# ---------------------------------------------------------------------------
def eval_graph(symbol: Symbol, var_values: Dict[str, object],
               is_train: bool, aux_sink: Optional[dict] = None):
    """Evaluate the DAG with NDArray (or traced-NDArray) leaf values.
    Returns list of NDArray outputs, one per symbol head."""
    vals: Dict[int, list] = {}
    for node in symbol._topo():
        if node.is_variable:
            if node.name not in var_values:
                raise MXNetError(f"bind: missing value for input "
                                 f"'{node.name}'")
            vals[id(node)] = [var_values[node.name]]
            continue
        opdef = op_registry.get(node.op)
        ins = [vals[id(src)][oi] for src, oi in node.inputs]
        out = opdef.call(ins, node, is_train, aux_sink)
        vals[id(node)] = out if isinstance(out, (list, tuple)) else [out]
    return [vals[id(n)][oi] for n, oi in symbol._outputs]


def _abstract_eval(symbol: Symbol, known_shapes: Dict[str, tuple],
                   type_dict: Dict[str, object], partial: bool):
    """Forward shape/dtype propagation: walk the graph, fill unknown
    parameter shapes from each op's param_shape_fn, get node output avals
    via jax.eval_shape on the op's pure function."""
    import jax
    from ..ndarray.ndarray import NDArray
    from .. import random as _random

    # ops that draw RNG keys (dropout) split from this local stream during
    # the eval_shape trace — splitting the global stream there would store
    # a tracer into global state (leak); one key serves every node
    _infer_key = jax.random.PRNGKey(0)

    node_avals: Dict[int, list] = {}
    var_avals: Dict[str, tuple] = {}

    def var_aval(node):
        if node.name in var_avals:
            return var_avals[node.name]
        shape = known_shapes.get(node.name)
        if shape is None and node.attrs.get("__shape__") is not None:
            shape = tuple(node.attrs["__shape__"])
        if shape is None:
            return None
        dt = type_dict.get(node.name, node.attrs.get("__dtype__",
                                                     _np.float32))
        var_avals[node.name] = (tuple(shape), _np.dtype(dt))
        return var_avals[node.name]

    for node in symbol._topo():
        if node.is_variable:
            a = var_aval(node)
            node_avals[id(node)] = None if a is None else [a]
            continue
        opdef = op_registry.get(node.op)
        in_avals = []
        unknown = []
        for pos, (src, oi) in enumerate(node.inputs):
            a = node_avals.get(id(src))
            if a is None:
                unknown.append((pos, src))
                in_avals.append(None)
            else:
                in_avals.append(a[oi])
        if unknown and opdef.param_shape_fn is not None \
                and in_avals and in_avals[0] is not None:
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            try:
                pshapes = opdef.param_shape_fn(attrs, in_avals[0][0])
            except Exception:
                pshapes = {}
            data_dt = in_avals[0][1]
            for pos, src in list(unknown):
                pname = opdef.arg_names[pos] if pos < len(
                    opdef.arg_names) else None
                if src.is_variable and pname in pshapes:
                    var_avals[src.name] = (tuple(pshapes[pname]),
                                           _np.dtype(type_dict.get(
                                               src.name, data_dt)))
                    node_avals[id(src)] = [var_avals[src.name]]
                    in_avals[pos] = var_avals[src.name]
                    unknown = [(p, s) for p, s in unknown if p != pos]
        if unknown:
            if partial:
                node_avals[id(node)] = None
                continue
            names = [s.name for _, s in unknown]
            raise MXNetError(
                f"infer_shape: inputs {names} of op '{node.name}' "
                f"({node.op}) have unknown shapes")

        def f(*arrs, _opdef=opdef, _node=node):
            nds = [NDArray(a) for a in arrs]
            out = _opdef.call(nds, _node, True, {})
            outs = out if isinstance(out, (list, tuple)) else [out]
            return tuple(o._data for o in outs)

        try:
            specs = [jax.ShapeDtypeStruct(s, d) for s, d in in_avals]
            with _random.trace_stream(_infer_key):
                out_avals = jax.eval_shape(f, *specs)
        except Exception as e:
            raise MXNetError(
                f"infer_shape failed at node '{node.name}' ({node.op}): "
                f"{e}") from e
        node_avals[id(node)] = [(tuple(o.shape), _np.dtype(o.dtype))
                                for o in out_avals]
    return node_avals, var_avals


# ---------------------------------------------------------------------------
# construction API
# ---------------------------------------------------------------------------
def var(name: str, attr: Optional[dict] = None, shape=None, dtype=None,
        lr_mult=None, wd_mult=None, init=None, stype=None,
        **kwargs) -> Symbol:
    """Create a symbolic variable (reference: symbol.var / sym.Variable)."""
    from .. import attribute as attr_mod
    attrs = {f"__{k}__": v for k, v in attr_mod.current().items()}
    attrs.update({f"__{k}__": v for k, v in (attr or {}).items()})
    attrs.update(kwargs)
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = dtype
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        attrs["__init__"] = str(init)
    node = _SymNode(None, name, attrs, [])
    return Symbol([(node, 0)])


Variable = var


def Group(symbols: Sequence[Symbol]) -> Symbol:
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str: str) -> Symbol:
    g = json.loads(json_str)
    nodes: List[_SymNode] = []
    for jn in g["nodes"]:
        attrs = {k: _attr_parse(v)
                 for k, v in (jn.get("attrs") or jn.get("param")
                              or {}).items()}
        op = jn["op"]
        inputs = [(nodes[i], oi) for i, oi, *_ in jn["inputs"]]
        if op == "null":
            node = _SymNode(None, jn["name"], attrs, [])
        else:
            opdef = op_registry.get(op)
            node = _SymNode(op, jn["name"], attrs, inputs,
                            num_outputs=opdef.num_outputs(attrs))
            for pos in range(len(inputs)):
                pname = (opdef.arg_names[pos]
                         if pos < len(opdef.arg_names) else None)
                if pname in opdef.aux_names and inputs[pos][0].is_variable:
                    inputs[pos][0].attrs["__is_aux__"] = True
        nodes.append(node)
    heads = [(nodes[i], oi) for i, oi, *_ in g["heads"]]
    return Symbol(heads)


def _attr_str(v) -> str:
    if isinstance(v, (tuple, list)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def _attr_parse(s: str):
    if not isinstance(s, str):
        return s
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


# ---------------------------------------------------------------------------
# symbolic op application
# ---------------------------------------------------------------------------
def _as_symbol(x) -> Optional[Symbol]:
    return x if isinstance(x, Symbol) else None


def apply_op(opname: str, *args, name: Optional[str] = None,
             attr: Optional[dict] = None, **kwargs) -> Symbol:
    """Create a graph node applying ``opname``.  Symbol-valued arguments are
    tensor inputs; the rest are attrs.  Missing required tensor inputs are
    auto-created as variables named ``{node}_{arg}`` (matching the
    reference's auto-named weights in the symbolic API)."""
    opdef = op_registry.get(opname)
    node_name = name or _next_name(opname.lower().replace(".", "_"))
    from .. import attribute as attr_mod
    # ambient AttrScope attrs first (dunder-wrapped: metadata, not kernel
    # kwargs); explicit attr= wins
    attrs = {f"__{k}__": v for k, v in attr_mod.current().items()}
    attrs.update({f"__{k}__": v for k, v in (attr or {}).items()})
    named_inputs: Dict[str, Symbol] = {}
    pos_inputs: List[Symbol] = []

    for i, a in enumerate(args):
        s = _as_symbol(a)
        if s is None:
            # positional non-symbol: map onto attr by signature position
            if not opdef.varargs and i < len(opdef.arg_names):
                attrs[opdef.arg_names[i]] = a
            continue
        if opdef.varargs:
            pos_inputs.append(s)
        elif i < len(opdef.arg_names):
            named_inputs[opdef.arg_names[i]] = s
        else:
            pos_inputs.append(s)
    for k, v in kwargs.items():
        s = _as_symbol(v)
        if s is not None:
            named_inputs[k] = s
        elif v is not None:
            attrs[k] = v

    if opdef.varargs:
        inputs = [(s._outputs[0]) for s in pos_inputs]
        node = _SymNode(opname, node_name, attrs,
                        [(n, i) for n, i in inputs],
                        num_outputs=opdef.num_outputs(attrs))
        return _node_symbol(node)

    inputs = []
    required = [n for n in opdef.required_args(attrs) if n not in attrs]
    for argn in opdef.arg_names:
        if argn in named_inputs:
            s = named_inputs[argn]
            if len(s._outputs) != 1:
                raise MXNetError(
                    f"{opname}: input '{argn}' must be a single-output "
                    "symbol")
            entry = s._outputs[0]
            if argn in opdef.aux_names and entry[0].is_variable:
                entry[0].attrs["__is_aux__"] = True
            inputs.append(entry)
        elif argn in required:
            vattrs = {}
            if argn in opdef.aux_names:
                vattrs["__is_aux__"] = True
            vnode = _SymNode(None, f"{node_name}_{argn}", vattrs, [])
            inputs.append((vnode, 0))
        # optional & not given: stop appending further positions only if
        # nothing later is present
    node = _SymNode(opname, node_name, attrs, inputs,
                    num_outputs=opdef.num_outputs(attrs))
    return _node_symbol(node)


def _node_symbol(node: _SymNode) -> Symbol:
    return Symbol([(node, i) for i in range(node.num_outputs)])


def _apply_binary(broadcast_op, scalar_op, lhs, rhs, reverse=False):
    if isinstance(rhs, Symbol):
        base = broadcast_op.replace("broadcast_", "")
        mapping = {"sub": "subtract", "mul": "multiply", "div": "divide",
                   "add": "add", "power": "power"}
        return apply_op(mapping.get(base, base), lhs, rhs)
    # scalar path: lower onto a dedicated scalar op (reference registers
    # _plus_scalar etc. as distinct ops)
    return _scalar_binary(scalar_op, lhs, float(rhs))


_SCALAR_FNS = {
    "_plus_scalar": lambda jnp, x, c: x + c,
    "_minus_scalar": lambda jnp, x, c: x - c,
    "_rminus_scalar": lambda jnp, x, c: c - x,
    "_mul_scalar": lambda jnp, x, c: x * c,
    "_div_scalar": lambda jnp, x, c: x / c,
    "_rdiv_scalar": lambda jnp, x, c: c / x,
    "_power_scalar": lambda jnp, x, c: x ** c,
}


def _ensure_scalar_ops_registered():
    from ..ndarray.ndarray import NDArray, _invoke
    for nm, fn in _SCALAR_FNS.items():
        if nm in op_registry._REGISTRY:
            continue

        def make(fn):
            def op(data, scalar=0.0, **_ig):
                import jax.numpy as jnp
                return _invoke(lambda x: fn(jnp, x, scalar), [data],
                               name="scalar_op")
            return op
        op_registry._REGISTRY[nm] = op_registry.OpDef(
            nm, make(fn), arg_names=["data"])


def _scalar_binary(scalar_op, lhs, scalar):
    _ensure_scalar_ops_registered()
    return apply_op(scalar_op, lhs, scalar=scalar)
