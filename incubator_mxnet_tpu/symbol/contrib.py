"""``mx.sym.contrib`` — symbolic faces of the contrib op corpus
(reference: python/mxnet/symbol/contrib.py; both frontends generate from
one registry, mirrored here by resolving through ``ndarray.contrib``).

Control flow (``foreach``/``while_loop``/``cond``) is exposed eagerly only:
the hybridize/jit path already compiles Python-driven loops through
``lax.scan`` in the eager implementation, so a symbolic subgraph-op clone
would be redundant — call the ``nd.contrib`` versions inside a
HybridBlock instead.
"""
from __future__ import annotations

from . import op_registry
from .symbol import apply_op

_EAGER_ONLY = {"foreach", "while_loop", "cond"}

# multi-output contrib ops (the registry default is 1)
_NUM_OUTPUTS = {"bipartite_matching": 2, "MultiBoxTarget": 3}


def __getattr__(name: str):
    if name.startswith("_"):
        raise AttributeError(name)
    if name in _EAGER_ONLY:
        raise AttributeError(
            f"contrib.{name} is eager-only in this build: use "
            f"mx.nd.contrib.{name} (hybridize compiles it via lax.scan)")
    from ..ndarray import contrib as _ndc
    if name not in _ndc.__all__:
        raise AttributeError(f"module 'symbol.contrib' has no op '{name}'")
    fn = getattr(_ndc, name)
    opname = f"_contrib_{name}"
    try:
        op_registry.get(opname)
    except Exception:
        n_out = _NUM_OUTPUTS.get(name)
        kw = ({"num_outputs_fn": (lambda attrs, n=n_out: n)}
              if n_out else {})
        op_registry.register(opname, fn=fn, **kw)

    def op(*args, **kwargs):
        return apply_op(opname, *args, **kwargs)
    op.__name__ = name
    globals()[name] = op
    return op


def __dir__():
    from ..ndarray import contrib as _ndc
    return sorted(n for n in _ndc.__all__ if n not in _EAGER_ONLY)
