"""Symbolic op registry: metadata binding symbol-graph nodes to the eager
``nd`` op corpus.

Reference analog: the nnvm ``Op`` registry attributes — ``FListInputNames``,
``FInferShape``, ``FMutateInputs`` (aux states), ``FNumOutputs``
(reference: 3rdparty/tvm/nnvm/include/nnvm/op.h and the
``NNVM_REGISTER_OP(...).set_attr(...)`` sites under src/operator/).  The
TPU-native design needs far less: shape/type inference is ``jax.eval_shape``
over the same pure function the eager path runs, so the registry only
carries (a) ordered tensor-input names, (b) which inputs are auxiliary
states, (c) how to derive parameter shapes from the data shape (for
``simple_bind``'s partial inference), and (d) train/eval rewrites
(Dropout→identity, BatchNorm→global stats) that the reference encodes as
per-op ``is_train`` kernel branches.
"""
from __future__ import annotations

import inspect
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError

_REGISTRY: Dict[str, "OpDef"] = {}


class OpDef:
    """Metadata for one symbolic op."""

    def __init__(self, name: str, fn: Callable,
                 arg_names: Optional[List[str]] = None,
                 varargs: bool = False,
                 aux_names: Sequence[str] = (),
                 param_shape_fn: Optional[Callable] = None,
                 required_fn: Optional[Callable] = None,
                 num_outputs_fn: Optional[Callable] = None,
                 special: Optional[str] = None):
        self.name = name
        self.fn = fn
        self.varargs = varargs
        if arg_names is None and not varargs:
            arg_names = _tensor_args_from_signature(fn)
        self.arg_names = arg_names or []
        self.aux_names = tuple(aux_names)
        self.param_shape_fn = param_shape_fn
        self.required_fn = required_fn
        self.num_outputs_fn = num_outputs_fn
        self.special = special

    # ---- creation-time helpers -------------------------------------------
    def required_args(self, attrs: dict) -> List[str]:
        if self.required_fn is not None:
            return self.required_fn(attrs)
        return list(self.arg_names)

    def num_outputs(self, attrs: dict) -> int:
        if self.num_outputs_fn is not None:
            return self.num_outputs_fn(attrs)
        return 1

    # ---- evaluation ------------------------------------------------------
    def call(self, inputs: list, node, is_train: bool, aux_sink: dict):
        """Run the op on NDArray inputs (eager or under a jit/eval_shape
        trace).  ``aux_sink`` collects auxiliary-state updates by var name."""
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        if self.special == "dropout":
            if not is_train:
                from ..ndarray import ops as _ops
                return _ops.identity(inputs[0])
            return self.fn(*inputs, **attrs)
        if self.special == "batchnorm":
            return self._call_batchnorm(inputs, node, attrs, is_train,
                                        aux_sink)
        if self.varargs:
            return self.fn(*inputs, **attrs)
        kwargs = dict(zip(self.arg_names, inputs))
        kwargs.update(attrs)
        return self.fn(**kwargs)

    def _call_batchnorm(self, inputs, node, attrs, is_train, aux_sink):
        from ..ndarray import nn as _nn
        momentum = attrs.get("momentum", 0.9)
        use_global = attrs.get("use_global_stats", False)
        want_mean_var = attrs.get("output_mean_var", False)
        attrs = {k: v for k, v in attrs.items() if k != "output_mean_var"}
        if not is_train or use_global:
            attrs["use_global_stats"] = True
            res = _nn.BatchNorm(*inputs, output_mean_var=True, **attrs)
        else:
            # training: batch stats; fold the running-stat EMA update into
            # the same compiled step (reference mutates aux in the kernel)
            data, gamma, beta, mmean, mvar = inputs
            res = _nn.BatchNorm(data, gamma, beta, output_mean_var=True,
                                **{k: v for k, v in attrs.items()
                                   if k != "use_global_stats"})
            out, bmean, bvar = res
            if aux_sink is not None and len(node.inputs) >= 5:
                mm_node = node.inputs[3][0]
                mv_node = node.inputs[4][0]
                aux_sink[mm_node.name] = momentum * mmean \
                    + (1.0 - momentum) * bmean
                aux_sink[mv_node.name] = momentum * mvar \
                    + (1.0 - momentum) * bvar
        if want_mean_var:
            return list(res)
        return res[0]


def _tensor_args_from_signature(fn) -> List[str]:
    """Leading no-default parameters are the tensor inputs; everything from
    the first defaulted parameter on is an attr.  Matches the generic nd ops
    where tensor args come first (data, lhs/rhs, ...) and attrs carry
    defaults."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return ["data"]
    names = []
    for p in sig.parameters.values():
        if p.kind in (inspect.Parameter.VAR_POSITIONAL,
                      inspect.Parameter.VAR_KEYWORD):
            break
        if p.default is not inspect.Parameter.empty:
            break
        names.append(p.name)
    return names or ["data"]


def register(name: str, **kw) -> None:
    from .. import ndarray as _nd
    fn = kw.pop("fn", None) or getattr(_nd, name)
    _REGISTRY[name] = OpDef(name, fn, **kw)


def get(name: str) -> OpDef:
    if name not in _REGISTRY:
        _autoregister(name)
    if name not in _REGISTRY:
        raise MXNetError(f"symbol op '{name}' is not registered")
    return _REGISTRY[name]


def known_ops() -> List[str]:
    from .. import ndarray as _nd
    seen = set(_REGISTRY)
    for n in dir(_nd):
        if not n.startswith("_") and callable(getattr(_nd, n, None)):
            seen.add(n)
    return sorted(seen)


def _autoregister(name: str) -> None:
    """Generic fallback: any eager ``nd`` op becomes a symbol op with
    signature-derived input names (the analog of the reference generating
    symbol wrappers from the same C-API op registry the ndarray wrappers
    come from)."""
    from .. import ndarray as _nd
    fn = getattr(_nd, name, None)
    if fn is None or not callable(fn) or inspect.isclass(fn):
        return
    try:
        sig = inspect.signature(fn)
        varargs = any(p.kind == inspect.Parameter.VAR_POSITIONAL
                      for p in sig.parameters.values())
    except (TypeError, ValueError):
        varargs = False
    _REGISTRY[name] = OpDef(name, fn, varargs=varargs)


# ---------------------------------------------------------------------------
# parameter-shape inference (reference: each op's FInferShape filling
# unknown in-shapes backward from the data shape)
# ---------------------------------------------------------------------------
def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _fc_shapes(attrs, ds):
    nh = int(attrs["num_hidden"])
    flat = attrs.get("flatten", True)
    c = _prod(ds[1:]) if flat else ds[-1]
    return {"weight": (nh, int(c)), "bias": (nh,)}


def _conv_shapes(attrs, ds):
    kernel = tuple(attrs["kernel"])
    nf = int(attrs["num_filter"])
    g = int(attrs.get("num_group", 1))
    return {"weight": (nf, int(ds[1]) // g) + kernel, "bias": (nf,)}


def _deconv_shapes(attrs, ds):
    kernel = tuple(attrs["kernel"])
    nf = int(attrs["num_filter"])
    g = int(attrs.get("num_group", 1))
    return {"weight": (int(ds[1]), nf // g) + kernel, "bias": (nf,)}


def _norm_axis_shapes(axis_default):
    def fn(attrs, ds):
        ax = int(attrs.get("axis", axis_default))
        c = int(ds[ax])
        return {"gamma": (c,), "beta": (c,),
                "moving_mean": (c,), "moving_var": (c,)}
    return fn


def _emb_shapes(attrs, ds):
    return {"weight": (int(attrs["input_dim"]), int(attrs["output_dim"]))}


def _rnn_shapes(attrs, ds):
    from ..ndarray.nn import rnn_param_size
    t, n, c = ds
    h = int(attrs["state_size"])
    nl = int(attrs.get("num_layers", 1))
    bi = attrs.get("bidirectional", False)
    ndir = 2 if bi else 1
    mode = attrs.get("mode", "lstm")
    psize = rnn_param_size(mode, int(c), h, num_layers=nl, bidirectional=bi)
    return {"parameters": (psize,), "state": (nl * ndir, int(n), h),
            "state_cell": (nl * ndir, int(n), h)}


def _no_bias_required(base):
    def fn(attrs):
        names = list(base)
        if attrs.get("no_bias", False) and "bias" in names:
            names.remove("bias")
        return names
    return fn


def _rnn_required(attrs):
    names = ["data", "parameters", "state"]
    if attrs.get("mode", "lstm") == "lstm":
        names.append("state_cell")
    return names


def _bn_outputs(attrs):
    return 3 if attrs.get("output_mean_var", False) else 1


def _rnn_outputs(attrs):
    if not attrs.get("state_outputs", False):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


def _register_layer_ops():
    from ..ndarray import nn as _nn
    for spelled in ("FullyConnected", "fully_connected"):
        register(spelled, fn=_nn.FullyConnected,
                 arg_names=["data", "weight", "bias"],
                 param_shape_fn=_fc_shapes,
                 required_fn=_no_bias_required(["data", "weight", "bias"]))
    for spelled in ("Convolution", "convolution"):
        register(spelled, fn=_nn.Convolution,
                 arg_names=["data", "weight", "bias"],
                 param_shape_fn=_conv_shapes,
                 required_fn=_no_bias_required(["data", "weight", "bias"]))
    for spelled in ("Deconvolution", "deconvolution"):
        register(spelled, fn=_nn.Deconvolution,
                 arg_names=["data", "weight", "bias"],
                 param_shape_fn=_deconv_shapes,
                 required_fn=_no_bias_required(["data", "weight", "bias"]))
    for spelled in ("BatchNorm", "batch_norm"):
        register(spelled, fn=_nn.BatchNorm,
                 arg_names=["data", "gamma", "beta", "moving_mean",
                            "moving_var"],
                 aux_names=("moving_mean", "moving_var"),
                 param_shape_fn=_norm_axis_shapes(1),
                 num_outputs_fn=_bn_outputs,
                 special="batchnorm")
    for spelled in ("LayerNorm", "layer_norm"):
        register(spelled, fn=_nn.LayerNorm,
                 arg_names=["data", "gamma", "beta"],
                 param_shape_fn=_norm_axis_shapes(-1))
    for spelled in ("InstanceNorm", "instance_norm"):
        register(spelled, fn=_nn.InstanceNorm,
                 arg_names=["data", "gamma", "beta"],
                 param_shape_fn=_norm_axis_shapes(1))
    for spelled in ("RNN", "rnn"):
        register(spelled, fn=_nn.RNN,
                 arg_names=["data", "parameters", "state", "state_cell"],
                 param_shape_fn=_rnn_shapes,
                 required_fn=_rnn_required,
                 num_outputs_fn=_rnn_outputs)
    from ..ndarray import ops as _ops
    register("Embedding", fn=_ops.Embedding,
             arg_names=["data", "weight"],
             param_shape_fn=_emb_shapes)
    for spelled in ("Dropout", "dropout"):
        register(spelled, fn=_ops.dropout, arg_names=["data"],
                 special="dropout")


def _register_legacy_ops():
    """Ops whose OPTIONAL tensor inputs (defaulted parameters) the
    signature-derived autoregistration cannot see — without explicit
    arg_names the symbolic frontend would silently drop those inputs at
    graph construction (reference analog: their FListInputNames)."""
    from ..ndarray import nn as _nn
    from ..ndarray import ops as _ops
    from ..ndarray import contrib as _contrib
    register("Convolution_v1", fn=_nn.Convolution_v1,
             arg_names=["data", "weight", "bias"],
             param_shape_fn=_conv_shapes,
             required_fn=_no_bias_required(["data", "weight", "bias"]))
    register("Crop", fn=_ops.Crop,
             arg_names=["data", "crop_like"],
             required_fn=lambda attrs: (
                 ["data", "crop_like"]
                 if int(attrs.get("num_args", 1)) == 2 else ["data"]))
    # pre-registered under the name symbol.contrib resolves to, so the
    # mode='like' second input survives graph construction
    register("_contrib_BilinearResize2D", fn=_contrib.BilinearResize2D,
             arg_names=["data", "like"],
             required_fn=lambda attrs: (
                 ["data", "like"] if str(attrs.get("mode")) == "like"
                 else ["data"]))


_register_layer_ops()
_register_legacy_ops()
