"""Mixture-of-Experts FFN with expert parallelism (reference lineage:
the Switch/GShard MoE layer — the reference repo itself predates MoE, so
this is a beyond-parity capability like ring attention, SURVEY §5.7;
built from the same op surface as every model here).

TPU-first design:
  * experts are STACKED parameters — w1 (E, C, H), w2 (E, H, C) — so
    expert parallelism is nothing but a sharding rule
    (``ep_rules('expert')``: PartitionSpec('expert', ...) on the stacked
    axis).  GSPMD then inserts the dispatch all-to-alls over ICI by
    itself; there is no hand-written collective (the scaling-book
    recipe: annotate, let XLA place the communication);
  * routing is the capacity-based GShard dispatch: one-hot
    dispatch/combine tensors and three einsums — dense, static-shaped,
    MXU-friendly; no sorts or dynamic shapes inside the program;
  * top-k (k=1 Switch, k=2 GShard default) with renormalized gates and
    rank-ordered capacity claims; overflowing tokens are DROPPED
    (combine weight 0) exactly like the reference implementations — the
    load-balancing auxiliary loss keeps that rare;
  * the auxiliary load-balancing loss (Switch eq. 4) is returned
    alongside the output so the training loss can add it.
"""
from __future__ import annotations

import math

import numpy as _np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import _invoke

__all__ = ["MoEFFN", "MoELoss", "ep_rules"]


def _moe_dispatch(logits, k, capacity, valid=None):
    """GShard routing over one GROUP of g tokens: returns (dispatch
    (g, E, Cap) f32, combine (g, E, Cap) f32, aux scalar).  Rank r
    claims capacity after ranks < r; tokens keep arrival order within a
    rank.  Vmapped over groups — capacity is per group, so the
    dispatch/combine tensors stay linear in total token count.

    ``valid`` (g,) 0/1 marks real tokens: invalid (padding) tokens
    claim NO expert capacity, produce zero output, and are excluded
    from the aux-loss statistics — without it, padded positions compete
    real tokens out of their expert buffers."""
    import jax
    import jax.numpy as jnp
    g, E = logits.shape
    raw = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    vals, idx = jax.lax.top_k(raw, k)                  # (g, k)
    w = vals / jnp.maximum(jnp.sum(vals, -1, keepdims=True), 1e-9)
    vfl = None if valid is None else valid.astype(jnp.float32)

    dispatch = jnp.zeros((g, E, capacity), jnp.float32)
    combine = jnp.zeros((g, E, capacity), jnp.float32)
    counts = jnp.zeros((E,), jnp.int32)
    top1 = None
    for r in range(k):
        onehot = jax.nn.one_hot(idx[:, r], E, dtype=jnp.int32)  # (g, E)
        if vfl is not None:     # padding claims nothing, routes nowhere
            onehot = onehot * vfl.astype(jnp.int32)[:, None]
        if r == 0:
            top1 = onehot
        # this token's slot in its expert's buffer: earlier tokens of
        # the same rank + everything claimed by lower ranks
        pos = jnp.cumsum(onehot, axis=0) - onehot + counts[None]
        pos_tok = jnp.sum(pos * onehot, axis=-1)                # (g,)
        keep = pos_tok < capacity
        slot = jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)
        d_r = (onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
               * keep.astype(jnp.float32)[:, None, None])
        dispatch = dispatch + d_r
        combine = combine + d_r * w[:, r][:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)

    # Switch aux loss: E * sum_e mean_gate_e * fraction_top1_e,
    # statistics over VALID tokens only
    if vfl is None:
        me = jnp.mean(raw, axis=0)
        ce = jnp.mean(top1.astype(jnp.float32), axis=0)
    else:
        n = jnp.maximum(jnp.sum(vfl), 1.0)
        me = jnp.sum(raw * vfl[:, None], axis=0) / n
        ce = jnp.sum(top1.astype(jnp.float32), axis=0) / n
    aux = E * jnp.sum(me * ce)
    return dispatch, combine, aux


class MoEFFN(HybridBlock):
    """Drop-in positionwise FFN with E experts.

    Forward returns ``(out (B, T, C), aux_loss scalar)``; add
    ``aux_weight * aux_loss`` to the training loss (Switch uses 1e-2).
    ``capacity_factor`` scales each expert's token buffer
    (ceil(cf * S * k / E)); overflow is dropped like the reference
    implementations."""

    def __init__(self, units, hidden_size, num_experts, top_k=2,
                 capacity_factor=1.25, group_size=256, activation="gelu",
                 dtype=_np.float32, **kwargs):
        super().__init__(**kwargs)
        if top_k < 1 or top_k > num_experts:
            raise MXNetError(f"top_k={top_k} must be in [1, num_experts]")
        self._units = units
        self._hidden = hidden_size
        self._E = num_experts
        self._k = top_k
        self._cf = capacity_factor
        self._group = group_size
        self._act = activation
        with self.name_scope():
            self.router = nn.Dense(num_experts, flatten=False,
                                   use_bias=False, in_units=units)
            self.w1 = self.params.get(
                "w1", shape=(num_experts, units, hidden_size), dtype=dtype)
            self.b1 = self.params.get(
                "b1", shape=(num_experts, hidden_size), dtype=dtype,
                init="zeros")
            self.w2 = self.params.get(
                "w2", shape=(num_experts, hidden_size, units), dtype=dtype)
            self.b2 = self.params.get(
                "b2", shape=(num_experts, units), dtype=dtype,
                init="zeros")

    def hybrid_forward(self, F, x, valid=None, w1=None, b1=None,
                       w2=None, b2=None):
        logits = self.router(x)                       # (B, T, E)
        E, k, cf, act = self._E, self._k, self._cf, self._act
        group = self._group

        def run(xv, lg, w1v, b1v, w2v, b2v, vv=None):
            import functools
            import jax
            import jax.numpy as jnp
            B, T, C = xv.shape
            S = B * T
            # route within fixed-size groups (GShard): capacity is per
            # group, so dispatch/combine memory is O(S * g), linear in
            # token count — never O(S^2)
            g = min(group or S, S)
            while S % g:              # largest divisor <= requested size
                g -= 1
            G = S // g
            capacity = max(1, int(math.ceil(cf * g * k / E)))
            fn = functools.partial(_moe_dispatch, k=k, capacity=capacity)
            if vv is None:
                dispatch, combine, aux = jax.vmap(fn)(lg.reshape(G, g, E))
            else:
                dispatch, combine, aux = jax.vmap(fn)(
                    lg.reshape(G, g, E),
                    valid=vv.reshape(G, g).astype(jnp.float32))
            aux = jnp.mean(aux)       # equal groups: mean == global
            xs = xv.reshape(G, g, C)
            # dispatch -> per-expert buffers -> FFN -> combine back
            ein = dispatch.astype(xv.dtype)
            expert_in = jnp.einsum("gsec,gsm->gecm", ein, xs)
            h = jnp.einsum("gecm,emh->gech", expert_in, w1v) \
                + b1v[None, :, None, :]
            h = jax.nn.gelu(h) if act == "gelu" else jax.nn.relu(h)
            y = jnp.einsum("gech,ehm->gecm", h, w2v) \
                + b2v[None, :, None, :]
            out = jnp.einsum("gsec,gecm->gsm",
                             combine.astype(xv.dtype), y)
            return out.reshape(B, T, C), aux

        if valid is None:
            out, aux = _invoke(run, [x, logits, w1, b1, w2, b2],
                               name="moe_ffn")
        else:
            out, aux = _invoke(
                lambda xv, lg, w1v, b1v, w2v, b2v, vv:
                    run(xv, lg, w1v, b1v, w2v, b2v, vv),
                [x, logits, w1, b1, w2, b2, valid], name="moe_ffn")
        return out, aux


class MoELoss(HybridBlock):
    """Wrap a base loss to add the router's load-balancing term: takes
    ``(out, aux, *labels)`` — the output signature of any MoE model
    (e.g. ``GPTModel(moe_experts=E)``) — and returns
    ``mean(base(out, *labels)) + aux_weight * aux`` (Switch uses
    aux_weight 1e-2).  Drop-in loss block for Trainer/SPMDTrainer."""

    def __init__(self, base, aux_weight=1e-2, **kwargs):
        super().__init__(**kwargs)
        self._aux_weight = aux_weight
        with self.name_scope():
            self.base = base

    def hybrid_forward(self, F, out, aux, *labels):
        return self.base(out, *labels).mean() + self._aux_weight * aux


def ep_rules(expert_axis="expert", block=None):
    """Expert-parallel sharding: the stacked expert axis of every expert
    parameter shards over the mesh's expert axis; GSPMD inserts the
    token all-to-alls.  Compose with tp/dp rules by concatenation.

    With ``block`` (a MoEFFN, or any Block containing them) the rules
    are derived from the ACTUAL parameter names — use this whenever the
    layers were built with a custom ``prefix=``, which the default
    auto-prefix regexes cannot see (they would silently replicate the
    experts)."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.spmd import exact_rule
    specs = {"w1": P(expert_axis, None, None),
             "b1": P(expert_axis, None),
             "w2": P(expert_axis, None, None),
             "b2": P(expert_axis, None)}
    if block is not None:
        rules = []
        blocks = []
        block.apply(lambda b: blocks.append(b)
                    if isinstance(b, MoEFFN) else None)
        if not blocks:
            raise MXNetError("ep_rules(block=...): no MoEFFN found")
        for b in blocks:
            rules.extend(exact_rule(getattr(b, short), spec)
                         for short, spec in specs.items())
        return rules
    return [(rf"moeffn\d+_{short}$", spec)
            for short, spec in specs.items()]
