"""Decoder-only causal language model (GPT-2 style; reference workload:
GluonNLP ``scripts/language_model`` + ``model.train.GPT2Model``, built —
like every model here — from this repo's op surface:
gluon.nn.Dense/LayerNorm/Embedding, python/mxnet/gluon/nn/basic_layers.py).

TPU-first design (mirrors models/bert.py and models/transformer.py):
  * pre-LN blocks; self-attention is the ONE fused SDPA op from bert.py,
    causal mask baked in statically — the whole stack is a single XLA
    program under hybridize/SPMDTrainer;
  * generation is a ``lax.scan`` over decode steps with per-layer KV
    caches in the carry (O(T) per step); ``use_cache=False`` re-runs the
    full prefix each step and is the tested oracle;
  * sampling (temperature / top-k) uses a threaded PRNG key in the scan
    carry — one compiled program, reproducible from mx.random.seed;
  * Megatron ``tp_rules`` + optional ``seq_axis`` ring/Ulysses attention
    make the same model the long-context/multichip workload.
"""
from __future__ import annotations

import math

import numpy as _np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray, _invoke
from .bert import MultiHeadAttention, PositionwiseFFN, maybe_remat_cell

__all__ = ["GPTCell", "GPTModel", "gpt_tiny", "gpt2_124m", "tp_rules"]


class GPTCell(HybridBlock):
    """Pre-LN decoder block: x + attn(ln1(x)), then x + ffn(ln2(x))."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 seq_axis=None, mesh=None, moe_experts=0, moe_top_k=2,
                 moe_capacity_factor=1.25, **kwargs):
        super().__init__(**kwargs)
        self._moe = int(moe_experts) > 0
        with self.name_scope():
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.attention = MultiHeadAttention(
                units, num_heads, dropout, causal=True,
                seq_axis=seq_axis, mesh=mesh)
            self.ln2 = nn.LayerNorm(in_channels=units)
            if self._moe:
                from .moe import MoEFFN
                self.ffn = MoEFFN(units, hidden_size, moe_experts,
                                  top_k=moe_top_k,
                                  capacity_factor=moe_capacity_factor)
                # MoEFFN is dropout-free inside (the routed einsums are
                # pure); regularize the combined output instead — the
                # Megatron-MoE placement
                self.moe_drop = nn.Dropout(dropout)
            else:
                self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                           activation="gelu")

    def hybrid_forward(self, F, x, valid=None):
        x = x + self.attention(self.ln1(x))
        if self._moe:
            if valid is None:
                y, aux = self.ffn(self.ln2(x))
            else:
                y, aux = self.ffn(self.ln2(x), valid)
            return x + self.moe_drop(y), aux
        return x + self.ffn(self.ln2(x))

    def prime(self, x):
        """Full-prefix forward that ALSO returns this layer's K/V
        projections — fills the generation cache in one pass, projecting
        each of Q/K/V exactly once (the plain forward would recompute
        K/V inside the attention block)."""
        from .bert import _sdpa
        at = self.attention
        h = self.ln1(x)
        q, k, v = at.query(h), at.key(h), at.value(h)
        out = _sdpa(q, k, v, at._num_heads, causal=True)
        x = x + at.dropout(at.proj(out))
        return x + self._ffn_out(self.ln2(x)), k, v

    def _ffn_out(self, h):
        """FFN output with the MoE aux loss discarded — the generation
        paths are inference-only, where only the activations matter."""
        if self._moe:
            return self.ffn(h)[0]
        return self.ffn(h)

    def step(self, x, cache_k, cache_v, t):
        """One-position incremental step: x (B, 1, C) at position ``t``,
        cache_k/v (B, Tmax, C) holding positions < t.  Returns
        (y (B, 1, C), cache_k', cache_v')."""
        import functools
        from .bert import cached_step_attn
        at = self.attention
        h = self.ln1(x)
        q, k_new, v_new = at.query(h), at.key(h), at.value(h)
        out, ck, cv = _invoke(
            functools.partial(cached_step_attn, num_heads=at._num_heads),
            [q, k_new, v_new, cache_k, cache_v, t], name="gpt_step_attn")
        out = x + at.dropout(at.proj(out))
        return out + self._ffn_out(self.ln2(out)), ck, cv


class GPTModel(HybridBlock):
    """Token + LEARNED position embeddings -> N GPTCells -> final LN ->
    tied LM head (logits through the embedding matrix, GPT-2's tying)."""

    def __init__(self, vocab_size, units=128, hidden_size=512,
                 num_layers=2, num_heads=2, max_length=256, dropout=0.1,
                 seq_axis=None, mesh=None, moe_experts=0, moe_top_k=2,
                 moe_capacity_factor=1.25, **kwargs):
        super().__init__(**kwargs)
        self._vocab_size = vocab_size
        self._units = units
        self._max_length = max_length
        self._moe = int(moe_experts) > 0
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units)
            self.pos_embed = nn.Embedding(max_length, units)
            self.drop = nn.Dropout(dropout)
            self.cells = nn.HybridSequential()
            for _ in range(num_layers):
                self.cells.add(GPTCell(
                    units, hidden_size, num_heads, dropout,
                    seq_axis=seq_axis, mesh=mesh, moe_experts=moe_experts,
                    moe_top_k=moe_top_k,
                    moe_capacity_factor=moe_capacity_factor))
            self.ln_f = nn.LayerNorm(in_channels=units)

    # -- helpers -------------------------------------------------------
    def _positions(self, ids, offset=0):
        def fn(iv):
            import jax.numpy as jnp
            T = iv.shape[1]
            return jnp.broadcast_to(
                jnp.arange(offset, offset + T, dtype=jnp.int32)[None],
                iv.shape)
        return _invoke(fn, [ids], name="gpt_positions")

    def _embed_at(self, ids, offset=0):
        x = self.embed(ids) + self.pos_embed(self._positions(ids, offset))
        return self.drop(x)

    def _project(self, x):
        """Tied LM head: logits = x @ E^T.  The embedding Parameter's own
        NDArray goes into the op, so the eager autograd tape reaches it —
        a fresh wrapper would silently drop the head's gradient."""
        w = self.embed.weight.data()
        return _invoke(_lm_logits, [x, w], name="gpt_lm_head")

    def hybrid_forward(self, F, ids):
        if ids.shape[1] > self._max_length:
            raise MXNetError(
                f"sequence length {ids.shape[1]} exceeds max_length "
                f"{self._max_length}")
        x = self._embed_at(ids)
        aux_total = None
        for cell in self.cells._children.values():
            out = maybe_remat_cell(cell, x)
            if cell._moe:
                x, aux = out
                aux_total = aux if aux_total is None else aux_total + aux
            else:
                x = out
        logits = self._project(self.ln_f(x))
        if self._moe:
            # SUM over MoE layers (the Switch recipe): loss adds
            # aux_weight * aux once, regardless of depth
            return logits, aux_total
        return logits

    # -- pipeline parallelism ------------------------------------------
    def pipeline_split(self):
        """Stage protocol for ``parallel.PipelineTrainer`` (reached via
        ``SPMDTrainer(..., pipeline_axis=...)``): returns
        ``(first_params, first_fn, cells, last_params, last_fn)``.
        Stage 0 owns the embeddings (``first_fn`` embeds a microbatch of
        ids into (b, T, C)); every stage runs its contiguous slice of
        ``cells``; the last stage applies the final LayerNorm and the
        TIED LM head — the embedding matrix arrives back via
        ``first_vals`` so the tying (and both gradient contributions,
        summed by the pipe-axis psum) is preserved.  Requires
        dropout=0 (the trainer enforces the pure-stage contract)."""
        import jax

        if self._moe:
            raise MXNetError(
                "pipeline_split does not yet support MoE cells (the "
                "stage protocol carries one activation tensor, not the "
                "aux loss); use expert parallelism (ep_rules) instead")

        first_params = [self.embed.weight, self.pos_embed.weight]
        max_length = self._max_length

        def first_fn(vals, ids):
            import jax.numpy as jnp
            E, Ppos = vals
            T = ids.shape[-1]
            if T > max_length:       # static shape — trace-time guard,
                raise MXNetError(    # same contract as hybrid_forward
                    f"sequence length {T} exceeds max_length "
                    f"{max_length}")
            pos = Ppos[jnp.arange(T)][None]
            return E[ids] + pos.astype(E.dtype)

        cells = list(self.cells._children.values())
        ln = self.ln_f
        last_params = [ln.gamma, ln.beta]
        key = jax.random.PRNGKey(0)     # LN consumes no randomness

        def last_fn(vals, first_vals, xv):
            from ..gluon.block import functional_call
            outs, _ = functional_call(ln, last_params, list(vals),
                                      [], [], [NDArray(xv)], False, key)
            return _lm_logits(outs[0], first_vals[0])

        return first_params, first_fn, cells, last_params, last_fn

    # -- generation ----------------------------------------------------
    def generate(self, ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, top_p=0.0, use_cache=True, seed=None):
        """Autoregressive continuation of prompt ``ids`` (B, Tp) int32.

        temperature == 0 -> greedy; otherwise softmax sampling at that
        temperature, restricted to the ``top_k`` highest logits when
        top_k > 0 and/or to the nucleus of smallest cumulative
        probability mass >= ``top_p`` when 0 < top_p < 1 (the top-1
        token always survives; both filters compose, top-k first).  One ``lax.scan`` program either way; ``use_cache``
        False re-runs the full prefix per step (the oracle).  Returns
        (B, Tp + max_new_tokens) int32 tokens.

        MoE models: padding positions are masked out of the router (they
        claim no expert capacity), so cached == full-prefix holds in the
        no-drop regime (ample ``moe_capacity_factor``).  Under capacity
        pressure the two paths form different routing groups (prefill
        routes B*Tp tokens at once, a decode step routes B) and may drop
        different tokens — inherent to capacity-based GShard routing,
        exactly as train-time vs incremental-serve routing differs in
        the public Switch/GShard implementations."""
        B, Tp = ids.shape
        total = Tp + max_new_tokens
        if total > self._max_length:
            raise MXNetError(
                f"prompt {Tp} + {max_new_tokens} new tokens exceeds "
                f"max_length {self._max_length}")
        if max_new_tokens < 0:
            raise MXNetError(
                f"max_new_tokens={max_new_tokens} is negative (a "
                "miscomputed budget?); use 0 for no-op generation")
        if max_new_tokens == 0:
            return ids
        from .. import random as _random
        key = _random.new_key() if seed is None else None
        if seed is not None:
            import jax
            key = jax.random.PRNGKey(seed)
        if use_cache:
            return self._generate_cached(ids, max_new_tokens, temperature,
                                         top_k, top_p, key)
        return self._generate_full(ids, max_new_tokens, temperature,
                                   top_k, top_p, key)

    def _sample_fn(self, temperature, top_k, top_p=0.0):
        if not 0.0 <= float(top_p) <= 1.0:
            raise MXNetError(f"top_p={top_p} outside [0, 1]")

        def pick(logits, key):
            import jax
            import jax.numpy as jnp
            lf = logits.astype(jnp.float32)
            if temperature <= 0.0:
                return jnp.argmax(lf, axis=-1).astype(jnp.int32)
            lf = lf / temperature
            k = min(int(top_k), lf.shape[-1]) if top_k else 0
            need_sort = (k > 0 and k < lf.shape[-1]) or 0.0 < top_p < 1.0
            if need_sort:
                # ONE descending sort feeds both filters (the nucleus
                # runs on the already-top-k-masked order: -inf entries
                # carry zero probability mass, so they can never be
                # kept or become the cutoff)
                srt = -jnp.sort(-lf, axis=-1)
            if k > 0 and k < lf.shape[-1]:
                # top_k >= vocab degenerates to plain sampling (GPT-2
                # convention) rather than an out-of-bounds sort index
                kth = srt[..., k - 1][..., None]
                lf = jnp.where(lf >= kth, lf, -jnp.inf)
                srt = jnp.where(jnp.arange(srt.shape[-1]) < k, srt,
                                -jnp.inf)
            if 0.0 < top_p < 1.0:
                # nucleus filter: keep the smallest prefix of the
                # descending-prob sort whose mass reaches top_p; the
                # exclusive cumsum keeps the top-1 token unconditionally
                probs = jax.nn.softmax(srt, axis=-1)
                before = jnp.cumsum(probs, axis=-1) - probs
                keep = before < top_p
                cutoff = jnp.min(jnp.where(keep, srt, jnp.inf),
                                 axis=-1, keepdims=True)
                lf = jnp.where(lf >= cutoff, lf, -jnp.inf)
            return jax.random.categorical(key, lf, axis=-1).astype(
                jnp.int32)
        return pick

    def _generate_full(self, ids, n_new, temperature, top_k, top_p,
                       key):
        """Oracle: whole prefix re-run per step, lax.scan outside."""
        pick = self._sample_fn(temperature, top_k, top_p)
        B, Tp = ids.shape
        total = Tp + n_new

        # pad to the full length once; scan carries (tokens, t, key)
        def fn(iv):
            import jax
            import jax.numpy as jnp

            toks0 = jnp.zeros((B, total), jnp.int32)
            toks0 = jax.lax.dynamic_update_slice(toks0, iv, (0, 0))

            def body(carry, _):
                toks, t, k = carry
                logits = self._fwd_tokens(toks, n_valid=t)  # (B, total, V)
                last = jnp.take_along_axis(
                    logits, (t - 1)[None, None, None].astype(jnp.int32)
                    .repeat(B, 0), axis=1)[:, 0]
                k, sub = jax.random.split(k)
                nxt = pick(last, sub)
                toks = toks.at[:, t].set(nxt)
                return (toks, t + 1, k), None

            (toks, _, _), _ = jax.lax.scan(
                body, (toks0, jnp.int32(Tp), key), None, length=n_new)
            return toks
        return _invoke(fn, [ids], name="gpt_generate_full")

    def _fwd_tokens(self, toks, n_valid=None):
        """jax-level forward over already-jax tokens (inside scan).
        ``n_valid`` (scalar, may be traced) marks how many leading
        positions hold real tokens: causal attention already ignores
        the zero-padded tail, but MoE routing would otherwise let
        padding claim expert capacity away from real tokens."""
        import jax.numpy as jnp
        x = self.embed.weight.data()._data[toks]
        pos = self.pos_embed.weight.data()._data[
            jnp.arange(toks.shape[1])]
        x = (x + pos[None].astype(x.dtype))
        xn = NDArray(x)
        valid = None
        if n_valid is not None and self._moe:
            valid = NDArray(jnp.broadcast_to(
                (jnp.arange(toks.shape[1]) < n_valid)[None], toks.shape)
                .astype(jnp.float32))
        for cell in self.cells._children.values():
            if cell._moe:
                xn = (cell(xn) if valid is None else cell(xn, valid))[0]
            else:
                xn = cell(xn)
        out = self.ln_f(xn)
        return _lm_logits(out._data, self.embed.weight.data()._data)

    def _generate_cached(self, ids, n_new, temperature, top_k, top_p,
                         key):
        pick = self._sample_fn(temperature, top_k, top_p)
        B, Tp = ids.shape
        total = Tp + n_new
        C = self._units
        cells = list(self.cells._children.values())

        # prime: one full-prefix pass filling each layer's cache
        x = self._embed_at(ids)
        caches = []
        for cell in cells:
            x, k_proj, v_proj = cell.prime(x)
            ck = _invoke(
                lambda kv: _pad_cache(kv, total), [k_proj],
                name="gpt_cache_pad")
            cv = _invoke(
                lambda kv: _pad_cache(kv, total), [v_proj],
                name="gpt_cache_pad")
            caches.append((ck, cv))
        logits_p = self._project(self.ln_f(x))

        def fn(iv, lp, *flat):
            import jax
            import jax.numpy as jnp
            cks = flat[0::2]
            cvs = flat[1::2]

            toks0 = jnp.zeros((B, total), jnp.int32)
            toks0 = jax.lax.dynamic_update_slice(toks0, iv, (0, 0))
            k0, sub0 = jax.random.split(key)
            first = pick(lp[:, -1], sub0)
            toks0 = toks0.at[:, Tp].set(first)

            def body(carry, _):
                toks, t, k, caches_c = carry
                # the token at position t is the newest one; its logits
                # produce position t+1
                cur = jnp.take_along_axis(
                    toks, jnp.broadcast_to(
                        t.reshape(1, 1), (B, 1)).astype(jnp.int32),
                    axis=1)
                xn = NDArray(
                    self.embed.weight.data()._data[cur]
                    + self.pos_embed.weight.data()._data[t][None, None])
                new_caches = []
                for cell, (ck, cv) in zip(cells, caches_c):
                    xn, ck2, cv2 = cell.step(
                        xn, NDArray(ck), NDArray(cv), NDArray(t))
                    new_caches.append((ck2._data, cv2._data))
                out = self.ln_f(xn)
                logits = _lm_logits(
                    out._data, self.embed.weight.data()._data)[:, 0]
                k, sub = jax.random.split(k)
                nxt = pick(logits, sub)
                toks = toks.at[:, t + 1].set(nxt)
                return (toks, t + 1, k, tuple(new_caches)), None

            caches_c = tuple((ck, cv) for ck, cv in zip(cks, cvs))
            (toks, _, _, _), _ = jax.lax.scan(
                body, (toks0, jnp.int32(Tp), k0, caches_c), None,
                length=max(n_new - 1, 0))
            return toks

        flat = []
        for ck, cv in caches:
            flat += [ck, cv]
        return _invoke(fn, [ids, logits_p] + flat, name="gpt_generate")


def _lm_logits(xv, wv):
    """The tied-head einsum, jax-level — the ONE definition every logits
    site (training forward, full-prefix oracle, cached scan body) uses."""
    import jax.numpy as jnp
    return jnp.einsum("btc,vc->btv", xv, wv.astype(xv.dtype))


def _pad_cache(kv, total):
    import jax.numpy as jnp
    B, Tp, C = kv.shape
    pad = jnp.zeros((B, total - Tp, C), kv.dtype)
    return jnp.concatenate([kv, pad], axis=1)


def tp_rules(model_axis="model", block=None):
    """Megatron sharding for SPMDTrainer (same spirit as bert.tp_rules):
    attention QKV + first FFN matmul column-parallel, attention proj +
    second FFN matmul row-parallel, embeddings row-sharded over vocab.
    Pass ``block=`` (the built net) for exact-name rules — required with
    custom ``prefix=`` models, where the auto-prefix regexes below would
    silently replicate the weights (SPMDTrainer warns on dead rules)."""
    from jax.sharding import PartitionSpec as P
    if block is not None:
        from .bert import derive_tp_rules, exact_rule

        def gpt_extra(b):
            if isinstance(b, GPTModel):
                return [exact_rule(b.embed.weight, P(model_axis, None))]
            return []
        return derive_tp_rules(block, model_axis, extra=gpt_extra)
    from .bert import core_tp_regex_rules
    return core_tp_regex_rules(model_axis) + [
        (r"gptmodel\d+_embedding0_weight", P(model_axis, None)),
    ]


def gpt_tiny(vocab_size=512, **kwargs):
    kwargs.setdefault("units", 64)
    kwargs.setdefault("hidden_size", 128)
    kwargs.setdefault("num_layers", 2)
    kwargs.setdefault("num_heads", 2)
    kwargs.setdefault("max_length", 128)
    return GPTModel(vocab_size, **kwargs)


def gpt2_124m(vocab_size=50257, **kwargs):
    """GPT-2 small (124M): 12 layers, 768 units, 12 heads, ctx 1024."""
    kwargs.setdefault("units", 768)
    kwargs.setdefault("hidden_size", 3072)
    kwargs.setdefault("num_layers", 12)
    kwargs.setdefault("num_heads", 12)
    kwargs.setdefault("max_length", 1024)
    return GPTModel(vocab_size, **kwargs)
