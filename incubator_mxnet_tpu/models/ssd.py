"""SSD single-shot detector (reference workload: SSD-512 COCO/VOC —
``example/ssd`` in the reference repo builds it from Convolution +
contrib multibox ops: src/operator/contrib/multibox_prior.cc,
multibox_target.cc, multibox_detection.cc).

TPU-first design choices:
  * the whole multi-scale forward is one HybridBlock — anchors, class
    heads, and box heads concatenate into static-shape (B, N, ·) tensors
    so the compiled program has no dynamic shapes;
  * anchor generation is constant-folded by XLA (MultiBoxPrior depends
    only on feature-map shape);
  * target assignment (MultiBoxTarget) and NMS decode (MultiBoxDetection)
    are fixed-size masked programs rather than data-dependent loops — the
    XLA-friendly re-derivation of the reference's CUDA kernels.
"""
from __future__ import annotations

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray import contrib as _contrib
from ..ndarray.ndarray import NDArray, _invoke

__all__ = ["SSD", "SSDLoss", "ssd_512", "ssd_300", "ssd_tiny"]


def _conv_block(out, channels, kernel=3, stride=1, pad=1):
    out.add(nn.Conv2D(channels, kernel, stride, pad, use_bias=False))
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))


class _DownsampleBackbone(HybridBlock):
    """Plain conv backbone emitting one feature map per scale.

    ``stage_channels`` — channels per downsampling stage; the last
    ``num_scales`` stage outputs feed the detection heads (reference
    analog: VGG-reduced + extra layers in example/ssd/symbol/symbol_builder.py).
    """

    def __init__(self, stage_channels, num_scales, **kwargs):
        super().__init__(**kwargs)
        self._num_scales = num_scales
        self._stages = []
        with self.name_scope():
            for i, ch in enumerate(stage_channels):
                stage = nn.HybridSequential(prefix=f"stage{i}_")
                with stage.name_scope():
                    _conv_block(stage, ch)
                    _conv_block(stage, ch)
                    stage.add(nn.MaxPool2D(2, 2))
                self.register_child(stage, f"stage{i}")
                self._stages.append(stage)

    def hybrid_forward(self, F, x):
        feats = []
        for stage in self._stages:
            x = stage(x)
            feats.append(x)
        return feats[-self._num_scales:]


class SSD(HybridBlock):
    """forward(x) -> (anchors (1,N,4), cls_preds (B,N,C+1),
    box_preds (B,N*4)); N = sum over scales of H*W*A.

    ``sizes``/``ratios`` — per-scale anchor configs as in
    contrib.MultiBoxPrior (A = len(sizes)+len(ratios)-1 per position).
    """

    def __init__(self, num_classes, stage_channels, sizes, ratios,
                 num_scales=None, **kwargs):
        super().__init__(**kwargs)
        num_scales = num_scales or len(sizes)
        if not (len(sizes) == len(ratios) == num_scales):
            raise ValueError("sizes/ratios must have one entry per scale")
        self._num_classes = num_classes
        self._sizes = sizes
        self._ratios = ratios
        with self.name_scope():
            self.backbone = _DownsampleBackbone(stage_channels, num_scales)
            self._cls_heads, self._box_heads = [], []
            for i in range(num_scales):
                A = len(sizes[i]) + len(ratios[i]) - 1
                cls = nn.Conv2D(A * (num_classes + 1), 3, 1, 1)
                box = nn.Conv2D(A * 4, 3, 1, 1)
                self.register_child(cls, f"cls_head{i}")
                self.register_child(box, f"box_head{i}")
                self._cls_heads.append(cls)
                self._box_heads.append(box)

    @staticmethod
    def _flatten_pred(pred, last):
        """(B, A*last, H, W) -> (B, H*W*A, last)."""
        def fn(p):
            import jax.numpy as jnp
            B, AL, H, W = p.shape
            p = p.transpose(0, 2, 3, 1).reshape(B, H * W * (AL // last),
                                                last)
            return p
        return _invoke(fn, [pred], name="ssd_flatten_pred")

    def hybrid_forward(self, F, x):
        feats = self.backbone(x)
        anchors, cls_preds, box_preds = [], [], []
        for i, feat in enumerate(feats):
            anchors.append(_contrib.MultiBoxPrior(
                feat, sizes=self._sizes[i], ratios=self._ratios[i],
                clip=False))
            cls_preds.append(self._flatten_pred(
                self._cls_heads[i](feat), self._num_classes + 1))
            box_preds.append(self._flatten_pred(
                self._box_heads[i](feat), 4))

        def cat(*xs):
            import jax.numpy as jnp
            return jnp.concatenate(xs, axis=1)
        anchor = _invoke(cat, anchors, name="ssd_cat_anchors")
        cls_pred = _invoke(cat, cls_preds, name="ssd_cat_cls")
        box_pred = _invoke(cat, box_preds, name="ssd_cat_box")
        box_pred = box_pred.reshape(box_pred.shape[0], -1)
        return anchor, cls_pred, box_pred

    def targets(self, anchor, label, cls_pred,
                negative_mining_ratio=3.0):
        """MultiBoxTarget wrapper: label (B,M,5) [cls,x0,y0,x1,y1], pad
        rows cls=-1.  Returns loc_target, loc_mask, cls_target."""
        return _contrib.MultiBoxTarget(
            anchor, label, cls_pred.transpose(0, 2, 1),
            negative_mining_ratio=negative_mining_ratio)

    def detect(self, x, threshold=0.01, nms_threshold=0.45, nms_topk=400):
        """Inference: decode + per-class NMS -> (B, N, 6) rows
        [cls_id, score, x0, y0, x1, y1], -1 rows invalid."""
        from .. import ndarray as F
        anchor, cls_pred, box_pred = self(x)
        cls_prob = F.softmax(cls_pred, axis=-1).transpose(0, 2, 1)
        return _contrib.MultiBoxDetection(
            cls_prob, box_pred, anchor, threshold=threshold,
            nms_threshold=nms_threshold, nms_topk=nms_topk)


class SSDLoss(HybridBlock):
    """Hard-negative-mined softmax CE over classes + smooth-L1 over
    encoded offsets (reference: example/ssd/symbol/symbol_builder.py
    training head).  cls_target -1 entries (ignored negatives) drop out
    of both terms."""

    def __init__(self, num_classes, lambd=1.0, **kwargs):
        super().__init__(**kwargs)
        self._C = num_classes + 1
        self._lambd = lambd

    def hybrid_forward(self, F, cls_pred, loc_pred, cls_target,
                       loc_target, loc_mask):
        C, lambd = self._C, self._lambd

        def fn(cp, lp, ct, lt, lm):
            import jax
            import jax.numpy as jnp
            logp = jax.nn.log_softmax(cp.astype(jnp.float32), axis=-1)
            ctc = jnp.clip(ct, 0, C - 1).astype(jnp.int32)
            nll = -jnp.take_along_axis(logp, ctc[..., None],
                                       axis=-1)[..., 0]
            keep = (ct >= 0).astype(nll.dtype)
            cls_loss = jnp.sum(nll * keep) / jnp.maximum(jnp.sum(keep), 1.0)
            d = (lp - lt) * lm
            ad = jnp.abs(d)
            sl1 = jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5)
            npos = jnp.maximum(jnp.sum(lm) / 4.0, 1.0)
            loc_loss = jnp.sum(sl1) / npos
            return cls_loss + lambd * loc_loss
        return _invoke(fn, [cls_pred, loc_pred, cls_target, loc_target,
                            loc_mask], name="ssd_loss")


def ssd_512(num_classes=80, **kw):
    """SSD-512 COCO-shaped config (the judged BASELINE workload):
    7 feature scales from 512x512 input."""
    sizes = [(0.07, 0.1025), (0.15, 0.2121), (0.3, 0.3674),
             (0.45, 0.5196), (0.6, 0.6708), (0.75, 0.8216),
             (0.9, 0.9721)]
    ratios = [(1, 2, 0.5)] * 3 + [(1, 2, 0.5, 3, 1.0 / 3)] * 4
    return SSD(num_classes,
               stage_channels=(64, 128, 256, 512, 512, 256, 256),
               sizes=sizes, ratios=ratios, **kw)


def ssd_300(num_classes=20, **kw):
    sizes = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
             (0.71, 0.79), (0.88, 0.961)]
    ratios = [(1, 2, 0.5)] * 2 + [(1, 2, 0.5, 3, 1.0 / 3)] * 4
    return SSD(num_classes, stage_channels=(64, 128, 256, 512, 256, 256),
               sizes=sizes, ratios=ratios, **kw)


def ssd_tiny(num_classes=3, **kw):
    """Small config for tests: 2 scales."""
    return SSD(num_classes, stage_channels=(8, 16),
               sizes=[(0.2, 0.272), (0.5, 0.62)],
               ratios=[(1, 2, 0.5)] * 2, **kw)
