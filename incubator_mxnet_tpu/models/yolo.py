"""YOLOv3 detector (reference workload: YOLOv3 COCO — GluonCV
``model_zoo/yolo`` builds it from this repo's Convolution/BatchNorm/
LeakyReLU + slice/sigmoid ops; the reference repo itself ships the ops
and the ``example/ssd`` detection tooling).

TPU-first design choices:
  * three scale heads emit static-shape (B, N, 5+C) predictions that are
    concatenated once — no per-box Python control flow anywhere;
  * target assignment is a dense one-shot scatter (best-anchor matching
    computed with vectorized shape-IoU + ``argmax``), so one XLA program
    builds all targets — the re-derivation of GluonCV's
    ``YOLOV3TargetMerger`` without dynamic shapes;
  * decode (grid offsets + anchor scaling) is folded into the same
    program as the heads.
"""
from __future__ import annotations

import numpy as _np

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray import contrib as _contrib
from ..ndarray.ndarray import NDArray, _invoke

__all__ = ["YOLOv3", "YOLOv3Loss", "yolo3_darknet53", "yolo3_tiny"]


def _conv_bn_leaky(out, channels, kernel, stride=1):
    pad = kernel // 2
    out.add(nn.Conv2D(channels, kernel, stride, pad, use_bias=False))
    out.add(nn.BatchNorm())
    out.add(nn.LeakyReLU(0.1))


class _DarknetBlock(HybridBlock):
    """Residual 1x1-reduce + 3x3 block (reference analog: GluonCV
    DarknetBasicBlockV3)."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.body = nn.HybridSequential()
            with self.body.name_scope():
                _conv_bn_leaky(self.body, channels // 2, 1)
                _conv_bn_leaky(self.body, channels, 3)

    def hybrid_forward(self, F, x):
        return x + self.body(x)


class _Darknet(HybridBlock):
    """Darknet-style backbone emitting features at strides 8/16/32.

    ``stage_channels``/``stage_blocks`` control depth; darknet53 uses
    (64,128,256,512,1024) x (1,2,8,8,4)."""

    def __init__(self, stage_channels, stage_blocks, **kwargs):
        super().__init__(**kwargs)
        self._n_stages = len(stage_channels)
        with self.name_scope():
            stem = nn.HybridSequential(prefix="stem_")
            with stem.name_scope():
                _conv_bn_leaky(stem, max(stage_channels[0] // 2, 8), 3)
            self.register_child(stem, "stem")
            for i, (ch, nb) in enumerate(zip(stage_channels, stage_blocks)):
                stage = nn.HybridSequential(prefix=f"stage{i}_")
                with stage.name_scope():
                    _conv_bn_leaky(stage, ch, 3, stride=2)
                    for _ in range(nb):
                        stage.add(_DarknetBlock(ch))
                self.register_child(stage, f"stage{i}")

    def hybrid_forward(self, F, x):
        children = list(self._children.values())
        x = children[0](x)
        feats = []
        for stage in children[1:]:
            x = stage(x)
            feats.append(x)
        return feats[-3:]   # strides 8, 16, 32 (for >=3 stages)


class YOLOv3(HybridBlock):
    """forward(x) -> (B, N, 5+C) raw predictions + self.anchors/strides
    metadata; ``decode`` turns them into boxes.

    ``anchors``: 3 scale groups of (A, 2) pixel anchor shapes, small
    scale first (GluonCV convention).  N = sum H_s*W_s*A."""

    def __init__(self, num_classes, stage_channels, stage_blocks, anchors,
                 strides=(8, 16, 32), **kwargs):
        super().__init__(**kwargs)
        if len(anchors) != 3:
            raise ValueError("anchors must have 3 scale groups")
        self._C = num_classes
        self.anchors = [_np.asarray(a, _np.float32).reshape(-1, 2)
                        for a in anchors]
        self.strides = tuple(strides)
        with self.name_scope():
            self.backbone = _Darknet(stage_channels, stage_blocks)
            for i in range(3):
                A = self.anchors[i].shape[0]
                head = nn.HybridSequential(prefix=f"head{i}_")
                with head.name_scope():
                    _conv_bn_leaky(head, stage_channels[-3 + i], 3)
                    head.add(nn.Conv2D(A * (5 + num_classes), 1, 1, 0))
                self.register_child(head, f"head{i}")

    def hybrid_forward(self, F, x):
        feats = self.backbone(x)
        heads = [self._children[f"head{i}"] for i in range(3)]
        preds = [heads[i](feats[i]) for i in range(3)]
        C = self._C

        def fn(*ps):
            import jax.numpy as jnp
            outs = []
            for p in ps:
                B, AL, H, W = p.shape
                A = AL // (5 + C)
                outs.append(p.transpose(0, 2, 3, 1)
                            .reshape(B, H * W * A, 5 + C))
            return jnp.concatenate(outs, axis=1)
        return _invoke(fn, preds, name="yolo_gather_heads")

    # -- static per-input-shape anchor/grid metadata ---------------------
    def _grid_meta(self, in_h, in_w):
        """Per-prediction-row [cx_cell, cy_cell, anchor_w, anchor_h,
        stride] as one (N, 5) numpy constant."""
        rows = []
        for s, anc in zip(self.strides, self.anchors):
            H, W = in_h // s, in_w // s
            A = anc.shape[0]
            gy, gx = _np.meshgrid(_np.arange(H), _np.arange(W),
                                  indexing="ij")
            cell = _np.stack([gx, gy], -1).reshape(H * W, 1, 2)
            cell = _np.broadcast_to(cell, (H * W, A, 2)).reshape(-1, 2)
            aa = _np.broadcast_to(anc[None], (H * W, A, 2)).reshape(-1, 2)
            st = _np.full((H * W * A, 1), s, _np.float32)
            rows.append(_np.concatenate([cell, aa, st], 1))
        return _np.concatenate(rows, 0).astype(_np.float32)

    def decode(self, preds, in_shape):
        """Raw (B,N,5+C) -> (boxes (B,N,4) corner pixels, obj (B,N),
        cls_prob (B,N,C))."""
        meta = self._grid_meta(*in_shape)

        def fn(p):
            import jax
            import jax.numpy as jnp
            m = jnp.asarray(meta)
            xy = (jax.nn.sigmoid(p[..., 0:2]) + m[:, 0:2]) * m[:, 4:5]
            wh = jnp.exp(jnp.clip(p[..., 2:4], -8, 8)) * m[:, 2:4]
            boxes = jnp.concatenate([xy - wh / 2, xy + wh / 2], -1)
            obj = jax.nn.sigmoid(p[..., 4])
            cls = jax.nn.sigmoid(p[..., 5:])
            return boxes, obj, cls
        return _invoke(fn, [preds], name="yolo_decode")

    def targets(self, labels, in_shape):
        """Dense target builder (one XLA program).

        labels: (B, M, 5) rows [cls, x0, y0, x1, y1] in pixels, pad rows
        cls=-1.  Returns [obj_t (B,N), box_t (B,N,4) raw-pred-space,
        cls_t (B,N,C), weight (B,N)] — weight is the box-loss scale
        2 - w*h/(in_h*in_w) of GluonCV."""
        meta = self._grid_meta(*in_shape)
        offsets = []       # row offset of each scale group
        off = 0
        for s, anc in zip(self.strides, self.anchors):
            offsets.append(off)
            off += (in_shape[0] // s) * (in_shape[1] // s) * anc.shape[0]
        N = off
        C = self._C
        all_anc = _np.concatenate(self.anchors, 0)      # (3A, 2)
        per_scale_A = [a.shape[0] for a in self.anchors]
        in_h, in_w = in_shape

        def fn(lb):
            import jax
            import jax.numpy as jnp
            B, M, _ = lb.shape
            cls_id = lb[..., 0]
            x0, y0, x1, y1 = (lb[..., 1], lb[..., 2], lb[..., 3],
                              lb[..., 4])
            gw, gh = x1 - x0, y1 - y0
            gcx, gcy = (x0 + x1) / 2, (y0 + y1) / 2
            valid = cls_id >= 0

            anc = jnp.asarray(all_anc)                   # (K,2)
            inter = (jnp.minimum(gw[..., None], anc[:, 0])
                     * jnp.minimum(gh[..., None], anc[:, 1]))
            union = gw[..., None] * gh[..., None] \
                + anc[:, 0] * anc[:, 1] - inter
            best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)  # B,M

            # map best anchor -> (scale, anchor-in-scale)
            bounds = _np.cumsum([0] + per_scale_A)
            scale_idx = jnp.sum(
                best[..., None] >= jnp.asarray(bounds[1:-1])[None, None],
                -1) if len(per_scale_A) > 1 else jnp.zeros_like(best)
            a_in_s = best - jnp.asarray(bounds[:-1])[scale_idx]

            strides = jnp.asarray(_np.asarray(self.strides, _np.float32))
            st = strides[scale_idx]
            ci = jnp.clip((gcx // st), 0, in_w / st - 1).astype(jnp.int32)
            cj = jnp.clip((gcy // st), 0, in_h / st - 1).astype(jnp.int32)
            Ws = (in_w / st).astype(jnp.int32)
            As = jnp.asarray(_np.asarray(per_scale_A, _np.int32))[scale_idx]
            row = (jnp.asarray(_np.asarray(offsets, _np.int32))[scale_idx]
                   + (cj * Ws + ci) * As + a_in_s)      # B,M
            # pad rows scatter out-of-bounds and are dropped, so they can
            # never clobber a real target that lives at row 0
            row = jnp.where(valid, row, N)

            # raw-space regression targets
            tx = gcx / st - (gcx // st)
            ty = gcy / st - (gcy // st)
            aw = anc[best][..., 0]
            ah = anc[best][..., 1]
            tw = jnp.log(jnp.maximum(gw, 1.0) / aw)
            th = jnp.log(jnp.maximum(gh, 1.0) / ah)
            box_t_rows = jnp.stack([tx, ty, tw, th], -1)  # B,M,4
            w_rows = 2.0 - (gw * gh) / float(in_h * in_w)

            vf = valid.astype(jnp.float32)
            bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, M))
            obj_t = jnp.zeros((B, N)).at[bidx, row].max(vf, mode="drop")
            box_t = jnp.zeros((B, N, 4)).at[bidx, row].set(
                box_t_rows * vf[..., None], mode="drop")
            onehot = jax.nn.one_hot(jnp.clip(cls_id, 0).astype(jnp.int32),
                                    C) * vf[..., None]
            cls_t = jnp.zeros((B, N, C)).at[bidx, row].set(
                onehot, mode="drop")
            weight = jnp.zeros((B, N)).at[bidx, row].set(
                w_rows * vf, mode="drop")
            return obj_t, box_t, cls_t, weight
        return _invoke(fn, [labels], name="yolo_targets",
                       differentiable=False)

    def detect(self, x, threshold=0.01, nms_threshold=0.45, topk=100):
        """Full inference: decode + class-agnostic NMS via contrib.box_nms.
        Returns (B, N, 6) rows [cls_id, score, x0, y0, x1, y1]."""
        from .. import ndarray as F
        preds = self(x)
        in_shape = (x.shape[2], x.shape[3])
        boxes, obj, cls = self.decode(preds, in_shape)

        def fn(bx, ob, cl):
            import jax.numpy as jnp
            score = ob[..., None] * cl                  # B,N,C
            best_c = jnp.argmax(score, -1).astype(jnp.float32)
            best_s = jnp.max(score, -1)
            return jnp.concatenate(
                [best_c[..., None], best_s[..., None], bx], -1)
        raw = _invoke(fn, [boxes, obj, cls], name="yolo_gather_det")
        return _contrib.box_nms(raw, overlap_thresh=nms_threshold,
                                valid_thresh=threshold, topk=topk,
                                coord_start=2, score_index=1, id_index=0)


class YOLOv3Loss(HybridBlock):
    """Objectness BCE (with ignore region) + center BCE + size L2 + class
    BCE (reference analog: GluonCV YOLOV3Loss).  All terms masked by the
    dense targets from YOLOv3.targets.

    Pass decoded ``boxes`` + raw ``labels`` to enable the ignore mask:
    negatives whose decoded box overlaps any ground truth above
    ``ignore_iou_thresh`` are excluded from the objectness loss (the
    GluonCV dynamic-IoU rule, computed densely)."""

    def __init__(self, ignore_iou_thresh=0.7, **kwargs):
        super().__init__(**kwargs)
        self._ignore = ignore_iou_thresh

    def hybrid_forward(self, F, preds, obj_t, box_t, cls_t, weight,
                       boxes=None, labels=None):
        thresh = self._ignore
        inputs = [preds, obj_t, box_t, cls_t, weight]
        with_ignore = boxes is not None and labels is not None
        if with_ignore:
            inputs += [boxes, labels]

        def fn(p, ot, bt, ct, w, *rest):
            import jax
            import jax.numpy as jnp
            p = p.astype(jnp.float32)

            def bce(logit, target):
                return jnp.maximum(logit, 0) - logit * target \
                    + jnp.log1p(jnp.exp(-jnp.abs(logit)))
            obj_w = jnp.ones_like(ot)
            if rest:
                bx, lb = rest                       # (B,N,4), (B,M,5)
                gt = lb[..., 1:5]                   # corner pixels
                gt_valid = lb[..., 0] >= 0
                ix0 = jnp.maximum(bx[:, :, None, 0], gt[:, None, :, 0])
                iy0 = jnp.maximum(bx[:, :, None, 1], gt[:, None, :, 1])
                ix1 = jnp.minimum(bx[:, :, None, 2], gt[:, None, :, 2])
                iy1 = jnp.minimum(bx[:, :, None, 3], gt[:, None, :, 3])
                inter = (jnp.maximum(ix1 - ix0, 0)
                         * jnp.maximum(iy1 - iy0, 0))
                area_p = ((bx[..., 2] - bx[..., 0])
                          * (bx[..., 3] - bx[..., 1]))[:, :, None]
                area_g = ((gt[..., 2] - gt[..., 0])
                          * (gt[..., 3] - gt[..., 1]))[:, None, :]
                iou = inter / jnp.maximum(area_p + area_g - inter, 1e-9)
                iou = jnp.where(gt_valid[:, None, :], iou, 0.0)
                best = jnp.max(iou, -1)             # B,N
                # positives always train objectness; high-IoU negatives
                # are ignored
                obj_w = jnp.where((best > thresh) & (ot < 0.5), 0.0, 1.0)
            npos = jnp.maximum(jnp.sum(ot), 1.0)
            obj_loss = jnp.sum(bce(p[..., 4], ot) * obj_w) / npos
            wb = (w * ot)[..., None]
            xy_loss = jnp.sum(bce(p[..., 0:2], bt[..., 0:2]) * wb) / npos
            wh_loss = jnp.sum(0.5 * (p[..., 2:4] - bt[..., 2:4]) ** 2
                              * wb) / npos
            cls_loss = jnp.sum(bce(p[..., 5:], ct) * ot[..., None]) / npos
            return obj_loss + xy_loss + wh_loss + cls_loss
        return _invoke(fn, inputs, name="yolo3_loss")


def yolo3_darknet53(num_classes=80, **kw):
    """Darknet53-backed YOLOv3 (the judged BASELINE COCO workload)."""
    anchors = [[(10, 13), (16, 30), (33, 23)],
               [(30, 61), (62, 45), (59, 119)],
               [(116, 90), (156, 198), (373, 326)]]
    return YOLOv3(num_classes, (64, 128, 256, 512, 1024), (1, 2, 8, 8, 4),
                  anchors, **kw)


def yolo3_tiny(num_classes=3, **kw):
    anchors = [[(4, 6), (8, 12)],
               [(12, 20), (20, 16)],
               [(30, 24), (40, 48)]]
    kw.setdefault("strides", (2, 4, 8))   # 3-stage backbone
    return YOLOv3(num_classes, (8, 16, 32), (1, 1, 1), anchors, **kw)
