"""BERT (reference workload: GluonNLP scripts/bert — the judged BASELINE
metric is BERT-large pretraining samples/sec/chip; the reference repo itself
provides the ops BERT is built from: gluon.nn.Dense, LayerNorm, Embedding,
batch_dot — python/mxnet/gluon/nn/basic_layers.py).

TPU-first design choices:
  * attention is ONE fused op (scaled-dot-product with stable softmax)
    lowered by XLA onto the MXU — not a chain of batch_dot/softmax eager
    ops; under hybridize()/SPMDTrainer the whole encoder is a single
    program;
  * bf16-friendly: all matmuls run in the param dtype; use net.cast
    ('bfloat16') + fp32 LayerNorm accumulations via XLA defaults;
  * sequence parallelism: pass ``seq_axis`` to route attention through
    parallel.ring_attention over a mesh 'seq' axis (capability beyond the
    reference, SURVEY §5.7);
  * tensor parallelism: FFN/attention projection weights match the
    classic Megatron sharding pattern (rules in ``tp_rules``).
"""
from __future__ import annotations

import contextlib as _contextlib
import math

import numpy as _np

from ..base import MXNetError
from ..gluon import nn
from ..gluon import loss as loss_mod
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray, _invoke

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "BERTEncoder", "BERTModel", "BERTForPretrain", "MLMPretrainLoss",
           "BERTMLMOnly", "bert_tiny", "bert_base", "bert_large",
           "tp_rules", "derive_tp_rules", "dense_attention",
           "cached_step_attn",
           "maybe_remat_cell"]


def _sdpa(q, k, v, num_heads, mask=None, seq_axis=None, mesh=None,
          causal=False, fuse_ok=True):
    """Fused scaled-dot-product attention op.

    q: (B, Tq, C), k/v: (B, Tk, C) NDArray (Tq == Tk for self-attention).
    Splits heads, runs stable softmax attention as one XLA program;
    ``mask`` is an optional (B, Tk) 0/1 key-validity mask; ``causal``
    adds the triangular decoder mask; with ``seq_axis`` uses ring
    attention over the mesh (sequence parallelism).  Shared by BERT and
    the NMT Transformer (models/transformer.py).
    """
    inputs = [q, k, v] + ([mask] if mask is not None else [])

    def fn(qv, kv, vv, *rest):
        import jax.numpy as jnp
        B, Tq, C = qv.shape
        Tk = kv.shape[1]
        hd = C // num_heads

        def split(x):
            return x.reshape(B, -1, num_heads, hd).transpose(0, 2, 1, 3)
        qh, kh, vh = split(qv), split(kv), split(vv)
        scale = 1.0 / math.sqrt(hd)
        if seq_axis is not None:
            from ..base import getenv
            sp_impl = (getenv("MXNET_SP_IMPL") or "ring").lower()
            if sp_impl == "ulysses":
                # all-to-all schedule (docs/parallelism.md: constant
                # collective count, needs heads % axis_size == 0)
                from ..parallel.ulysses import ulysses_attention
                from ..base import getenv_bool as _gb
                out = ulysses_attention(
                    qh, kh, vh, mesh=mesh, axis_name=seq_axis,
                    scale=scale, causal=causal,
                    mask=rest[0] if rest else None,
                    use_flash=fuse_ok and _gb("MXNET_USE_FUSION"))
            elif sp_impl == "ring":
                from ..parallel.ring import _ring_body
                from functools import partial
                from jax.sharding import PartitionSpec as P
                from ..parallel._shmap import shard_map
                spec = P(None, None, seq_axis, None)
                from ..base import getenv_bool as _gb
                body = partial(_ring_body, axis_name=seq_axis,
                               scale=scale, causal=causal,
                               # blockwise (flash) local compute rides
                               # the same fusion gate as dense SDPA
                               use_flash=fuse_ok
                               and _gb("MXNET_USE_FUSION"))
                if rest:
                    # valid_length mask is sequence-sharded like K/V and
                    # rotates around the ring with them
                    out = shard_map(
                        body, mesh=mesh,
                        in_specs=(spec, spec, spec, P(None, seq_axis)),
                        out_specs=spec, check_vma=False)(qh, kh, vh,
                                                         rest[0])
                else:
                    out = shard_map(
                        body, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(qh, kh, vh)
            else:
                raise MXNetError(
                    f"MXNET_SP_IMPL={sp_impl!r} unknown; use 'ring' or "
                    "'ulysses'")
        else:
            from ..base import getenv_bool
            if (fuse_ok and qh.shape == kh.shape
                    and getenv_bool("MXNET_USE_FUSION")):
                # Pallas flash-attention kernel (reference env-var parity:
                # MXNET_USE_FUSION gates the fused-kernel tier,
                # src/operator/fusion/fused_op.cc); opt-in until the
                # kernel is profiled on the real chip.  The (B, Tk)
                # key-validity mask rides through the kernel as an
                # additive bias, so padded batches stay on the fused path.
                from ..kernels import flash_attention
                out = flash_attention(qh, kh, vh, scale=scale,
                                      causal=causal,
                                      mask=rest[0] if rest else None)
            else:
                s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * scale
                if causal:
                    tri = jnp.tril(jnp.ones((Tq, Tk), jnp.bool_))
                    s = jnp.where(tri[None, None], s, -1e30)
                if rest:
                    s = jnp.where(rest[0][:, None, None, :] > 0, s, -1e30)
                m = jnp.max(s, axis=-1, keepdims=True)
                p = jnp.exp(s - m)
                l = jnp.sum(p, axis=-1, keepdims=True)
                out = jnp.einsum("bhqk,bhkd->bhqd",
                                 (p / l).astype(vh.dtype), vh)
        return out.transpose(0, 2, 1, 3).reshape(B, -1, C)
    return _invoke(fn, inputs, name="sdpa")


class MultiHeadAttention(HybridBlock):
    """Projected multi-head attention over _sdpa.  ``mem`` (optional third
    positional input) switches to cross-attention: keys/values project
    from ``mem`` while queries project from ``x``."""

    def __init__(self, units, num_heads, dropout=0.0, seq_axis=None,
                 mesh=None, causal=False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError("num_heads must divide units")
        self._units = units
        self._num_heads = num_heads
        self._seq_axis = seq_axis
        self._mesh = mesh
        self._causal = causal
        with self.name_scope():
            self.query = nn.Dense(units, flatten=False, in_units=units)
            self.key = nn.Dense(units, flatten=False, in_units=units)
            self.value = nn.Dense(units, flatten=False, in_units=units)
            self.proj = nn.Dense(units, flatten=False, in_units=units)
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None, mem=None):
        kv_src = x if mem is None else mem
        q, k, v = self.query(x), self.key(kv_src), self.value(kv_src)
        out = _sdpa(q, k, v, self._num_heads, mask=mask,
                    seq_axis=self._seq_axis, mesh=self._mesh,
                    causal=self._causal)
        return self.dropout(self.proj(out))

    def project_kv(self, mem):
        """Precompute this head's K/V projections of an encoder memory —
        the cross-attention half of a KV cache (incremental decoding)."""
        return self.key(mem), self.value(mem)


def cached_step_attn(qv, kn, vn, ck, cv, tv, num_heads):
    """jax-level single-position attention over a KV cache, shared by the
    incremental decoders (transformer._DecoderCell.step, gpt.GPTCell.step):
    write this position's K/V at index ``tv``, attend causally over
    positions <= tv.  qv/kn/vn (B, 1, C); ck/cv (B, Tmax, C); returns
    (out (B, 1, C), ck', cv')."""
    import jax.numpy as jnp
    B, _, C = qv.shape
    hd = C // num_heads
    Tm = ck.shape[1]
    ck = ck.at[:, tv].set(kn[:, 0])
    cv = cv.at[:, tv].set(vn[:, 0])
    qh = qv.reshape(B, 1, num_heads, hd).transpose(0, 2, 1, 3)
    kh = ck.reshape(B, Tm, num_heads, hd).transpose(0, 2, 1, 3)
    vh = cv.reshape(B, Tm, num_heads, hd).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / math.sqrt(hd)
    s = jnp.where(jnp.arange(Tm)[None, None, None, :] <= tv, s, -1e30)
    p = jnp.exp(s - jnp.max(s, -1, keepdims=True))
    p = p / jnp.sum(p, -1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vh.dtype), vh)
    return out.transpose(0, 2, 1, 3).reshape(B, 1, C), ck, cv


@_contextlib.contextmanager
def dense_attention(net):
    """Temporarily run every attention cell of ``net`` on the dense
    (non-sequence-parallel) path.  Needed when a seq-parallel model must
    do a one-off eager forward on a single device — e.g. settling
    deferred parameter shapes before an SPMDTrainer builds — where the
    shard_map path cannot execute.  Shapes do not depend on the
    schedule, so the settled state is identical."""
    cells = []
    net.apply(lambda b: cells.append(b)
              if isinstance(b, MultiHeadAttention) else None)
    saved = [(c, c._seq_axis) for c in cells]
    try:
        for c in cells:
            c._seq_axis = None
        yield net
    finally:
        for c, s in saved:
            c._seq_axis = s


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  in_units=units)
            self.ffn_2 = nn.Dense(units, flatten=False,
                                  in_units=hidden_size)
            self.dropout = nn.Dropout(dropout)
        self._activation = activation

    def hybrid_forward(self, F, x):
        h = self.ffn_1(x)
        h = F.gelu(h) if self._activation == "gelu" \
            else F.Activation(h, act_type=self._activation)
        return self.dropout(self.ffn_2(h))


class TransformerEncoderCell(HybridBlock):
    """Post-LN transformer layer (BERT style)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 seq_axis=None, mesh=None, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads, dropout,
                                                seq_axis, mesh)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout)
            self.ln2 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, mask=None):
        x = self.ln1(x + self.attention(x, mask))
        x = self.ln2(x + self.ffn(x))
        return x


def maybe_remat_cell(cell, x, *rest):
    """Run one layer, optionally under ``jax.checkpoint``
    (``MXNET_BACKWARD_DO_MIRROR`` — the reference's mirror/memonger knob,
    docs/faq/env_var.md: trade recompute for activation memory).  Under
    the compiled paths (SPMDTrainer/hybridize via functional_call) the
    layer's internal activations are then rematerialized in the backward
    instead of saved — the standard seq-512/large-batch enabler on HBM.
    The eager-tape path records per-op, where a checkpoint boundary can't
    apply — plain call there."""
    from ..base import getenv_bool
    from .. import autograd as _ag
    if not getenv_bool("MXNET_BACKWARD_DO_MIRROR") or _ag.is_recording():
        return cell(x, *rest)
    import jax

    def f(xv):
        out = cell(NDArray(xv), *rest)
        if isinstance(out, tuple):      # e.g. MoE cells: (y, aux_loss)
            return tuple(o._data for o in out)
        return out._data
    out = jax.checkpoint(f)(x._data)
    if isinstance(out, tuple):
        return tuple(NDArray(o) for o in out)
    return NDArray(out)


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, seq_axis=None, mesh=None, **kwargs):
        super().__init__(**kwargs)
        self._cells = []
        with self.name_scope():
            for i in range(num_layers):
                cell = TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout, seq_axis, mesh)
                self.register_child(cell, f"layer{i}")

    def hybrid_forward(self, F, x, mask=None):
        for cell in self._children.values():
            x = maybe_remat_cell(cell, x, mask)
        return x


class BERTModel(HybridBlock):
    """Embeddings + encoder + pooler (reference workload: GluonNLP
    BERTModel).  forward(input_ids, token_types) -> (sequence_out,
    pooled_out)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 token_type_vocab=2, dropout=0.1, seq_axis=None, mesh=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units)
            self.token_type_embed = nn.Embedding(token_type_vocab, units)
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units),
                init="normal")
            self.embed_ln = nn.LayerNorm(in_channels=units)
            self.embed_dropout = nn.Dropout(dropout)
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, dropout, seq_axis, mesh)
            self.pooler = nn.Dense(units, activation="tanh",
                                   flatten=False, in_units=units)

    def hybrid_forward(self, F, input_ids, token_types, valid_length=None,
                       position_weight=None):
        T = input_ids.shape[1]
        emb = self.word_embed(input_ids) \
            + self.token_type_embed(token_types)
        pos = position_weight.slice_axis(0, 0, T).expand_dims(0)
        emb = self.embed_dropout(self.embed_ln(emb + pos))
        mask = None
        if valid_length is not None:
            ar = F.arange(0, T).reshape(1, -1)
            mask = (ar < valid_length.reshape(-1, 1)).astype("float32")
        seq = self.encoder(emb, mask)
        pooled = self.pooler(seq.slice_axis(1, 0, 1).squeeze(axis=1))
        return seq, pooled


class BERTForPretrain(HybridBlock):
    """MLM + NSP heads (reference workload: GluonNLP BERTForPretrain)."""

    def __init__(self, bert: BERTModel, vocab_size=30522, **kwargs):
        super().__init__(**kwargs)
        self._vocab_size = vocab_size
        with self.name_scope():
            self.bert = bert
            units = bert._units
            self.mlm_dense = nn.Dense(units, flatten=False,
                                      activation=None, in_units=units)
            self.mlm_ln = nn.LayerNorm(in_channels=units)
            self.mlm_decoder = nn.Dense(vocab_size, flatten=False,
                                        in_units=units)
            self.nsp_classifier = nn.Dense(2, in_units=units)

    def hybrid_forward(self, F, input_ids, token_types, valid_length=None):
        seq, pooled = self.bert(input_ids, token_types, valid_length)
        h = F.gelu(self.mlm_dense(seq))
        mlm_scores = self.mlm_decoder(self.mlm_ln(h))
        nsp_scores = self.nsp_classifier(pooled)
        return mlm_scores, nsp_scores


class MLMPretrainLoss(HybridBlock):
    """Masked-LM cross-entropy over flattened (B*T, V) scores — the loss
    head bench.py and the driver's multichip dryrun both train with."""

    def __init__(self, vocab_size, **kwargs):
        super().__init__(**kwargs)
        self._vocab_size = vocab_size
        with self.name_scope():
            self.ce = loss_mod.SoftmaxCrossEntropyLoss()

    def hybrid_forward(self, F, mlm_scores, labels):
        return self.ce(mlm_scores.reshape(-1, self._vocab_size),
                       labels.reshape(-1))


class BERTPretrainLoss(HybridBlock):
    """Full pretraining loss: masked-LM CE + next-sentence CE (the anchor
    workload's objective — reference: GluonNLP scripts/bert pretraining
    loss = MLM + NSP).  Labels pack both targets in one (B, T+1) array:
    ``labels[:, :T]`` are per-token MLM targets, ``labels[:, T]`` the NSP
    class."""

    def __init__(self, vocab_size, **kwargs):
        super().__init__(**kwargs)
        self._vocab_size = vocab_size
        with self.name_scope():
            self.ce = loss_mod.SoftmaxCrossEntropyLoss()

    def hybrid_forward(self, F, mlm_scores, nsp_scores, labels):
        mlm_labels = labels[:, :-1]
        nsp_labels = labels[:, -1]
        mlm = self.ce(mlm_scores.reshape(-1, self._vocab_size),
                      mlm_labels.reshape(-1))
        nsp = self.ce(nsp_scores, nsp_labels)
        return mlm.mean() + nsp.mean()


class BERTMLMOnly(HybridBlock):
    """Wrap BERTForPretrain to expose only the MLM scores (single-output
    step function for SPMDTrainer)."""

    def __init__(self, inner, **kwargs):
        kwargs.setdefault("prefix", "")
        super().__init__(**kwargs)
        with self.name_scope():
            self.inner = inner

    def hybrid_forward(self, F, input_ids, token_types):
        mlm_scores, _nsp_scores = self.inner(input_ids, token_types)
        return mlm_scores


from ..parallel.spmd import exact_rule  # noqa: E402  (shared rule builder)


def derive_tp_rules(block, model_axis="model", extra=None):
    """Megatron TP rules derived from a BUILT model's ACTUAL parameter
    names: every MultiHeadAttention gets QKV column- / proj row-parallel,
    every PositionwiseFFN first-matmul column- / second-matmul
    row-parallel.  Name-exact, so custom ``prefix=`` models shard
    correctly (the regex fallbacks in each family's ``tp_rules`` key on
    the default auto-prefix names and would silently replicate a
    custom-prefixed model — SPMDTrainer warns when that happens).
    ``extra``: optional callable(block) -> list of rules appended per
    visited block (model-family hooks for embeddings/heads)."""
    from jax.sharding import PartitionSpec as P
    rules = []

    def visit(b):
        if isinstance(b, MultiHeadAttention):
            rules.extend(exact_rule(d.weight, P(model_axis, None))
                         for d in (b.query, b.key, b.value))
            rules.append(exact_rule(b.proj.weight, P(None, model_axis)))
        elif isinstance(b, PositionwiseFFN):
            rules.append(exact_rule(b.ffn_1.weight, P(model_axis, None)))
            rules.append(exact_rule(b.ffn_2.weight, P(None, model_axis)))
        elif isinstance(b, BERTForPretrain):
            rules.append(exact_rule(b.mlm_decoder.weight,
                                     P(model_axis, None)))
        elif isinstance(b, BERTModel):
            rules.append(exact_rule(b.word_embed.weight,
                                     P(None, model_axis)))
        if extra is not None:
            rules.extend(extra(b))

    block.apply(visit)
    if not rules:
        raise MXNetError("derive_tp_rules: no shardable layers under "
                         f"{type(block).__name__}")
    return rules


def core_tp_regex_rules(model_axis="model"):
    """The attention/FFN Megatron rules every transformer family shares
    (regexes over the DEFAULT auto-prefix names: dense0..2 =
    query/key/value, dense3 = proj — construction order; ffn dense0/1 =
    first/second matmul).  Each family's ``tp_rules`` appends its own
    embedding/head rules."""
    from jax.sharding import PartitionSpec as P
    return [
        (r"multiheadattention\d+_dense[012]_weight", P(model_axis, None)),
        (r"multiheadattention\d+_dense3_weight", P(None, model_axis)),
        (r"positionwiseffn\d+_dense0_weight", P(model_axis, None)),
        (r"positionwiseffn\d+_dense1_weight", P(None, model_axis)),
    ]


def tp_rules(model_axis="model", block=None):
    """Megatron-style tensor-parallel sharding rules for SPMDTrainer:
    attention QKV + FFN first matmul column-parallel (axis 0 of the
    (out, in) Dense weight), attention proj + FFN second matmul
    row-parallel, MLM decoder column-parallel, word embedding sharded
    over the units axis.  The regexes target DEFAULT auto-prefix names;
    pass ``block=`` (the built net) to derive exact-name rules instead,
    required whenever any layer was built with a custom ``prefix=``
    (shard_params warns when a required rule goes dead)."""
    from jax.sharding import PartitionSpec as P
    if block is not None:
        return derive_tp_rules(block, model_axis)
    return core_tp_regex_rules(model_axis) + [
        # BERTForPretrain heads: dense0 = mlm_dense, dense1 = mlm_decoder
        # ((?#optional): a plain BERTModel has no pretrain head — exempt
        # from shard_params' dead-rule warning, invisible to re.search)
        (r"(?#optional)bertforpretrain\d+_dense1_weight",
         P(model_axis, None)),
        # BERTModel embeddings: embedding0 = word, embedding1 = token type
        (r"bertmodel\d+_embedding0_weight", P(None, model_axis)),
    ]


def bert_tiny(vocab_size=1024, max_length=128, **kw):
    return BERTModel(vocab_size=vocab_size, units=64, hidden_size=128,
                     num_layers=2, num_heads=2, max_length=max_length, **kw)


def bert_base(vocab_size=30522, **kw):
    return BERTModel(vocab_size=vocab_size, units=768, hidden_size=3072,
                     num_layers=12, num_heads=12, **kw)


def bert_large(vocab_size=30522, **kw):
    return BERTModel(vocab_size=vocab_size, units=1024, hidden_size=4096,
                     num_layers=24, num_heads=16, **kw)
