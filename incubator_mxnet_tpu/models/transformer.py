"""Transformer for NMT (reference workload: Transformer-base WMT14 En-De —
GluonNLP ``scripts/machine_translation`` builds it from this repo's ops:
gluon.nn.Dense/LayerNorm/Embedding/Dropout + batch_dot/softmax,
python/mxnet/gluon/nn/basic_layers.py).

TPU-first design choices (mirrors models/bert.py):
  * self/cross attention is ONE fused op (stable-softmax SDPA) so XLA
    keeps the whole layer on the MXU; causal masking is a static
    triangular mask baked into the compiled program — no dynamic shapes;
  * sinusoidal position table is a constant folded at trace time;
  * greedy decode runs as a ``lax.scan`` over decode steps (static trip
    count = max_length) instead of a Python loop, so inference is one
    compiled program; the default path carries per-layer KV caches in
    the scan state (O(T) per step), with the full-prefix re-run kept as
    the tested oracle;
  * Megatron-style ``tp_rules`` identical in spirit to bert.tp_rules.
"""
from __future__ import annotations

import math

import numpy as _np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray, _invoke
from .bert import MultiHeadAttention, PositionwiseFFN

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "LabelSmoothingCELoss", "transformer_base", "transformer_big",
           "tp_rules"]


def _positional_table(max_length, units):
    """Sinusoidal table, float32 numpy constant (folded by XLA)."""
    pos = _np.arange(max_length)[:, None]
    dim = _np.arange(units // 2)[None, :]
    ang = pos / _np.power(10000.0, 2.0 * dim / units)
    table = _np.zeros((max_length, units), _np.float32)
    table[:, 0::2] = _np.sin(ang)
    table[:, 1::2] = _np.cos(ang)
    return table


class _EncoderCell(HybridBlock):
    """Post-LN layer (original Vaswani/GluonNLP transformer); attention
    and FFN are the shared blocks from models/bert.py."""

    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation="relu")
            self.ln2 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, src_mask=None):
        x = self.ln1(x + self.attention(x, src_mask))
        return self.ln2(x + self.ffn(x))


class _DecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.self_attention = MultiHeadAttention(units, num_heads,
                                                     dropout, causal=True)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.cross_attention = MultiHeadAttention(units, num_heads,
                                                      dropout)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                       activation="relu")
            self.ln3 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, mem, src_mask=None):
        x = self.ln1(x + self.self_attention(x, None))
        x = self.ln2(x + self.cross_attention(x, src_mask, mem))
        return self.ln3(x + self.ffn(x))

    def step(self, x, cache_k, cache_v, t, mem_k, mem_v, src_mask=None):
        """One-position incremental decode step with a KV cache.

        x (B*K, 1, C) current-position activations (K = beams, rows
        ordered b*K+k; K=1 for greedy); cache_k/cache_v (B*K, Tmax, C)
        this layer's self-attention cache; t scalar step index;
        mem_k/mem_v (B, Ts, C) UNREPLICATED cross-attention projections
        (MultiHeadAttention.project_kv) — x's batch must be an exact
        K-multiple of theirs, and the K beams of a batch row fold into
        the cross-attention query axis.  Returns (y (B*K, 1, C),
        cache_k', cache_v').  O(Tmax) per step instead of re-running the
        full prefix."""
        import functools
        from .bert import cached_step_attn
        sa = self.self_attention
        q = sa.query(x)
        k_new = sa.key(x)
        v_new = sa.value(x)
        out, ck, cv = _invoke(
            functools.partial(cached_step_attn, num_heads=sa._num_heads),
            [q, k_new, v_new, cache_k, cache_v, t],
            name="decode_self_attn")
        x = self.ln1(x + sa.dropout(sa.proj(out)))

        ca = self.cross_attention
        # cross-attention over the precomputed K/V is exactly bert._sdpa's
        # masked non-causal path — reuse it for bit-identical numerics
        # with the full-prefix oracle.  Beam search runs with a flattened
        # (B*K, 1, C) query against UNREPLICATED (B, Ts, C) memory: each
        # beam is an independent single query, so beams fold into the
        # query-position axis ((B, K, C)) instead of replicating K/V
        # K-fold — same numbers, 1/K the memory
        from .bert import _sdpa
        q2 = ca.query(x)
        if q2.shape[0] % mem_k.shape[0]:
            raise MXNetError(
                f"step: query batch {q2.shape[0]} is not a multiple of "
                f"the memory batch {mem_k.shape[0]}")
        kfold = q2.shape[0] // mem_k.shape[0]
        if kfold > 1:
            q2 = q2.reshape(mem_k.shape[0], kfold, q2.shape[-1])
        # fuse_ok=False: the beam fold can make q/k shapes coincide,
        # which must not flip this cross-attention onto the flash-kernel
        # path the oracle does not take (parity contract)
        out2 = _sdpa(q2, mem_k, mem_v, ca._num_heads, mask=src_mask,
                     fuse_ok=False)
        if kfold > 1:
            out2 = out2.reshape(x.shape[0], 1, out2.shape[-1])
        x = self.ln2(x + ca.dropout(ca.proj(out2)))
        return self.ln3(x + self.ffn(x)), ck, cv


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            for i in range(num_layers):
                self.register_child(
                    _EncoderCell(units, hidden_size, num_heads, dropout),
                    f"layer{i}")

    def hybrid_forward(self, F, x, src_mask=None):
        for cell in self._children.values():
            x = cell(x, src_mask)
        return x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            for i in range(num_layers):
                self.register_child(
                    _DecoderCell(units, hidden_size, num_heads, dropout),
                    f"layer{i}")

    def hybrid_forward(self, F, x, mem, src_mask=None):
        for cell in self._children.values():
            x = cell(x, mem, src_mask)
        return x


class TransformerModel(HybridBlock):
    """Encoder-decoder NMT transformer (reference workload:
    Transformer-base, GluonNLP machine_translation scripts).

    forward(src_ids, tgt_ids[, src_valid]) -> (B, Tt, vocab) logits.
    Shares source/target embedding and ties the output projection to the
    embedding weight (the WMT14 recipe)."""

    def __init__(self, vocab_size=36000, units=512, hidden_size=2048,
                 num_layers=6, num_heads=8, max_length=1024, dropout=0.1,
                 tie_weights=True, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab_size = vocab_size
        self._tie = tie_weights
        self._pos_table = _positional_table(max_length, units)
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units)
            self.embed_dropout = nn.Dropout(dropout)
            self.encoder = TransformerEncoder(num_layers, units,
                                              hidden_size, num_heads,
                                              dropout)
            self.decoder = TransformerDecoder(num_layers, units,
                                              hidden_size, num_heads,
                                              dropout)
            if not tie_weights:
                self.out_proj = nn.Dense(vocab_size, flatten=False,
                                         in_units=units)

    def _embed(self, F, ids):
        T = ids.shape[-1]
        if T > self._pos_table.shape[0]:
            raise MXNetError(
                f"sequence length {T} exceeds max_length "
                f"{self._pos_table.shape[0]}; construct TransformerModel "
                "with a larger max_length")
        emb = self.embed(ids) * math.sqrt(self._units)
        pos = NDArray(self._pos_table[:T]).astype(emb.dtype)
        return self.embed_dropout(emb + pos.expand_dims(0))

    @staticmethod
    def _valid_to_mask(src_ids, src_valid):
        """(B,) valid lengths -> (B, Ts) 0/1 key mask (None passthrough),
        the mask form bert._sdpa consumes."""
        if src_valid is None:
            return None
        Ts = src_ids.shape[-1]

        def fn(vl):
            import jax.numpy as jnp
            return (jnp.arange(Ts)[None, :]
                    < vl.reshape(-1, 1)).astype(jnp.float32)
        return _invoke(fn, [src_valid], name="valid_to_mask",
                       differentiable=False)

    def _project(self, h):
        if self._tie:
            w = self.embed.weight.data()

            def fn(hv, wv):
                import jax.numpy as jnp
                return jnp.einsum("btu,vu->btv", hv, wv)
            return _invoke(fn, [h, w], name="tied_projection")
        return self.out_proj(h)

    def encode(self, src_ids, src_valid=None, _mask=None):
        from .. import ndarray as F
        mask = (self._valid_to_mask(src_ids, src_valid)
                if _mask is None else _mask)
        return self.encoder(self._embed(F, src_ids), mask)

    def hybrid_forward(self, F, src_ids, tgt_ids, src_valid=None):
        mask = self._valid_to_mask(src_ids, src_valid)
        mem = self.encoder(self._embed(F, src_ids), mask)
        dec = self.decoder(self._embed(F, tgt_ids), mem, mask)
        return self._project(dec)

    def greedy_decode(self, src_ids, max_length=32, bos=2, eos=3,
                      src_valid=None, use_cache=True):
        """Greedy translation as one lax.scan program (static trip count;
        reference analog: GluonNLP BeamSearchTranslator, greedy mode).
        Returns (B, max_length) int32 token ids.

        ``use_cache=True`` (default) runs KV-cache incremental decoding —
        O(T) single-position steps; ``use_cache=False`` re-runs the full
        prefix per step (the simpler oracle both paths are tested
        against)."""
        if use_cache:
            return self._greedy_decode_cached(src_ids, max_length, bos,
                                              eos, src_valid)
        mask = self._valid_to_mask(src_ids, src_valid)
        mem = self.encode(src_ids, _mask=mask)
        maskv = None if mask is None else mask._data
        B = src_ids.shape[0]

        def fn(memv):
            import jax
            import jax.numpy as jnp

            def step(toks, t):
                # re-run the decoder over the fixed-width prefix; the
                # causal mask makes positions >= t inert, so growing the
                # prefix is sharding- and shape-static
                logits = self._decode_tokens(jnp.asarray(toks), memv,
                                             maskv)
                nxt = jnp.argmax(logits[:, t, :], axis=-1).astype(jnp.int32)
                # sequences that already emitted eos stay frozen on eos
                nxt = jnp.where(toks[:, t] == eos, eos, nxt)
                toks = toks.at[:, t + 1].set(nxt)
                return toks, nxt

            toks0 = jnp.full((B, max_length), eos, jnp.int32)
            toks0 = toks0.at[:, 0].set(bos)
            toks, _ = jax.lax.scan(step, toks0,
                                   jnp.arange(max_length - 1))
            return toks
        out = fn(mem._data)
        return NDArray(out)

    def _cached_decode_setup(self, src_ids, max_length, src_valid):
        """Shared setup for the KV-cached decode paths: max_length guard,
        source mask, encoder memory, per-layer cross K/V (NOT replicated
        per beam — _DecoderCell.step folds beams into the query axis, so
        K/V and mask stay (B, Ts, ·)), and the position-embedding helper
        (cast to the activation dtype so bf16 models stay bf16, matching
        the full-prefix oracle)."""
        import jax.numpy as jnp
        from .. import autograd as ag

        if max_length > self._pos_table.shape[0]:
            raise MXNetError(
                f"decode length {max_length} exceeds max_length "
                f"{self._pos_table.shape[0]}; construct TransformerModel "
                "with a larger max_length")
        mask = self._valid_to_mask(src_ids, src_valid)
        mem = self.encode(src_ids, _mask=mask)
        cells = list(self.decoder._children.values())
        with ag.pause():
            mem_kv = [cell.cross_attention.project_kv(mem)
                      for cell in cells]
        pos = self._pos_table
        sqrt_d = math.sqrt(self._units)

        def embed_pos(e, tv):
            def fn(ev, t_):
                p_ = jnp.asarray(pos)[t_][None, None, :].astype(ev.dtype)
                return ev * jnp.asarray(sqrt_d, ev.dtype) + p_
            return _invoke(fn, [e, tv], name="decode_embed_pos")
        return mask, mem, cells, mem_kv, embed_pos

    def _greedy_decode_cached(self, src_ids, max_length, bos, eos,
                              src_valid):
        """KV-cache greedy decode: one lax.scan whose carry holds each
        decoder layer's (B, max_length, C) self-attention K/V cache;
        cross-attention K/V are projected once from the encoder memory."""
        import jax
        import jax.numpy as jnp
        from .. import autograd as ag

        mask, mem, cells, mem_kv, embed_pos = self._cached_decode_setup(
            src_ids, max_length, src_valid)
        B = src_ids.shape[0]
        C = self._units

        def step(carry, t):
            toks, cks, cvs = carry
            with ag.pause():
                x = self.embed(NDArray(toks[:, t][:, None]))
                x = embed_pos(x, NDArray(t))
                new_cks, new_cvs = [], []
                for l, cell in enumerate(cells):
                    x, ck, cv = cell.step(
                        x, NDArray(cks[l]), NDArray(cvs[l]), NDArray(t),
                        mem_kv[l][0], mem_kv[l][1], mask)
                    new_cks.append(ck._data)
                    new_cvs.append(cv._data)
                logits = self._project(x)._data[:, 0]
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nxt = jnp.where(toks[:, t] == eos, eos, nxt)
            toks = toks.at[:, t + 1].set(nxt)
            return (toks, tuple(new_cks), tuple(new_cvs)), None

        toks0 = jnp.full((B, max_length), eos, jnp.int32)
        toks0 = toks0.at[:, 0].set(bos)
        # cache in the model's compute dtype (bf16 after net.cast stays
        # bf16 — same numerics as the full-prefix oracle)
        zeros = tuple(jnp.zeros((B, max_length, C), mem._data.dtype)
                      for _ in cells)
        (toks, _, _), _ = jax.lax.scan(
            step, (toks0, zeros, zeros), jnp.arange(max_length - 1))
        return NDArray(toks)

    def _decode_tokens(self, toks, memv, maskv=None):
        """jnp (B, T) tokens + jnp memory (+ optional (B, Ts) source
        mask) -> jnp logits; traceable."""
        from .. import autograd as ag
        with ag.pause():
            dec = self.decoder(self._embed(None, NDArray(toks)),
                               NDArray(memv),
                               None if maskv is None else NDArray(maskv))
            return self._project(dec)._data

    def beam_search(self, src_ids, beam_size=4, max_length=32, bos=2,
                    eos=3, alpha=0.6, src_valid=None, use_cache=True):
        """Beam-search translation as one lax.scan program (reference
        analog: GluonNLP BeamSearchTranslator over this model).

        Returns (tokens (B, K, max_length) int32, scores (B, K) float32)
        sorted best-first, with GNMT length normalization
        ``score / ((5+len)/6)**alpha``.  Finished beams (emitted ``eos``)
        are frozen: they only extend with ``eos`` at no score cost.

        ``use_cache=True`` (default) decodes incrementally with per-layer
        KV caches over the flattened (B*K) beam batch — O(T) per step;
        beam reorders gather the caches.  ``use_cache=False`` re-runs the
        full prefix per step (the tested oracle).  In float32 the two
        paths are token-exact; in bfloat16 the differently-ordered
        reductions can swap near-tied lower-ranked beams (scores agree
        to bf16 precision; the best beam is stable in practice)."""
        if use_cache:
            return self._beam_search_cached(src_ids, beam_size,
                                            max_length, bos, eos, alpha,
                                            src_valid)
        mask = self._valid_to_mask(src_ids, src_valid)
        mem = self.encode(src_ids, _mask=mask)
        B = src_ids.shape[0]
        K = beam_size
        V = self._vocab_size

        def fn(memv):
            import jax
            import jax.numpy as jnp
            # replicate memory (and source mask) per beam: (B*K, ...)
            memk = jnp.repeat(memv, K, axis=0)
            maskk = (None if mask is None
                     else jnp.repeat(mask._data, K, axis=0))
            neg_inf = jnp.float32(-1e30)

            def step(carry, t):
                toks, scores, lengths = carry      # (B,K,T),(B,K),(B,K)
                flat = toks.reshape(B * K, -1)
                logits = self._decode_tokens(flat, memk,
                                             maskk)[:, t, :]
                logp = jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=-1).reshape(B, K, V)
                done = toks[:, :, t] == eos        # beam already finished
                # finished beams: only eos, at zero cost
                only_eos = jnp.full((V,), neg_inf).at[eos].set(0.0)
                logp = jnp.where(done[..., None], only_eos[None, None],
                                 logp)
                total = scores[..., None] + logp          # B,K,V
                flat_total = total.reshape(B, K * V)
                top_scores, top_idx = jax.lax.top_k(flat_total, K)
                beam_idx = top_idx // V                   # B,K
                tok_idx = (top_idx % V).astype(jnp.int32)
                bsel = jnp.arange(B)[:, None]
                toks = toks[bsel, beam_idx]               # reorder beams
                lengths = lengths[bsel, beam_idx]
                was_done = done[bsel, beam_idx]
                toks = toks.at[:, :, t + 1].set(tok_idx)
                lengths = jnp.where(
                    was_done, lengths,
                    lengths + (tok_idx != eos).astype(lengths.dtype))
                return (toks, top_scores, lengths), None

            toks0 = jnp.full((B, K, max_length), eos, jnp.int32)
            toks0 = toks0.at[:, :, 0].set(bos)
            # all beams start identical: only beam 0 live, so the first
            # expansion picks K distinct tokens instead of K copies
            scores0 = jnp.full((B, K), neg_inf).at[:, 0].set(0.0)
            len0 = jnp.zeros((B, K), jnp.float32)
            (toks, scores, lengths), _ = jax.lax.scan(
                step, (toks0, scores0, len0),
                jnp.arange(max_length - 1))
            norm = ((5.0 + lengths) / 6.0) ** alpha
            final = scores / norm
            order = jnp.argsort(-final, axis=-1)
            bsel = jnp.arange(B)[:, None]
            return toks[bsel, order], final[bsel, order]
        toks, scores = fn(mem._data)
        return NDArray(toks), NDArray(scores)

    def _beam_search_cached(self, src_ids, beam_size, max_length, bos,
                            eos, alpha, src_valid):
        """KV-cache beam search: caches live on the flattened (B*K) beam
        batch; each top-k reorder gathers the caches along the beam
        axis so every beam's cache matches its token prefix."""
        import jax
        import jax.numpy as jnp
        from .. import autograd as ag

        K = beam_size
        mask, mem, cells, mem_kv, embed_pos = self._cached_decode_setup(
            src_ids, max_length, src_valid)
        B = src_ids.shape[0]
        V = self._vocab_size
        C = self._units
        neg_inf = jnp.float32(-1e30)

        def step(carry, t):
            toks, scores, lengths, cks, cvs = carry
            with ag.pause():
                x = self.embed(
                    NDArray(toks[:, :, t].reshape(B * K, 1)))
                x = embed_pos(x, NDArray(t))
                new_cks, new_cvs = [], []
                for l, cell in enumerate(cells):
                    x, ck, cv = cell.step(
                        x, NDArray(cks[l]), NDArray(cvs[l]), NDArray(t),
                        mem_kv[l][0], mem_kv[l][1], mask)
                    new_cks.append(ck._data)
                    new_cvs.append(cv._data)
                logits = self._project(x)._data[:, 0]       # (B*K, V)
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1).reshape(B, K, V)
            done = toks[:, :, t] == eos
            only_eos = jnp.full((V,), neg_inf).at[eos].set(0.0)
            logp = jnp.where(done[..., None], only_eos[None, None], logp)
            total = scores[..., None] + logp
            top_scores, top_idx = jax.lax.top_k(total.reshape(B, K * V),
                                                K)
            beam_idx = top_idx // V
            tok_idx = (top_idx % V).astype(jnp.int32)
            bsel = jnp.arange(B)[:, None]
            toks = toks[bsel, beam_idx]
            lengths = lengths[bsel, beam_idx]
            was_done = done[bsel, beam_idx]
            toks = toks.at[:, :, t + 1].set(tok_idx)
            lengths = jnp.where(
                was_done, lengths,
                lengths + (tok_idx != eos).astype(lengths.dtype))
            # caches follow their beams through the reorder
            new_cks = tuple(
                c.reshape(B, K, *c.shape[1:])[bsel, beam_idx]
                .reshape(B * K, *c.shape[1:]) for c in new_cks)
            new_cvs = tuple(
                c.reshape(B, K, *c.shape[1:])[bsel, beam_idx]
                .reshape(B * K, *c.shape[1:]) for c in new_cvs)
            return (toks, top_scores, lengths, new_cks, new_cvs), None

        toks0 = jnp.full((B, K, max_length), eos, jnp.int32)
        toks0 = toks0.at[:, :, 0].set(bos)
        scores0 = jnp.full((B, K), neg_inf).at[:, 0].set(0.0)
        len0 = jnp.zeros((B, K), jnp.float32)
        zeros = tuple(jnp.zeros((B * K, max_length, C), mem._data.dtype)
                      for _ in cells)
        (toks, scores, lengths, _, _), _ = jax.lax.scan(
            step, (toks0, scores0, len0, zeros, zeros),
            jnp.arange(max_length - 1))
        norm = ((5.0 + lengths) / 6.0) ** alpha
        final = scores / norm
        order = jnp.argsort(-final, axis=-1)
        bsel = jnp.arange(B)[:, None]
        return NDArray(toks[bsel, order]), NDArray(final[bsel, order])


class LabelSmoothingCELoss(HybridBlock):
    """Cross entropy with label smoothing eps (WMT14 recipe: eps=0.1),
    ignoring padding positions (label == ``pad``).  Mean over non-pad
    tokens."""

    def __init__(self, vocab_size, eps=0.1, pad=0, **kwargs):
        super().__init__(**kwargs)
        self._V = vocab_size
        self._eps = eps
        self._pad = pad

    def hybrid_forward(self, F, logits, labels):
        V, eps, pad = self._V, self._eps, self._pad

        def fn(lg, lb):
            import jax
            import jax.numpy as jnp
            lg = lg.reshape(-1, V)
            lb = lb.reshape(-1)
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, lb[:, None].astype(jnp.int32),
                                       axis=-1)[:, 0]
            smooth = -jnp.mean(logp, axis=-1)
            loss = (1.0 - eps) * nll + eps * smooth
            keep = (lb != pad).astype(loss.dtype)
            return jnp.sum(loss * keep) / jnp.maximum(jnp.sum(keep), 1.0)
        return _invoke(fn, [logits, labels], name="label_smoothing_ce")


def tp_rules(model_axis="model", block=None):
    """Megatron-style TP sharding rules for SPMDTrainer (see
    bert.tp_rules; regexes target default auto-prefix names — pass
    ``block=`` for exact-name rules with custom ``prefix=`` models)."""
    from jax.sharding import PartitionSpec as P
    if block is not None:
        from .bert import derive_tp_rules, exact_rule

        def tf_extra(b):
            rules = []
            if isinstance(b, TransformerModel):
                rules.append(exact_rule(b.embed.weight,
                                         P(None, model_axis)))
                if not b._tie:
                    rules.append(exact_rule(b.out_proj.weight,
                                             P(model_axis, None)))
            return rules
        return derive_tp_rules(block, model_axis, extra=tf_extra)
    from .bert import core_tp_regex_rules
    return core_tp_regex_rules(model_axis) + [
        (r"embedding\d+_weight", P(None, model_axis)),
        # untied output projection ((?#optional): absent when tied)
        (r"(?#optional)transformermodel\d+_dense0_weight",
         P(model_axis, None)),
    ]


def transformer_base(vocab_size=36000, **kw):
    """Vaswani et al. base config — the WMT14 En-De judged workload."""
    return TransformerModel(vocab_size=vocab_size, units=512,
                            hidden_size=2048, num_layers=6, num_heads=8,
                            **kw)


def transformer_big(vocab_size=36000, **kw):
    return TransformerModel(vocab_size=vocab_size, units=1024,
                            hidden_size=4096, num_layers=6, num_heads=16,
                            **kw)
