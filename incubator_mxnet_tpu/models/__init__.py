"""Model collection (reference analog: gluon model_zoo + the GluonNLP
model scripts that are the judged workloads — BASELINE.md)."""
from . import bert  # noqa: F401
from .bert import (BERTModel, BERTEncoder, BERTForPretrain,
                   bert_base, bert_large, bert_tiny)
from . import transformer  # noqa: F401
from .transformer import (TransformerModel, transformer_base,
                          transformer_big)
from . import ssd  # noqa: F401
from .ssd import SSD, ssd_512, ssd_300, ssd_tiny
from . import yolo  # noqa: F401
from .yolo import YOLOv3, yolo3_darknet53, yolo3_tiny
from . import gpt  # noqa: F401
from .gpt import GPTModel, gpt_tiny, gpt2_124m
from . import moe  # noqa: F401
from .moe import MoEFFN, MoELoss

__all__ = ["bert", "BERTModel", "BERTEncoder", "BERTForPretrain",
           "bert_base", "bert_large", "bert_tiny",
           "transformer", "TransformerModel", "transformer_base",
           "transformer_big",
           "ssd", "SSD", "ssd_512", "ssd_300", "ssd_tiny",
           "yolo", "YOLOv3", "yolo3_darknet53", "yolo3_tiny",
           "gpt", "GPTModel", "gpt_tiny", "gpt2_124m",
           "moe", "MoEFFN", "MoELoss"]
