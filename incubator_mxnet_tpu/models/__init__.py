"""Model collection (reference analog: gluon model_zoo + the GluonNLP
model scripts that are the judged workloads — BASELINE.md)."""
from . import bert  # noqa: F401
from .bert import (BERTModel, BERTEncoder, BERTForPretrain,
                   bert_base, bert_large, bert_tiny)

__all__ = ["bert", "BERTModel", "BERTEncoder", "BERTForPretrain",
           "bert_base", "bert_large", "bert_tiny"]
