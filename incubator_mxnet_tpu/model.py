"""Checkpoint save/load for the symbolic world (reference:
python/mxnet/model.py save_checkpoint/load_checkpoint — the
``prefix-symbol.json`` + ``prefix-%04d.params`` twin-artifact format with
``arg:``/``aux:`` key prefixes, shared with Module.save_checkpoint and
Gluon's HybridBlock.export)."""
from __future__ import annotations

from typing import Dict, Tuple

from .base import MXNetError
from . import ndarray as nd
from .symbol import Symbol, load as _sym_load

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "BatchEndParam"]

from .callback import BatchEndParam  # noqa: E402  (re-export, ref parity)


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict, remove_amp_cast: bool = True) -> None:
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix: str, epoch: int) -> Tuple[Dict, Dict]:
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            raise MXNetError(f"invalid param key {k!r} (want arg:/aux:)")
    return arg_params, aux_params


def load_checkpoint(prefix: str, epoch: int):
    symbol = _sym_load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
