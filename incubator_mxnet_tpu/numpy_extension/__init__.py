"""``mx.npx``: operators that extend NumPy semantics with NN primitives
(reference: python/mxnet/numpy_extension/__init__.py and
python/mxnet/_numpy_op_doc.py; npx.set_np semantics from
python/mxnet/util.py).

The reference gates NumPy semantics behind ``npx.set_np()`` because its
legacy ndarray forbids zero-dim/zero-size arrays.  Here the tensor is a
``jax.Array``, which is NumPy-semantic natively, so ``set_np`` only flips
the compatibility flags that other modules may consult.
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from ..ndarray import nn as _nd_nn
from ..ndarray import ops as _nd_ops
from ..ndarray.ndarray import NDArray
from ..numpy.multiarray import _reclass

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "set_np_shape", "use_np", "np_shape", "np_array",
           # nn ops
           "activation", "relu", "sigmoid", "softmax", "log_softmax",
           "batch_norm", "layer_norm", "instance_norm", "group_norm",
           "convolution", "deconvolution", "fully_connected", "pooling",
           "dropout", "embedding", "leaky_relu", "gelu", "rnn",
           "one_hot", "pick", "topk", "batch_dot", "gamma", "gammaln",
           "digamma", "sequence_mask", "sequence_last", "sequence_reverse",
           "reshape_like", "smooth_l1", "gather_nd", "scatter_nd",
           "stop_gradient", "erf", "erfinv", "arange_like",
           "slice_axis", "roi_align", "box_nms", "multibox_detection",
           "nonzero", "sample_categorical",
           "broadcast_like", "batch_flatten", "shape_array",
           "softmax_cross_entropy", "slice_like", "index_array",
           "index_copy", "foreach", "while_loop", "cond",
           "waitall", "seed", "cpu", "gpu", "num_gpus", "current_device",
           "load", "save"]

_state = threading.local()


def _flags():
    if not hasattr(_state, "np_shape"):
        _state.np_shape = False
        _state.np_array = False
    return _state


def set_np(shape=True, array=True):
    """Enable NumPy semantics (reference: npx.set_np).  Always-on here —
    the flags are tracked for API parity."""
    if not shape and array:
        raise MXNetError("NumPy array semantics require NumPy shape "
                         "semantics (reference behavior)")
    f = _flags()
    f.np_shape, f.np_array = shape, array


def reset_np():
    set_np(False, False)


def is_np_shape():
    return _flags().np_shape


def is_np_array():
    return _flags().np_array


def set_np_shape(active):
    f = _flags()
    prev, f.np_shape = f.np_shape, active
    return prev


class np_shape:
    """Context manager (reference: mxnet.util.np_shape)."""

    def __init__(self, active=True):
        self._active = active

    def __enter__(self):
        self._prev = set_np_shape(self._active)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev)


class np_array(np_shape):
    """Context manager (reference: mxnet.util.np_array)."""

    def __enter__(self):
        f = _flags()
        self._prev = f.np_array
        f.np_array = self._active
        return self

    def __exit__(self, *exc):
        _flags().np_array = self._prev


def use_np(func):
    """Decorator form (reference: mxnet.util.use_np).  NumPy semantics are
    native here, so this is an identity decorator kept for parity."""
    return func


def _np_face(fn, name=None):
    def wrapped(*args, **kwargs):
        return _reclass(fn(*args, **kwargs))
    wrapped.__name__ = name or fn.__name__
    wrapped.__doc__ = fn.__doc__
    return wrapped


# NN primitives with npx spellings (lowercase, numpy-flavored), each
# delegating to the eager op corpus (which records autograd + jits)
activation = _np_face(_nd_ops.Activation, "activation")
relu = _np_face(lambda data: _nd_ops.Activation(data, act_type="relu"),
                "relu")
sigmoid = _np_face(lambda data: _nd_ops.Activation(data, act_type="sigmoid"),
                   "sigmoid")
softmax = _np_face(_nd_ops.softmax, "softmax")
log_softmax = _np_face(_nd_ops.log_softmax, "log_softmax")
leaky_relu = _np_face(_nd_ops.leaky_relu, "leaky_relu")
gelu = _np_face(_nd_ops.gelu, "gelu")
batch_norm = _np_face(_nd_nn.BatchNorm, "batch_norm")
layer_norm = _np_face(_nd_nn.LayerNorm, "layer_norm")
instance_norm = _np_face(_nd_nn.InstanceNorm, "instance_norm")
group_norm = _np_face(_nd_nn.GroupNorm, "group_norm")
convolution = _np_face(_nd_nn.Convolution, "convolution")
deconvolution = _np_face(_nd_nn.Deconvolution, "deconvolution")
fully_connected = _np_face(_nd_nn.FullyConnected, "fully_connected")
pooling = _np_face(_nd_nn.Pooling, "pooling")
dropout = _np_face(_nd_ops.dropout, "dropout")
embedding = _np_face(_nd_ops.Embedding, "embedding")
rnn = _np_face(_nd_nn.RNN, "rnn")
one_hot = _np_face(_nd_ops.one_hot, "one_hot")
pick = _np_face(_nd_ops.pick, "pick")
topk = _np_face(_nd_ops.topk, "topk")
batch_dot = _np_face(_nd_ops.batch_dot, "batch_dot")
sequence_mask = _np_face(_nd_ops.SequenceMask, "sequence_mask")
reshape_like = _np_face(_nd_ops.reshape_like, "reshape_like")
smooth_l1 = _np_face(_nd_ops.smooth_l1, "smooth_l1")
gather_nd = _np_face(_nd_ops.gather_nd, "gather_nd")
scatter_nd = _np_face(_nd_ops.scatter_nd, "scatter_nd")
stop_gradient = _np_face(_nd_ops.stop_gradient, "stop_gradient")
gammaln = _np_face(_nd_ops.gammaln, "gammaln")
slice_axis = _np_face(_nd_ops.slice_axis, "slice_axis")
digamma = _np_face(_nd_ops.digamma, "digamma")
sequence_last = _np_face(_nd_ops.SequenceLast, "sequence_last")
sequence_reverse = _np_face(_nd_ops.SequenceReverse, "sequence_reverse")
broadcast_like = _np_face(_nd_ops.broadcast_like, "broadcast_like")
batch_flatten = _np_face(_nd_ops.Flatten, "batch_flatten")
shape_array = _np_face(_nd_ops.shape_array, "shape_array")
softmax_cross_entropy = _np_face(_nd_ops.softmax_cross_entropy,
                                 "softmax_cross_entropy")
slice_like = _np_face(_nd_ops.slice_like, "slice_like")


def _contrib_face(name, alias=None):
    from ..ndarray import contrib as _nd_contrib
    return _np_face(getattr(_nd_contrib, name), alias or name)


arange_like = _contrib_face("arange_like")
roi_align = _contrib_face("ROIAlign", "roi_align")
box_nms = _contrib_face("box_nms")
multibox_detection = _contrib_face("MultiBoxDetection",
                                   "multibox_detection")


def nonzero(a):
    """Indices of non-zero elements as an (ndim, N) array (reference:
    npx nonzero; eager-only — data-dependent shape)."""
    from ..ndarray.ops_ext import argwhere as _aw
    return _reclass(_aw(a).T)


def sample_categorical(prob, shape=None, dtype="int32"):
    """Categorical draws from probabilities (reference: npx sampling
    face of sample_multinomial)."""
    from ..ndarray.ops_ext import sample_multinomial as _sm
    return _reclass(_sm(prob, shape=shape, dtype=dtype))
index_array = _contrib_face("index_array")
index_copy = _contrib_face("index_copy")
foreach = _contrib_face("foreach")
while_loop = _contrib_face("while_loop")
cond = _contrib_face("cond")


def gamma(data):
    """Elementwise gamma function Γ(x) via exp(gammaln) with the
    reflection sign for x<0 (sign Γ(x) = sign sin(πx) there)."""
    from ..ndarray.ndarray import _invoke

    def run(x):
        import jax.numpy as jnp
        import jax.scipy.special as jsp
        mag = jnp.exp(jsp.gammaln(x))
        sign = jnp.where(x > 0, 1.0, jnp.sign(jnp.sin(jnp.pi * x)))
        return mag * sign
    return _reclass(_invoke(run, [data], name="gamma"))


def erf(data):
    from ..ndarray.ndarray import _invoke

    def run(x):
        import jax.scipy.special as jsp
        return jsp.erf(x)
    return _reclass(_invoke(run, [data], name="erf"))


def erfinv(data):
    from ..ndarray.ndarray import _invoke

    def run(x):
        import jax.scipy.special as jsp
        return jsp.erfinv(x)
    return _reclass(_invoke(run, [data], name="erfinv"))


# conveniences re-exported under npx like the reference
def waitall():
    from ..ndarray.ndarray import waitall as w
    w()


def seed(s):
    from .. import random as _r
    _r.seed(s)


def cpu(device_id=0):
    from ..context import cpu as c
    return c(device_id)


def gpu(device_id=0):
    from ..context import gpu as g
    return g(device_id)


def num_gpus():
    from ..context import num_gpus as n
    return n()


def current_device():
    from ..context import current_context as c
    return c()


def save(file, arr):
    """reference: npx.save — same container format as mx.nd.save."""
    from ..ndarray.utils import save as s
    s(file, arr)


def load(file):
    """reference: npx.load — arrays come back with np-ndarray class."""
    from ..ndarray.utils import load as l
    out = l(file)
    if isinstance(out, dict):
        return {k: _reclass(v) for k, v in out.items()}
    return _reclass(out)
