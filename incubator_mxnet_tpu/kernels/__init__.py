"""Pallas TPU kernels for the hot ops (SURVEY §7 stance: XLA fuses most
of the graph; hand-written kernels only where the compiler's schedule
leaves HBM bandwidth on the table — attention being the canonical case)."""
from .flash_attention import (flash_attention,  # noqa: F401
                              flash_attention_lse)

__all__ = ["flash_attention", "flash_attention_lse"]
