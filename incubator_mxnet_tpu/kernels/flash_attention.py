"""Flash attention as a Pallas TPU kernel.

Reference analog: the reference's fused-kernel tier (NVRTC pointwise
fusion `src/operator/fusion/fused_op.*` + cuDNN attention in its era) —
re-designed for TPU: an online-softmax (FlashAttention-2 style) kernel
that streams K/V blocks through VMEM, never materializing the (T, T)
score matrix in HBM.  The MXU does the two matmuls per block; running
max/sum rescaling happens on the VPU.

Scope/contract:
* forward AND backward are Pallas online-softmax kernels
  (FlashAttention-2): the forward also emits the per-row logsumexp, the
  backward recomputes P = exp(S - LSE) blockwise — dQ in a
  query-parallel kernel, dK/dV (+ the key-bias cotangent) in a
  key-parallel kernel — so the (T, T) score matrix exists in neither
  direction.  ``MXNET_FLASH_BWD=xla`` switches the backward to the
  XLA-recompute path, kept as the numerics oracle
  (tests/test_flash_attention.py grad-checks pallas vs xla);
* dense (non-causal or causal) attention, with an optional (B, Tk) 0/1
  key-validity mask (the shape every padded BERT batch carries as
  ``valid_length``) applied as an additive -1e30 bias streamed through
  VMEM per K block; rows must keep >= 1 valid key (valid_length >= 1),
  same contract as the XLA path.  Arbitrary (Tq, Tk) score masks are NOT
  supported — those callers use the XLA path;
* K/V for one (batch, head) stay VMEM-resident and are block-streamed
  from there, so the (T, T) score matrix never exists but T is bounded
  by the VMEM budget (~8MB for K+V).  Longer sequences fall back to XLA
  here; the genuinely long-context path is ring attention over the mesh
  (parallel/ring.py), which shards T before kernels even run;
* the Pallas path engages only for TPU-tile-aligned shapes (T a multiple
  of 128); everything else falls back to XLA;
* on CPU backends the kernel runs in interpret mode, which keeps the
  numerics testable everywhere (tests/test_flash_attention.py).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "flash_attention_lse", "decode_attention",
           "paged_decode_attention", "verify_decode_attention",
           "paged_verify_decode_attention"]

_BLOCK_Q = 128
_BLOCK_K = 128


def _causal_mask(s, q0, k0):
    """-inf the strictly-upper-triangular scores of one (BQ, BK) block;
    ``q0``/``k0`` are the absolute positions of the block's first
    row/column.  Shared by the forward and both backward kernels."""
    bq, bk = s.shape
    iq = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    ik = k0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(iq >= ik, s, -jnp.inf)


def _n_diag_blocks(qi, block_q, block_k, n_kb):
    """How many leading K blocks a causal query block (index ``qi``) can
    see: blocks past the diagonal contribute nothing."""
    return jnp.minimum(
        (qi * block_q + block_q + block_k - 1) // block_k, n_kb)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, block_k,
                seq_len, has_bias, with_lse):
    from jax.experimental import pallas as pl

    b_ref = rest[0] if has_bias else None
    lse_ref = rest[-1] if with_lse else None
    o_ref = rest[-2] if with_lse else rest[-1]
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    block_q = q.shape[0]
    qi = pl.program_id(1)
    n_kb = seq_len // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_bias:
            # (1, block_k) additive key bias (0 valid / -1e30 masked),
            # broadcast over the query rows
            s = s + b_ref[0, :, pl.ds(j * block_k, block_k)]
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # fully-masked rows (causal upper blocks) keep m=-inf: exp(-inf
        # - -inf) would be nan — pin those rows' correction to 0
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    if causal:
        n_needed = _n_diag_blocks(qi, block_q, block_k, n_kb)
        m, l, acc = jax.lax.fori_loop(0, n_needed, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if with_lse:
        # per-row logsumexp of the (scaled, biased, masked) scores — the
        # one residual the FA2 backward needs to recompute P blockwise
        safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
        lse_ref[0] = (safe_m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


def _xla_attention(q, k, v, scale, causal, bias=None):
    """(BH, T, D) reference path; ``bias`` is an optional (BH, 1, Tk)
    additive score bias (0 valid / -1e30 masked)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias
    if causal:
        T = q.shape[1]
        iq = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        ik = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        s = jnp.where(iq[None] >= ik[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(
        q.dtype)


def _flash_fwd_impl(q, k, v, bias, scale, causal, interpret, n_heads,
                    with_lse=False):
    """``bias``: None, or a (B, 1, Tk) float32 additive key bias shared by
    the batch's ``n_heads`` grid rows (indexed bh -> bh // n_heads, so the
    per-head copies never materialize in HBM).  ``with_lse`` additionally
    returns the per-row logsumexp (BH, T) float32 for the backward."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    block_q = min(_BLOCK_Q, T)
    block_k = min(_BLOCK_K, T)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_len=T,
                               has_bias=bias is not None,
                               with_lse=with_lse)
    grid = (BH, T // block_q)
    spec_q = pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0),
                          memory_space=pltpu.VMEM)
    spec_kv = pl.BlockSpec((1, T, D), lambda bh, qi: (bh, 0, 0),
                           memory_space=pltpu.VMEM)
    in_specs = [spec_q, spec_kv, spec_kv]
    operands = [q, k, v]
    if bias is not None:
        in_specs.append(pl.BlockSpec(
            (1, 1, T), lambda bh, qi: (bh // n_heads, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(bias)
    out_shape = jax.ShapeDtypeStruct((BH, T, D), q.dtype)
    out_specs = spec_q
    if with_lse:
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((BH, T), jnp.float32)]
        out_specs = [spec_q,
                     pl.BlockSpec((1, block_q), lambda bh, qi: (bh, qi),
                                  memory_space=pltpu.VMEM)]
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        interpret=interpret,
    )(*operands)


def _bwd_dq_kernel(q_ref, do_ref, lse_ref, dd_ref, k_ref, v_ref, *rest,
                   scale, causal, block_k, seq_len, has_bias):
    """Query-parallel dQ: stream K/V blocks, recompute P from the saved
    logsumexp, accumulate dQ = sum_j (P * (dP - D)) @ K * scale."""
    from jax.experimental import pallas as pl

    b_ref = rest[0] if has_bias else None
    dq_ref = rest[-1]
    q = q_ref[0].astype(jnp.float32)                  # (BQ, D)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0].astype(jnp.float32)              # (BQ,)
    dd = dd_ref[0].astype(jnp.float32)                # (BQ,)
    block_q = q.shape[0]
    qi = pl.program_id(1)
    n_kb = seq_len // block_k

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32
                                ) * scale
        if has_bias:
            s = s + b_ref[0, :, pl.ds(j * block_k, block_k)]
        if causal:
            s = _causal_mask(s, qi * block_q, j * block_k)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dd[:, None])                   # (BQ, BK)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    dq0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    if causal:
        n_needed = _n_diag_blocks(qi, block_q, block_k, n_kb)
        dq = jax.lax.fori_loop(0, n_needed, body, dq0)
    else:
        dq = jax.lax.fori_loop(0, n_kb, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, do_ref, lse_ref, dd_ref, k_ref, v_ref, *rest,
                    scale, causal, block_q, seq_len, has_bias):
    """Key-parallel dK/dV (+ key-bias cotangent rows): stream Q/dO
    blocks over one K/V block, recomputing P from the logsumexp.
    dV = P^T dO;  dK = (P * (dP - D))^T Q * scale;
    dbias_rows = sum_rows(P * (dP - D))."""
    from jax.experimental import pallas as pl

    b_ref = rest[0] if has_bias else None
    dk_ref, dv_ref, dbs_ref = rest[-3], rest[-2], rest[-1]
    k = k_ref[0].astype(jnp.float32)                  # (BK, D)
    v = v_ref[0].astype(jnp.float32)
    block_k = k.shape[0]
    kj = pl.program_id(1)
    n_qb = seq_len // block_q

    def body(i, carry):
        dk, dv, dbs = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q)].astype(jnp.float32)
        dd = dd_ref[0, pl.ds(i * block_q, block_q)].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32
                                ) * scale
        if has_bias:
            s = s + b_ref[0]                          # (1, BK) broadcast
        if causal:
            s = _causal_mask(s, i * block_q, kj * block_k)
        p = jnp.where(jnp.isfinite(s), jnp.exp(s - lse[:, None]), 0.0)
        dv = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (BK, D)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dd[:, None])                   # (BQ, BK)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        dbs = dbs + jnp.sum(ds, axis=0)               # (BK,)
        return dk, dv, dbs

    z = jnp.zeros((block_k, k.shape[1]), jnp.float32)
    carry0 = (z, z, jnp.zeros((block_k,), jnp.float32))
    if causal:
        # q blocks strictly above the diagonal see this k block masked out
        start = (kj * block_k) // block_q
        dk, dv, dbs = jax.lax.fori_loop(start, n_qb, body, carry0)
    else:
        dk, dv, dbs = jax.lax.fori_loop(0, n_qb, body, carry0)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    dbs_ref[0] = dbs


def _flash_bwd_impl(q, k, v, bias, out, lse, g, scale, causal, interpret,
                    n_heads, g_lse=None):
    """FA2 backward as two Pallas kernels; returns (dq, dk, dv, dbias).

    ``g_lse``: optional cotangent of the logsumexp output (the
    with-lse variant used by blockwise ring attention).  It folds into
    the existing kernels with NO kernel change: ds = p*(dp - dd) and
    d(lse_i)/d(s_ij) = p_ij, so the lse term is exactly dd -> dd - g_lse
    (dv = p^T dO is lse-independent and untouched)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    block_q = min(_BLOCK_Q, T)
    block_k = min(_BLOCK_K, T)
    has_bias = bias is not None
    # D_i = rowsum(dO * O): tiny elementwise reduce, XLA fuses it
    dd = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), -1)
    if g_lse is not None:
        dd = dd - g_lse.astype(jnp.float32)

    spec_row_q = pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0),
                              memory_space=pltpu.VMEM)
    spec_full = pl.BlockSpec((1, T, D), lambda bh, i: (bh, 0, 0),
                             memory_space=pltpu.VMEM)
    spec_vec_q = pl.BlockSpec((1, block_q), lambda bh, i: (bh, i),
                              memory_space=pltpu.VMEM)
    spec_vec_full = pl.BlockSpec((1, T), lambda bh, i: (bh, 0),
                                 memory_space=pltpu.VMEM)

    # dQ: grid over query blocks
    in_specs = [spec_row_q, spec_row_q, spec_vec_q, spec_vec_q,
                spec_full, spec_full]
    operands = [q, g, lse, dd, k, v]
    if has_bias:
        in_specs.append(pl.BlockSpec(
            (1, 1, T), lambda bh, i: (bh // n_heads, 0, 0),
            memory_space=pltpu.VMEM))
        operands.append(bias)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, seq_len=T, has_bias=has_bias),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        grid=(BH, T // block_q),
        in_specs=in_specs,
        out_specs=spec_row_q,
        interpret=interpret,
    )(*operands)

    # dK/dV (+ bias-cotangent rows): grid over key blocks
    spec_row_k = pl.BlockSpec((1, block_k, D), lambda bh, j: (bh, j, 0),
                              memory_space=pltpu.VMEM)
    spec_vec_k = pl.BlockSpec((1, block_k), lambda bh, j: (bh, j),
                              memory_space=pltpu.VMEM)
    in_specs = [spec_full, spec_full, spec_vec_full, spec_vec_full,
                spec_row_k, spec_row_k]
    operands = [q, g, lse, dd, k, v]
    if has_bias:
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda bh, j: (bh // n_heads, 0, j),
            memory_space=pltpu.VMEM))
        operands.append(bias)
    dk, dv, dbs = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_len=T, has_bias=has_bias),
        out_shape=[jax.ShapeDtypeStruct((BH, T, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, T, D), v.dtype),
                   jax.ShapeDtypeStruct((BH, T), jnp.float32)],
        grid=(BH, T // block_k),
        in_specs=in_specs,
        out_specs=[spec_row_k, spec_row_k, spec_vec_k],
        interpret=interpret,
    )(*operands)

    dbias = None
    if has_bias:
        # (BH, Tk) rows -> the (B, 1, Tk) bias: sum the head axis out
        dbias = dbs.reshape(-1, n_heads, T).sum(1)[:, None, :]
    return dq, dk, dv, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, bias, scale, causal, interpret, n_heads):
    """One custom_vjp covers both paths: ``bias`` is None (dense) or the
    (B, 1, Tk) additive key bias (None is an empty pytree to JAX, so the
    masked/unmasked cases share this plumbing)."""
    return _flash_fwd_impl(q, k, v, bias, scale, causal, interpret,
                           n_heads)


def _flash_fwd(q, k, v, bias, scale, causal, interpret, n_heads):
    out, lse = _flash_fwd_impl(q, k, v, bias, scale, causal, interpret,
                               n_heads, with_lse=True)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(scale, causal, interpret, n_heads, res, g):
    q, k, v, bias, out, lse = res
    from ..base import getenv
    # read at TRACE time: an already-jitted step keeps whichever backward
    # it was traced with (docs/env_var.md) — set before the first trace
    if (getenv("MXNET_FLASH_BWD") or "pallas").lower() != "xla":
        dq, dk, dv, dbias = _flash_bwd_impl(
            q, k, v, bias, out, lse, g, scale, causal, interpret, n_heads)
        return dq, dk, dv, dbias
    # MXNET_FLASH_BWD=xla — the recompute oracle: same math, standard
    # memory, autodiffed under XLA
    BH = q.shape[0]
    if bias is None:
        _, vjp = jax.vjp(lambda q_, k_, v_: _xla_attention(
            q_, k_, v_, scale, causal), q, k, v)
        return vjp(g) + (None,)
    # broadcast the (B, 1, Tk) bias to the (BH, 1, Tk) the reference path
    # wants, summing the head axis back out of its cotangent
    def ref(q_, k_, v_, b_):
        bb = jnp.broadcast_to(
            b_[:, None], (b_.shape[0], n_heads) + b_.shape[1:]).reshape(
                (BH,) + b_.shape[1:])
        return _xla_attention(q_, k_, v_, scale, causal, bias=bb)
    _, vjp = jax.vjp(ref, q, k, v, bias)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_lse(q, k, v, bias, scale, causal, interpret, n_heads):
    """Variant exposing (out, lse) as OUTPUTS — the building block of
    blockwise ring attention, whose cross-shard merge needs each
    block's logsumexp (and gradients through it)."""
    return _flash_fwd_impl(q, k, v, bias, scale, causal, interpret,
                           n_heads, with_lse=True)


def _flash_lse_fwd(q, k, v, bias, scale, causal, interpret, n_heads):
    out, lse = _flash_fwd_impl(q, k, v, bias, scale, causal, interpret,
                               n_heads, with_lse=True)
    return (out, lse), (q, k, v, bias, out, lse)


def _flash_lse_bwd(scale, causal, interpret, n_heads, res, g):
    q, k, v, bias, out, lse = res
    g_out, g_lse = g
    from ..base import getenv
    if (getenv("MXNET_FLASH_BWD") or "pallas").lower() != "xla":
        return _flash_bwd_impl(q, k, v, bias, out, lse, g_out, scale,
                               causal, interpret, n_heads, g_lse=g_lse)
    # MXNET_FLASH_BWD=xla — the recompute oracle (same switch as the
    # no-lse path; AD produces the g_lse term naturally here)
    BH = q.shape[0]

    def ref(q_, k_, v_, b_):
        bb = None
        if b_ is not None:
            bb = jnp.broadcast_to(
                b_[:, None], (b_.shape[0], n_heads) + b_.shape[1:]
            ).reshape((BH,) + b_.shape[1:])
        return _xla_attention_lse(q_, k_, v_, scale, causal, bias=bb)

    if bias is None:
        _, vjp = jax.vjp(lambda q_, k_, v_: ref(q_, k_, v_, None),
                         q, k, v)
        return vjp((g_out, g_lse)) + (None,)
    _, vjp = jax.vjp(ref, q, k, v, bias)
    return vjp((g_out, g_lse))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _xla_attention_lse(q, k, v, scale, causal, bias=None):
    """(BH, T, D) reference path returning (out, lse) — differentiable
    by plain AD; the odd-shape fallback of flash_attention_lse."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias
    if causal:
        T = q.shape[1]
        iq = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
        ik = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
        s = jnp.where(iq[None] >= ik[None], s, -1e30)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(
        q.dtype)
    return out, lse


def flash_attention_lse(q, k, v, scale=None, causal=False, mask=None):
    """Like :func:`flash_attention` but ALSO returns the per-row
    logsumexp: (out (B, H, T, D), lse (B, H, T)).  Gradients flow
    through both outputs (the lse cotangent folds into the kernels'
    dd term).  Used by blockwise ring attention to merge per-shard
    blocks exactly; same tile-alignment gate and XLA fallback as
    flash_attention (one dispatcher)."""
    return _dispatch(q, k, v, scale, causal, mask, with_lse=True)


def _dispatch(q, k, v, scale, causal, mask, with_lse):
    """ONE dispatcher for both public entry points: mask→bias encoding,
    the tile-alignment + VMEM gate, and platform/interpret detection
    live here once (they had already drifted when duplicated)."""
    B, H, T, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    bias = None
    if mask is not None:
        bias = jnp.where(mask > 0, 0.0, -1e30).astype(
            jnp.float32).reshape(B, 1, T)
    qf, kf, vf = (x.reshape(B * H, T, D) for x in (q, k, v))
    kv_bytes = 2 * T * D * q.dtype.itemsize
    if T % _BLOCK_Q or kv_bytes > 8 * 2 ** 20:
        # not tile-aligned, or K+V would blow the VMEM budget: XLA path
        bb = None if bias is None else jnp.broadcast_to(
            bias[:, None], (B, H, 1, T)).reshape(B * H, 1, T)
        if with_lse:
            out, lse = _xla_attention_lse(qf, kf, vf, scale, causal,
                                          bias=bb)
            return out.reshape(B, H, T, D), lse.reshape(B, H, T)
        return _xla_attention(qf, kf, vf, scale, causal,
                              bias=bb).reshape(B, H, T, D)
    # interpret on CPU: decide from where the DATA lives (a concrete
    # array on the CPU backend of a TPU-default process must interpret);
    # tracers have no devices — fall back to the default backend
    try:
        platform = next(iter(q.devices())).platform
    except Exception:
        platform = jax.default_backend()
    interpret = platform == "cpu"
    if with_lse:
        out, lse = _flash_lse(qf, kf, vf, bias, scale, causal,
                              interpret, H)
        return out.reshape(B, H, T, D), lse.reshape(B, H, T)
    out = _flash(qf, kf, vf, bias, scale, causal, interpret, H)
    return out.reshape(B, H, T, D)


def flash_attention(q, k, v, scale=None, causal=False, mask=None):
    """Online-softmax attention over (B, H, T, D) jax arrays.

    ``mask``: optional (B, Tk) key-validity array (nonzero = attend), the
    ``valid_length``-derived mask every padded batch carries; rows must
    keep >= 1 valid key.  Falls back to the XLA implementation when shapes
    don't fit the kernel contract (T not divisible by the block size)."""
    return _dispatch(q, k, v, scale, causal, mask, with_lse=False)


# ---------------------------------------------------------------------------
# decode-shaped attention: one query position per slot over a
# preallocated KV cache (the GenerationEngine's per-step attention).
# ---------------------------------------------------------------------------

def _xla_decode_attention(q, k, v, positions, scale):
    """(S, H, D) single-position attention over (S, H, T, D) caches.
    Per-slot ``positions`` mask out cache entries beyond each slot's
    write head (entries > position are stale/garbage by contract)."""
    T = k.shape[2]
    s = jnp.einsum("shd,shtd->sht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    live = jnp.arange(T, dtype=jnp.int32)[None, None, :] \
        <= positions[:, None, None]
    s = jnp.where(live, s, -1e30)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("sht,shtd->shd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, block_k, n_kb):
    """Grid (S, H, n_kb): one query row (1, D) against K/V blocks
    (block_k, D) of its slot+head, online softmax across the kb axis.
    Scratch persists along the innermost (kb) grid dim."""
    from jax.experimental import pallas as pl
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos = pos_ref[0]
    D = q_ref.shape[-1]
    q = q_ref[...].reshape(1, D).astype(jnp.float32)
    k = k_ref[...].reshape(block_k, D).astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # (1, block_k)
    idx = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    s = jnp.where(idx <= pos, s, -1e30)
    m_prev, l_prev = m_ref[:], l_ref[:]               # (1, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                            # (1, block_k)
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:] = m_new
    v_blk = v_ref[...].reshape(block_k, D).astype(jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (1, D)

    @pl.when(kb == n_kb - 1)
    def _fin():
        o_ref[...] = (acc_ref[:] / l_ref[:]).reshape(
            o_ref.shape).astype(o_ref.dtype)


def _decode_pallas(q, k, v, positions, scale, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, H, T, D = k.shape
    block_k = min(_BLOCK_K, T)
    n_kb = T // block_k
    kernel = functools.partial(_decode_kernel, scale=scale,
                               block_k=block_k, n_kb=n_kb)
    return pl.pallas_call(
        kernel,
        grid=(S, H, n_kb),
        in_specs=[
            pl.BlockSpec((1,), lambda s, h, kb: (s,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, D), lambda s, h, kb: (s, h, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, kb: (s, h, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, kb: (s, h, kb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, D), lambda s, h, kb: (s, h, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(positions.astype(jnp.int32), q, k, v)


def decode_attention(q, k, v, positions, scale=None):
    """Per-slot single-position attention over a preallocated KV cache.

    ``q`` (S, H, D): this step's query, one position per slot; ``k``/``v``
    (S, H, T, D): the cache, already holding this position's K/V at index
    ``positions[s]``; ``positions`` (S,) int32: each slot's current write
    head.  Attends over cache entries ``<= positions[s]`` (later entries
    are stale garbage by the continuous-batching contract) and returns
    (S, H, D).

    The position mask is the load-bearing contract for **scanned decode
    bursts** (``GenerationEngine.decode_burst``): ``positions`` may be a
    traced value riding a ``lax.scan`` carry — per-slot, data-dependent,
    frozen for finished slots — not just a host constant.  Every
    implementation below masks strictly by comparison against
    ``positions`` (never by python-level slicing on its value), so a
    frozen slot keeps attending over exactly its old prefix and stale
    bytes past the write head stay invisible at any scan step.

    Dispatch mirrors :func:`flash_attention`: a Pallas online-softmax
    kernel when T is tile-aligned and K+V fit the VMEM budget, otherwise
    the lax fallback.  On CPU the lax path is the default — decode runs
    once per generated token, and interpret-mode emulation is a parity
    tool, not a serving path (``MXNET_FA_DECODE_FORCE_PALLAS=1`` forces
    the interpreted kernel for tests)."""
    from ..base import getenv_bool
    S, H, T, D = k.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    try:
        platform = next(iter(q.devices())).platform
    except Exception:
        platform = jax.default_backend()
    force = getenv_bool("MXNET_FA_DECODE_FORCE_PALLAS")
    kv_bytes = 2 * T * D * q.dtype.itemsize
    aligned = T % _BLOCK_K == 0 and kv_bytes <= 8 * 2 ** 20
    if force and aligned:
        return _decode_pallas(q, k, v, positions, scale,
                              interpret=platform == "cpu")
    if platform == "cpu" or not aligned:
        return _xla_decode_attention(q, k, v, positions, scale)
    return _decode_pallas(q, k, v, positions, scale, interpret=False)


# ---------------------------------------------------------------------------
# paged decode attention: the same single-query attention, but the KV
# cache lives in fixed-size blocks (serving/kvcache.py BlockPool) and
# each slot reads through an int32 block table instead of a dense strip.
# ---------------------------------------------------------------------------

def _xla_paged_decode_attention(q, k_pages, v_pages, tables, positions,
                                scale):
    """Gather each slot's blocks into a dense (S, H, T, D) view and reuse
    :func:`_xla_decode_attention` verbatim.  Masked (stale / null-block)
    positions contribute exact-zero softmax weight, so the result is
    bit-identical to dense decode over the same valid entries."""
    S, nb = tables.shape
    _, H, bs, D = k_pages.shape
    k = jnp.moveaxis(k_pages[tables], 2, 1).reshape(S, H, nb * bs, D)
    v = jnp.moveaxis(v_pages[tables], 2, 1).reshape(S, H, nb * bs, D)
    return _xla_decode_attention(q, k, v, positions, scale)


def _paged_decode_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, scale, block_k, n_kb):
    """Grid (S, H, n_kb): like :func:`_decode_kernel`, but the K/V block
    for grid step ``kb`` was fetched through the scalar-prefetched block
    table (see the index maps in :func:`_paged_decode_pallas`), so the
    kernel body only differs in where ``pos`` comes from."""
    from jax.experimental import pallas as pl
    s = pl.program_id(0)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos = pos_ref[s]
    D = q_ref.shape[-1]
    q = q_ref[...].reshape(1, D).astype(jnp.float32)
    k = k_ref[...].reshape(block_k, D).astype(jnp.float32)
    sc = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # (1, block_k)
    idx = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    sc = jnp.where(idx <= pos, sc, -1e30)
    m_prev, l_prev = m_ref[:], l_ref[:]               # (1, 1)
    m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(sc - m_new)                           # (1, block_k)
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:] = m_new
    v_blk = v_ref[...].reshape(block_k, D).astype(jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (1, D)

    @pl.when(kb == n_kb - 1)
    def _fin():
        o_ref[...] = (acc_ref[:] / l_ref[:]).reshape(
            o_ref.shape).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, tables, positions, scale,
                         interpret):
    """Block tables + positions ride as scalar-prefetch operands, so the
    BlockSpec index maps can route grid step (s, h, kb) straight to
    physical block ``tables[s, kb]`` — the gather never materializes a
    dense (S, H, T, D) view."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, n_kb = tables.shape
    _, H, bs, D = k_pages.shape
    kernel = functools.partial(_paged_decode_kernel, scale=scale,
                               block_k=bs, n_kb=n_kb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, H, n_kb),
        in_specs=[
            pl.BlockSpec((1, 1, D), lambda s, h, kb, tbl, pos: (s, h, 0)),
            pl.BlockSpec((1, 1, bs, D),
                         lambda s, h, kb, tbl, pos: (tbl[s, kb], h, 0, 0)),
            pl.BlockSpec((1, 1, bs, D),
                         lambda s, h, kb, tbl, pos: (tbl[s, kb], h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, D), lambda s, h, kb, tbl, pos: (s, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), positions.astype(jnp.int32),
      q, k_pages, v_pages)


def paged_decode_attention(q, k_pages, v_pages, tables, positions,
                           scale=None):
    """Per-slot single-position attention over a PAGED KV cache.

    ``q`` (S, H, D): this step's query; ``k_pages``/``v_pages``
    (num_blocks, H, block_size, D): the block pool, already holding this
    position's K/V; ``tables`` (S, max_blocks) int32: each slot's block
    table, padded with the null block 0; ``positions`` (S,) int32: each
    slot's current write head in logical token coordinates.  Attends over
    logical positions ``<= positions[s]`` and returns (S, H, D).

    Same scanned-burst contract as :func:`decode_attention`:
    ``positions`` (and the write head it masks) may be carry-traced
    inside ``lax.scan``, so all masking is comparison-based against the
    traced value — a slot frozen mid-burst attends over exactly its old
    prefix while its redirected null-block writes stay invisible.

    The lax gather reference is the default (and the CPU path); the
    Pallas kernel — the table-driven gather XLA has no good lowering
    for — sits behind ``MXNET_USE_FUSION`` on accelerators and
    ``MXNET_FA_DECODE_FORCE_PALLAS=1`` (interpret mode) for parity
    tests."""
    from ..base import getenv_bool
    _, H, bs, D = k_pages.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    try:
        platform = next(iter(q.devices())).platform
    except Exception:
        platform = jax.default_backend()
    force = getenv_bool("MXNET_FA_DECODE_FORCE_PALLAS")
    aligned = bs % 8 == 0 and D % 8 == 0
    if force and aligned:
        return _paged_decode_pallas(q, k_pages, v_pages, tables, positions,
                                    scale, interpret=platform == "cpu")
    if platform == "cpu" or not aligned \
            or not getenv_bool("MXNET_USE_FUSION"):
        return _xla_paged_decode_attention(q, k_pages, v_pages, tables,
                                           positions, scale)
    return _paged_decode_pallas(q, k_pages, v_pages, tables, positions,
                                scale, interpret=False)


# ---------------------------------------------------------------------------
# verify-shaped attention: a k+1-wide query block per slot over the same
# caches — the speculative-decode verify program scores every drafted
# position in ONE dispatch.  Query row j of slot s sits at logical
# position positions[s] + j, so the mask is causal-within-the-block on
# top of the per-slot length mask the single-query kernels already use.
# ---------------------------------------------------------------------------

def _xla_verify_decode_attention(q, k, v, positions, scale):
    """(S, H, Q, D) query-block attention over (S, H, T, D) caches.
    ``positions`` (S,) is the base position of query row 0; row j attends
    keys ``<= positions[s] + j`` (causal inside the block, stale entries
    beyond each row's head masked exactly like single-query decode)."""
    S, H, Q, D = q.shape
    T = k.shape[2]
    s = jnp.einsum("shqd,shtd->shqt", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    key_idx = jnp.arange(T, dtype=jnp.int32)
    qpos = positions[:, None].astype(jnp.int32) \
        + jnp.arange(Q, dtype=jnp.int32)[None, :]          # (S, Q)
    live = key_idx[None, None, None, :] <= qpos[:, None, :, None]
    s = jnp.where(live, s, -1e30)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("shqt,shtd->shqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _verify_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, scale, n_q, block_k, n_kb):
    """Grid (S, H, n_kb): a (Q, D) query block against K/V blocks
    (block_k, D), online softmax across the kb axis with per-row
    running max / denominator (scratch (Q, 1) instead of (1, 1))."""
    from jax.experimental import pallas as pl
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos = pos_ref[0]
    D = q_ref.shape[-1]
    q = q_ref[...].reshape(n_q, D).astype(jnp.float32)
    k = k_ref[...].reshape(block_k, D).astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # (Q, block_k)
    idx = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (n_q, block_k), 1)
    head = pos + jax.lax.broadcasted_iota(jnp.int32, (n_q, block_k), 0)
    s = jnp.where(idx <= head, s, -1e30)
    m_prev, l_prev = m_ref[:], l_ref[:]               # (Q, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                            # (Q, block_k)
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:] = m_new
    v_blk = v_ref[...].reshape(block_k, D).astype(jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (Q, D)

    @pl.when(kb == n_kb - 1)
    def _fin():
        o_ref[...] = (acc_ref[:] / l_ref[:]).reshape(
            o_ref.shape).astype(o_ref.dtype)


def _verify_pallas(q, k, v, positions, scale, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, H, T, D = k.shape
    n_q = q.shape[2]
    block_k = min(_BLOCK_K, T)
    n_kb = T // block_k
    kernel = functools.partial(_verify_kernel, scale=scale, n_q=n_q,
                               block_k=block_k, n_kb=n_kb)
    return pl.pallas_call(
        kernel,
        grid=(S, H, n_kb),
        in_specs=[
            pl.BlockSpec((1,), lambda s, h, kb: (s,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, n_q, D), lambda s, h, kb: (s, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, kb: (s, h, kb, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, kb: (s, h, kb, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, n_q, D), lambda s, h, kb: (s, h, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((n_q, D), jnp.float32),
            pltpu.VMEM((n_q, 1), jnp.float32),
            pltpu.VMEM((n_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(positions.astype(jnp.int32), q, k, v)


def verify_decode_attention(q, k, v, positions, scale=None):
    """Per-slot k+1-wide attention over a preallocated KV cache.

    ``q`` (S, H, Q, D): this step's query block — row j is the query of
    the token at logical position ``positions[s] + j``; ``k``/``v``
    (S, H, T, D): the cache, already holding all Q positions' K/V;
    ``positions`` (S,) int32: the base position of row 0.  Row j attends
    entries ``<= positions[s] + j`` and the call returns (S, H, Q, D).
    With Q == 1 this is exactly :func:`decode_attention`.

    Dispatch gates mirror :func:`decode_attention`: Pallas when T is
    tile-aligned and K+V fit the VMEM budget, lax otherwise; on CPU the
    lax path is the default and ``MXNET_FA_DECODE_FORCE_PALLAS=1`` forces
    the interpreted kernel for parity tests."""
    from ..base import getenv_bool
    S, H, T, D = k.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    try:
        platform = next(iter(q.devices())).platform
    except Exception:
        platform = jax.default_backend()
    force = getenv_bool("MXNET_FA_DECODE_FORCE_PALLAS")
    kv_bytes = 2 * T * D * q.dtype.itemsize
    aligned = T % _BLOCK_K == 0 and kv_bytes <= 8 * 2 ** 20
    if force and aligned:
        return _verify_pallas(q, k, v, positions, scale,
                              interpret=platform == "cpu")
    if platform == "cpu" or not aligned:
        return _xla_verify_decode_attention(q, k, v, positions, scale)
    return _verify_pallas(q, k, v, positions, scale, interpret=False)


def _xla_paged_verify_decode_attention(q, k_pages, v_pages, tables,
                                       positions, scale):
    """Gather each slot's blocks into a dense (S, H, T, D) view and reuse
    :func:`_xla_verify_decode_attention` verbatim (same bit-identity
    argument as the single-query paged gather)."""
    S, nb = tables.shape
    _, H, bs, D = k_pages.shape
    k = jnp.moveaxis(k_pages[tables], 2, 1).reshape(S, H, nb * bs, D)
    v = jnp.moveaxis(v_pages[tables], 2, 1).reshape(S, H, nb * bs, D)
    return _xla_verify_decode_attention(q, k, v, positions, scale)


def _paged_verify_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, scale, n_q, block_k,
                         n_kb):
    """Grid (S, H, n_kb): :func:`_verify_kernel` with the K/V block for
    grid step ``kb`` fetched through the scalar-prefetched block table
    (index maps in :func:`_paged_verify_pallas`)."""
    from jax.experimental import pallas as pl
    s = pl.program_id(0)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    pos = pos_ref[s]
    D = q_ref.shape[-1]
    q = q_ref[...].reshape(n_q, D).astype(jnp.float32)
    k = k_ref[...].reshape(block_k, D).astype(jnp.float32)
    sc = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale    # (Q, block_k)
    idx = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (n_q, block_k), 1)
    head = pos + jax.lax.broadcasted_iota(jnp.int32, (n_q, block_k), 0)
    sc = jnp.where(idx <= head, sc, -1e30)
    m_prev, l_prev = m_ref[:], l_ref[:]               # (Q, 1)
    m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(sc - m_new)                           # (Q, block_k)
    l_ref[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:] = m_new
    v_blk = v_ref[...].reshape(block_k, D).astype(jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (Q, D)

    @pl.when(kb == n_kb - 1)
    def _fin():
        o_ref[...] = (acc_ref[:] / l_ref[:]).reshape(
            o_ref.shape).astype(o_ref.dtype)


def _paged_verify_pallas(q, k_pages, v_pages, tables, positions, scale,
                         interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    S, n_kb = tables.shape
    _, H, bs, D = k_pages.shape
    n_q = q.shape[2]
    kernel = functools.partial(_paged_verify_kernel, scale=scale, n_q=n_q,
                               block_k=bs, n_kb=n_kb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, H, n_kb),
        in_specs=[
            pl.BlockSpec((1, 1, n_q, D),
                         lambda s, h, kb, tbl, pos: (s, h, 0, 0)),
            pl.BlockSpec((1, 1, bs, D),
                         lambda s, h, kb, tbl, pos: (tbl[s, kb], h, 0, 0)),
            pl.BlockSpec((1, 1, bs, D),
                         lambda s, h, kb, tbl, pos: (tbl[s, kb], h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, n_q, D),
                               lambda s, h, kb, tbl, pos: (s, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_q, D), jnp.float32),
            pltpu.VMEM((n_q, 1), jnp.float32),
            pltpu.VMEM((n_q, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), positions.astype(jnp.int32),
      q, k_pages, v_pages)


def paged_verify_decode_attention(q, k_pages, v_pages, tables, positions,
                                  scale=None):
    """Per-slot k+1-wide attention over a PAGED KV cache.

    ``q`` (S, H, Q, D): query block, row j at logical position
    ``positions[s] + j``; ``k_pages``/``v_pages`` (num_blocks, H,
    block_size, D); ``tables`` (S, max_blocks) int32 padded with null
    block 0; ``positions`` (S,) int32 base positions.  Returns
    (S, H, Q, D).  Gates mirror :func:`paged_decode_attention` (lax is
    the CPU/default path, Pallas behind ``MXNET_USE_FUSION``,
    ``MXNET_FA_DECODE_FORCE_PALLAS=1`` interprets for parity)."""
    from ..base import getenv_bool
    _, H, bs, D = k_pages.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    try:
        platform = next(iter(q.devices())).platform
    except Exception:
        platform = jax.default_backend()
    force = getenv_bool("MXNET_FA_DECODE_FORCE_PALLAS")
    aligned = bs % 8 == 0 and D % 8 == 0
    if force and aligned:
        return _paged_verify_pallas(q, k_pages, v_pages, tables, positions,
                                    scale, interpret=platform == "cpu")
    if platform == "cpu" or not aligned \
            or not getenv_bool("MXNET_USE_FUSION"):
        return _xla_paged_verify_decode_attention(q, k_pages, v_pages,
                                                  tables, positions, scale)
    return _paged_verify_pallas(q, k_pages, v_pages, tables, positions,
                                scale, interpret=False)
