"""Testing utilities (reference: python/mxnet/test_utils.py — the
load-bearing fixture module of the reference suite, SURVEY §4):
finite-difference gradient checking, dtype-aware comparisons,
cross-context consistency, random array factories.

Works on both Symbols (bound through the executor) and plain callables
over NDArrays — the TPU build's ops are jax-lowered either way.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, current_context
from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = [
    "default_context", "set_default_context", "default_rtol_atol",
    "same", "almost_equal", "assert_almost_equal",
    "rand_ndarray", "rand_shape_2d", "rand_shape_3d", "rand_shape_nd",
    "check_numeric_gradient", "check_symbolic_forward",
    "check_symbolic_backward", "check_consistency", "simple_forward",
    "probe_accelerator",
]


def probe_accelerator(timeout=120):
    """Probe the default (accelerator) jax backend in a SUBPROCESS, so a
    hung PJRT init (single-client tunnel already claimed, relay down)
    cannot hang the caller.  Returns ``(platform, device_kind, error)``:
    platform is None (with ``error`` saying why) when nothing answered,
    'cpu' when only the host backend exists.  Single source of truth for
    the tests_tpu gate and tools/run_tpu_tier.py (reference analog: the
    GPU tier's device availability check)."""
    import subprocess
    import sys
    code = ("import jax; d = jax.devices()[0]; "
            "import jax.numpy as jnp; "
            "(jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready(); "
            "print(d.platform, '|', getattr(d, 'device_kind', ''))")
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout)
        if out.returncode == 0 and out.stdout.strip():
            # parse only the probe's own (last) line: a PJRT plugin may
            # print notices to stdout before it
            last = out.stdout.strip().splitlines()[-1]
            platform, _, kind = last.partition("|")
            return platform.strip(), kind.strip(), None
        tail = (out.stderr or out.stdout or "").strip().splitlines()
        return None, None, (f"probe rc={out.returncode}: "
                            + (tail[-1][:200] if tail else "no output"))
    except subprocess.TimeoutExpired:
        return None, None, (f"probe hung >{timeout}s (PJRT init never "
                            "returned — tunnel down?)")

_default_ctx: Context | None = None

# dtype-aware tolerance table (reference: test_utils default_numeric_eps /
# assert_almost_equal defaults, widened for bf16)
_RTOL = {_np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-4,
         _np.dtype(_np.float64): 1e-6}
_ATOL = {_np.dtype(_np.float16): 1e-2, _np.dtype(_np.float32): 1e-5,
         _np.dtype(_np.float64): 1e-8}


def default_context() -> Context:
    """The context tests run on (reference: test_utils.default_context).
    Override with set_default_context — the GPU/TPU-tier trick of
    re-running one suite on another device."""
    return _default_ctx if _default_ctx is not None else current_context()


def set_default_context(ctx: Context):
    global _default_ctx
    _default_ctx = ctx


def default_rtol_atol(dtype):
    d = _np.dtype(dtype)
    try:
        import ml_dtypes
        if d == _np.dtype(ml_dtypes.bfloat16):
            return 1e-2, 1e-2
    except ImportError:
        pass
    return _RTOL.get(d, 1e-5), _ATOL.get(d, 1e-7)


def _as_np(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return _np.asarray(a)


def same(a, b) -> bool:
    """Exact equality (reference: test_utils.same)."""
    return _np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_np(a), _as_np(b)
    if rtol is None or atol is None:
        r, t = default_rtol_atol(a.dtype)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else t
    return _np.allclose(a.astype(_np.float64), b.astype(_np.float64),
                        rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Dtype-aware allclose with a useful failure message (reference:
    test_utils.assert_almost_equal)."""
    an, bn = _as_np(a), _as_np(b)
    if rtol is None or atol is None:
        r, t = default_rtol_atol(an.dtype)
        rtol = rtol if rtol is not None else r
        atol = atol if atol is not None else t
    if _np.allclose(an.astype(_np.float64), bn.astype(_np.float64),
                    rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    af, bf = an.astype(_np.float64), bn.astype(_np.float64)
    err = _np.abs(af - bf)
    denom = _np.maximum(_np.abs(bf), atol / max(rtol, 1e-300))
    rel = err / _np.maximum(denom, 1e-300)
    idx = _np.unravel_index(_np.argmax(rel), rel.shape) if rel.size \
        else ()
    raise AssertionError(
        f"{names[0]} and {names[1]} differ beyond rtol={rtol} atol={atol}"
        f": max abs err {err.max() if err.size else 0:.3e}, max rel err "
        f"{rel.max() if rel.size else 0:.3e} at {idx}; "
        f"{names[0]}[{idx}]={af[idx] if err.size else None} "
        f"{names[1]}[{idx}]={bf[idx] if err.size else None}")


# ---------------------------------------------------------------------------
# random data factories (reference: test_utils.rand_ndarray/rand_shape_*)
# ---------------------------------------------------------------------------
def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1),
            _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_np.random.randint(1, dim0 + 1),
            _np.random.randint(1, dim1 + 1),
            _np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, scale=1.0):
    """Random array, dense or sparse storage (reference:
    test_utils.rand_ndarray)."""
    dtype = dtype or _np.float32
    data = (_np.random.standard_normal(shape) * scale).astype(dtype)
    if stype == "default":
        return nd.array(data, ctx=ctx, dtype=dtype)
    density = 0.1 if density is None else density
    mask = _np.random.random(shape) < density
    data = _np.where(mask, data, 0).astype(dtype)
    from .ndarray import sparse as _sp
    if stype == "row_sparse":
        return _sp.RowSparseNDArray.from_dense(nd.array(data, dtype=dtype))
    if stype == "csr":
        return _sp.CSRNDArray.from_dense(nd.array(data, dtype=dtype))
    raise MXNetError(f"unknown stype {stype!r}")


# ---------------------------------------------------------------------------
# gradient checking (reference: test_utils.check_numeric_gradient)
# ---------------------------------------------------------------------------
def _normalize_fn(fn_or_sym, location):
    """Return (callable(np arrays)->list[np], input names).  Symbols are
    evaluated through eval_graph; callables take NDArrays positionally."""
    from .symbol.symbol import Symbol, eval_graph
    if isinstance(fn_or_sym, Symbol):
        names = fn_or_sym.list_arguments()
        if isinstance(location, dict):
            order = [n for n in names if n in location]
        else:
            order = names[:len(location)]

        def run(*arrays):
            vals = {n: a for n, a in zip(order, arrays)}
            outs = eval_graph(fn_or_sym, vals, is_train=True)
            return outs if isinstance(outs, list) else [outs]
        return run, order

    def run(*arrays):
        outs = fn_or_sym(*arrays)
        if isinstance(outs, (list, tuple)):
            return list(outs)
        return [outs]
    names = [f"arg{i}" for i in range(len(location))]
    return run, names


def _loc_list(location):
    if isinstance(location, dict):
        return [_np.asarray(_as_np(v), _np.float64)
                for v in location.values()]
    return [_np.asarray(_as_np(v), _np.float64) for v in location]


def check_numeric_gradient(fn_or_sym, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, dtype=_np.float64, seed=0):
    """Central-difference gradient check against autograd (reference:
    test_utils.check_numeric_gradient — the universal grad test).

    The objective is ``sum(out * proj)`` for a fixed random projection, so
    one scalar objective checks the whole Jacobian action.
    """
    from . import autograd as _ag
    run, names = _normalize_fn(fn_or_sym, location)
    locs64 = _loc_list(location)
    comp_dtype = _np.float32 if dtype == _np.float32 else _np.float64
    rng = _np.random.default_rng(seed)

    # fixed projections, one per output
    probe_out = run(*[nd.array(l.astype(comp_dtype)) for l in locs64])
    projs = [rng.standard_normal(_as_np(o).shape) for o in probe_out]

    def objective_np(arrays_np):
        outs = run(*[nd.array(a.astype(comp_dtype)) for a in arrays_np])
        total = 0.0
        for o, p in zip(outs, projs):
            total += float((_as_np(o).astype(_np.float64) * p).sum())
        return total

    grad_idx = (list(range(len(locs64))) if grad_nodes is None
                else list(grad_nodes))

    # analytic grads via the tape
    inputs = [nd.array(l.astype(comp_dtype)) for l in locs64]
    for i in grad_idx:
        inputs[i].attach_grad()
    with _ag.record():
        outs = run(*inputs)
        loss = None
        for o, p in zip(outs, projs):
            term = (o * nd.array(p.astype(comp_dtype))).sum()
            loss = term if loss is None else loss + term
    loss.backward()
    analytic = {i: inputs[i].grad.asnumpy().astype(_np.float64)
                for i in grad_idx}

    # numeric central differences
    for i in grad_idx:
        base = [l.copy() for l in locs64]
        num = _np.zeros_like(base[i])
        flat = base[i].reshape(-1)
        nflat = num.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + numeric_eps
            fp = objective_np(base)
            flat[j] = orig - numeric_eps
            fm = objective_np(base)
            flat[j] = orig
            nflat[j] = (fp - fm) / (2 * numeric_eps)
        a = analytic[i]
        atol_i = atol if atol is not None else 1e-4 + 1e-2 * _np.abs(
            num).max()
        assert_almost_equal(
            num, a, rtol=rtol, atol=atol_i,
            names=(f"numeric_grad({names[i]})",
                   f"autograd_grad({names[i]})"))


def check_symbolic_forward(fn_or_sym, location, expected, rtol=1e-4,
                           atol=1e-6, aux_states=None):
    """Forward vs expected numpy values (reference:
    test_utils.check_symbolic_forward)."""
    run, _ = _normalize_fn(fn_or_sym, location)
    outs = run(*[nd.array(l) for l in _loc_list(location)])
    expected = expected if isinstance(expected, (list, tuple)) \
        else [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(_as_np(o), _np.asarray(e), rtol=rtol,
                            atol=atol, names=("forward", "expected"))


def check_symbolic_backward(fn_or_sym, location, out_grads, expected,
                            rtol=1e-4, atol=1e-6, grad_nodes=None):
    """Backward vs expected grads (reference:
    test_utils.check_symbolic_backward)."""
    from . import autograd as _ag
    run, _ = _normalize_fn(fn_or_sym, location)
    locs = _loc_list(location)
    inputs = [nd.array(l.astype(_np.float32)) for l in locs]
    grad_idx = (list(range(len(inputs))) if grad_nodes is None
                else list(grad_nodes))
    for i in grad_idx:
        inputs[i].attach_grad()
    with _ag.record():
        outs = run(*inputs)
        og = out_grads if isinstance(out_grads, (list, tuple)) \
            else [out_grads]
        loss = None
        for o, g in zip(outs, og):
            term = (o * nd.array(_as_np(g).astype(_np.float32))).sum()
            loss = term if loss is None else loss + term
    loss.backward()
    expected = expected if isinstance(expected, (list, tuple)) \
        else [expected]
    for i, e in zip(grad_idx, expected):
        assert_almost_equal(inputs[i].grad.asnumpy(), _np.asarray(e),
                            rtol=rtol, atol=atol,
                            names=(f"grad({i})", "expected"))


def check_consistency(fn_or_sym, location, ctx_list=None, rtol=None,
                      atol=None, grad=True):
    """Run the same computation on several contexts and require matching
    outputs (and grads) (reference: test_utils.check_consistency — the
    CPU-vs-GPU tier; here CPU-jax vs TPU-jax)."""
    from . import autograd as _ag
    if ctx_list is None:
        ctx_list = [cpu(0)]
    results = []
    for ctx in ctx_list:
        run, _ = _normalize_fn(fn_or_sym, location)
        inputs = [nd.array(l.astype(_np.float32), ctx=ctx)
                  for l in _loc_list(location)]
        if grad:
            for p in inputs:
                p.attach_grad()
            with _ag.record():
                outs = run(*inputs)
                loss = None
                for o in outs:
                    term = o.sum()
                    loss = term if loss is None else loss + term
            loss.backward()
            grads = [p.grad.asnumpy() for p in inputs]
        else:
            outs = run(*inputs)
            grads = []
        results.append(([_as_np(o) for o in outs], grads))
    ref_outs, ref_grads = results[0]
    for (outs, grads), ctx in list(zip(results, ctx_list))[1:]:
        for o, r in zip(outs, ref_outs):
            assert_almost_equal(o, r, rtol=rtol, atol=atol,
                                names=(f"out@{ctx}",
                                       f"out@{ctx_list[0]}"))
        for g, r in zip(grads, ref_grads):
            assert_almost_equal(g, r, rtol=rtol, atol=atol,
                                names=(f"grad@{ctx}",
                                       f"grad@{ctx_list[0]}"))
    return results


def simple_forward(fn_or_sym, ctx=None, is_train=False, **inputs):
    """One-shot forward with kwargs inputs (reference:
    test_utils.simple_forward)."""
    run, names = _normalize_fn(fn_or_sym, inputs)
    outs = run(*[nd.array(_as_np(v)) for v in inputs.values()])
    return outs[0] if len(outs) == 1 else outs
