"""``mx.np.linalg`` (reference: python/mxnet/numpy/linalg.py).

Delegates to jax.numpy.linalg (XLA-native decompositions; on TPU these
lower to MXU-friendly blocked algorithms where available, else run on
host via XLA CustomCall exactly like the reference falls back to LAPACK).
"""
from __future__ import annotations

from .multiarray import _np_op


def _gen():
    import jax.numpy.linalg as jla
    names = ["norm", "inv", "pinv", "det", "slogdet", "matrix_rank",
             "matrix_power", "solve", "lstsq", "cholesky", "qr", "svd",
             "svdvals", "eig", "eigh", "eigvals", "eigvalsh", "multi_dot",
             "tensorinv", "tensorsolve", "cond", "matrix_transpose",
             "vector_norm", "matrix_norm", "cross", "outer", "diagonal",
             "trace", "vecdot"]
    out = {}
    for n in names:
        f = getattr(jla, n, None)
        if f is not None:
            out[n] = _np_op(f, f"linalg.{n}")
    return out


globals().update(_gen())

__all__ = [n for n in list(globals()) if not n.startswith("_") and n != "annotations"]
