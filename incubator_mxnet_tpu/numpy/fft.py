"""``mx.np.fft`` — NumPy-compatible FFT family (reference: the upstream
``mx.np`` stops at ``contrib`` FFT ops; exposed here as the standard
``np.fft`` namespace because XLA lowers FFTs natively — on TPU via the
accelerated convolution/FFT path, on CPU via Ducc/Eigen).

Complex results come back as complex64 ndarrays (complex IS an XLA
dtype); gradients flow through every transform (jnp.fft is
differentiable), and the wrappers record on the autograd tape like any
other mx.np function.
"""
from __future__ import annotations

from .multiarray import _np_op

_NAMES = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _gen():
    import jax.numpy.fft as jfft
    # hard getattr: every name below exists in supported jax versions,
    # and a silent shrink of __all__ would be invisible to the audit —
    # fail at import instead of as a user-facing AttributeError
    return {n: _np_op(getattr(jfft, n), f"fft.{n}") for n in _NAMES}


globals().update(_gen())

__all__ = list(_NAMES)
