"""NumPy-compatible array + operator namespace (reference:
python/mxnet/numpy/multiarray.py and python/mxnet/numpy/*.py, ~15k LoC of
wrappers there).

TPU-native design: ``mx.np.ndarray`` is the SAME eager tensor as
``mx.nd.NDArray`` (one ``jax.Array`` underneath, one autograd tape), just a
subclass carrying NumPy conventions — ``array(...)`` repr, NumPy argument
spellings (``axis=``, ``keepdims=``, ``size=``), and NumPy function names.
Functions are generated from ``jax.numpy``, which already implements NumPy
semantics on XLA, so every op here inherits the jit/grad/sharding machinery
instead of re-implementing ~300 wrappers by hand.
"""
from __future__ import annotations

import numpy as _onp

from ..base import MXNetError
from ..context import current_context
from ..ndarray.ndarray import (NDArray, _invoke, _place,
                               array as _nd_array)


def _jnp():
    import jax.numpy as jnp
    return jnp


class ndarray(NDArray):
    """NumPy-flavoured view of the framework tensor (reference:
    numpy/multiarray.py ndarray).  Same storage/autograd as NDArray."""

    __slots__ = ()

    def __repr__(self):
        arr = self.asnumpy()
        prefix = "array("
        body = _onp.array2string(arr, separator=", ", prefix=prefix)
        ctx = self.context
        suffix = f", ctx={ctx})" if ctx.device_type != "cpu" else ")"
        return f"{prefix}{body}{suffix}"

    def __str__(self):
        return str(self.asnumpy())

    def as_nd_ndarray(self) -> NDArray:
        out = NDArray(self._data, ctx=self._ctx)
        out._ag_node, out._ag_idx = self._ag_node, self._ag_idx
        out._require_grad = self._require_grad
        out._grad, out._grad_req = self._grad, self._grad_req
        return out

    def as_np_ndarray(self) -> "ndarray":
        return self

    # NumPy spellings over the base methods
    def mean(self, axis=None, dtype=None, keepdims=False):
        return mean(self, axis=axis, dtype=dtype, keepdims=keepdims)

    def sum(self, axis=None, dtype=None, keepdims=False):
        return sum(self, axis=axis, dtype=dtype, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return min(self, axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return prod(self, axis=axis, keepdims=keepdims)

    def std(self, axis=None, ddof=0, keepdims=False):
        return std(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def var(self, axis=None, ddof=0, keepdims=False):
        return var(self, axis=axis, ddof=ddof, keepdims=keepdims)

    def argmax(self, axis=None):
        return argmax(self, axis=axis)

    def argmin(self, axis=None):
        return argmin(self, axis=axis)

    def cumsum(self, axis=None):
        return cumsum(self, axis=axis)

    def dot(self, b):
        return dot(self, b)

    def round(self, decimals=0):
        return around(self, decimals=decimals)

    def clip(self, a_min=None, a_max=None):
        return clip(self, a_min, a_max)

    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return reshape(self, shape, **kwargs)

    def ravel(self):
        return ravel(self)

    def flatten(self):
        return ravel(self)

    def squeeze(self, axis=None):
        return squeeze(self, axis=axis)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return transpose(self, axes if axes else None)

    @property
    def T(self):
        return transpose(self)

    def astype(self, dtype, copy=True):
        return _reclass(super().astype(dtype, copy=copy))

    def copy(self):
        return _reclass(super().copy())

    def tolist(self):
        return self.asnumpy().tolist()


def _reclass(x):
    """Re-brand base-NDArray results as np ndarrays (zero-copy: identical
    slot layout, so only __class__ changes)."""
    _ensure_funcs()   # methods like .mean() resolve generated module
    #                   globals directly, bypassing module __getattr__
    if isinstance(x, (list, tuple)):
        return [_reclass(i) for i in x]
    if isinstance(x, NDArray) and not isinstance(x, ndarray):
        x.__class__ = ndarray
    return x


# re-brand operator results: the base dunders (__add__, __getitem__, ...)
# return base NDArray; np semantics keep the np class closed under ops
def _np_dunder(name):
    base = getattr(NDArray, name)

    def f(self, *a, **kw):
        return _reclass(base(self, *a, **kw))
    f.__name__ = name
    return f


for _name in ["__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
              "__rmul__", "__truediv__", "__rtruediv__", "__floordiv__",
              "__rfloordiv__", "__mod__", "__rmod__", "__pow__", "__rpow__",
              "__matmul__", "__rmatmul__", "__neg__", "__abs__", "__eq__",
              "__ne__", "__gt__", "__ge__", "__lt__", "__le__",
              "__getitem__"]:
    if hasattr(NDArray, _name):
        setattr(ndarray, _name, _np_dunder(_name))
ndarray.__hash__ = None  # rich __eq__ → unhashable, like numpy


# ---------------------------------------------------------------------------
# generic wrapper: jax.numpy function → eager autograd-recorded np function
# ---------------------------------------------------------------------------

# reductions whose ``where=`` selects which ELEMENTS participate —
# jax.numpy implements these natively, so the kwarg passes straight
# through; for everything else ``where=`` is the ufunc output mask and is
# emulated below with jnp.where
_WHERE_REDUCTIONS = frozenset({
    "sum", "prod", "mean", "std", "var", "max", "min", "amax", "amin",
    "nansum", "nanprod", "nanmean", "nanstd", "nanvar", "nanmax",
    "nanmin", "all", "any", "count_nonzero", "average",
})


def _apply_out(res, out, name):
    """NumPy ``out=`` semantics: cast into out's dtype, write in place,
    return the SAME object (so ``np.add(a, b, out=c) is c``)."""
    if isinstance(out, tuple):
        if len(out) != 1:
            raise MXNetError(f"{name}: out must be an ndarray or a "
                             "1-tuple of one")
        out = out[0]
    if not isinstance(out, NDArray):
        raise MXNetError(f"{name}: out must be an mx.np ndarray, got "
                         f"{type(out).__name__}")
    if tuple(out.shape) != tuple(res.shape):
        raise MXNetError(
            f"{name}: non-broadcastable output operand with shape "
            f"{tuple(out.shape)} doesn't match the result shape "
            f"{tuple(res.shape)}")
    if out.dtype != res.dtype:
        res = res.astype(out.dtype)      # numpy same-kind casts into out
    out[:] = res                          # in-place write (cuts out's tape)
    # ...then graft the RESULT's tape node onto the out object, so
    # differentiating through `np.op(a, b, out=c)` sees the op — the
    # write above only replaced the buffer.  An attach_grad'ed buffer
    # stays attached (OR, not overwrite): a plain `buf[:] = ...` write
    # keeps that invariant, so out= must too.
    out._ag_node, out._ag_idx = res._ag_node, res._ag_idx
    out._require_grad = res._require_grad or out._require_grad
    return _reclass(out)


def _np_op(jfn, name):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        where = None
        if name not in _WHERE_REDUCTIONS and "where" in kwargs:
            where = kwargs.pop("where")
        if kwargs.get("order") in ("A", "K"):
            # device arrays have no strides: every array is logically
            # C-contiguous, so numpy's layout-dependent orders collapse
            kwargs["order"] = "C"
        # NDArrays may sit anywhere in the argument pytree (e.g.
        # concatenate([a, b])); flatten, lift them out, and rebuild inside
        # the recorded fun so autograd sees every array input.
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, NDArray))
        arr_idx = [i for i, l in enumerate(leaves)
                   if isinstance(l, NDArray)]
        arrs = [leaves[i] for i in arr_idx]

        # tuple-returning functions (diag_indices, frexp, divmod,
        # unique_all, histogram, ...) must come back as the SAME
        # container numpy uses — a tuple (or namedtuple), never a list:
        # a[np.diag_indices(2)] fancy-indexes axis 0 if handed a list.
        # _invoke flattens tuple outputs to a list, so capture the
        # container type during execution and restore it after.
        out_type = {}

        def run(*jarrs):
            ls = list(leaves)
            for i, j in zip(arr_idx, jarrs):
                ls[i] = j
            a, kw = jax.tree_util.tree_unflatten(treedef, ls)
            r = jfn(*a, **kw)
            if isinstance(r, tuple):
                out_type["t"] = type(r)
            return r

        if where is None:
            res = _reclass(_invoke(run, arrs, name=name))
            t = out_type.get("t")
            if t is not None and isinstance(res, list):
                res = t(*res) if hasattr(t, "_fields") else t(res)
        else:
            # ufunc mask semantics via the double-where trick: masked-OUT
            # positions (a) read 1 instead of the real input, so sqrt(-1)
            # etc. can't produce NaN values OR NaN gradients there, and
            # (b) take out's prior value in the result (0 with no out —
            # numpy leaves them uninitialized; 0 is the deterministic
            # instance of that)
            other = out[0] if isinstance(out, tuple) else out
            n_arr = len(arrs)

            def run_masked(*jall):
                jnp = _jnp()
                jarrs, w = jall[:n_arr], jall[n_arr]
                o = jall[n_arr + 1] if len(jall) > n_arr + 1 else None
                ls = list(leaves)
                for i, j in zip(arr_idx, jarrs):
                    ls[i] = jnp.where(w, j, jnp.ones((), j.dtype))
                a, kw = jax.tree_util.tree_unflatten(treedef, ls)
                r = jfn(*a, **kw)
                base = (o.astype(r.dtype) if o is not None
                        else jnp.zeros((), r.dtype))
                return jnp.where(w, r, base)

            masked_in = arrs + [asarray(where)] \
                + ([asarray(other)] if other is not None else [])
            res = _reclass(_invoke(run_masked, masked_in, name=name))
        if out is not None:
            return _apply_out(res, out, name)
        return res
    fn.__name__ = name
    fn.__qualname__ = name
    fn.__doc__ = (f"NumPy-compatible ``{name}`` lowered through jax.numpy "
                  f"(reference: python/mxnet/numpy {name}); supports "
                  "``out=`` (in-place write, same-object return), ufunc "
                  "``where=`` masks, and C/F/A/K ``order`` where numpy "
                  "has them.")
    return fn


# Names numpy kept but modern jax.numpy dropped → equivalent jnp callable
# (in1d flattens to 1-D per numpy semantics; isin preserves shape)
def _jnp_aliases(jnp):
    return {
        "row_stack": jnp.vstack,  # numpy: row_stack aliases vstack
        "in1d": lambda ar1, ar2, **kw: jnp.isin(ar1, ar2, **kw).ravel(),
    }

# The exported function surface.  Every name is a jax.numpy function with
# NumPy semantics; wrappers record on the autograd tape when inputs do.
_JNP_FUNCS = [
    # math / elementwise
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "fmod", "power", "float_power", "negative",
    "positive", "absolute", "abs", "fabs", "sign", "rint", "floor",
    "ceil", "trunc", "exp", "expm1", "exp2", "log", "log2", "log10",
    "log1p", "sqrt", "cbrt", "square", "reciprocal", "gcd", "lcm",
    "maximum", "minimum", "fmax", "fmin", "hypot", "heaviside",
    "logaddexp", "logaddexp2", "ldexp", "copysign", "nextafter",
    # trig / hyperbolic
    "sin", "cos", "tan", "arcsin", "arccos", "arctan", "arctan2",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    "degrees", "radians", "deg2rad", "rad2deg", "unwrap",
    # rounding / clip
    "around", "round", "clip", "nan_to_num",
    # reductions
    "sum", "prod", "mean", "std", "var", "max", "min", "amax", "amin",
    "ptp", "median", "average", "percentile", "quantile",
    "nansum", "nanprod", "nanmean", "nanstd", "nanvar", "nanmax", "nanmin",
    "cumsum", "cumprod", "nancumsum", "nancumprod",
    "argmax", "argmin", "nanargmax", "nanargmin", "count_nonzero",
    "all", "any",
    # linear algebra (top-level numpy names)
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "cross", "trace", "diagonal",
    # comparison / logic
    "equal", "not_equal", "greater", "greater_equal", "less", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "isnan", "isinf", "isfinite", "isposinf", "isneginf", "isclose",
    "array_equal", "allclose", "signbit",
    # bit ops
    "bitwise_and", "bitwise_or", "bitwise_xor", "invert", "bitwise_not",
    "left_shift", "right_shift",
    # shape manipulation
    "reshape", "ravel", "transpose", "swapaxes", "moveaxis", "rollaxis",
    "expand_dims", "squeeze", "broadcast_to", "broadcast_arrays",
    "atleast_1d", "atleast_2d", "atleast_3d", "flip", "fliplr", "flipud",
    "rot90", "roll", "tile", "repeat", "concatenate", "stack", "vstack",
    "hstack", "dstack", "column_stack", "row_stack", "split",
    "array_split", "hsplit", "vsplit", "dsplit", "append", "insert",
    "delete", "pad", "resize", "flatnonzero",
    # indexing / selection
    "where", "take", "take_along_axis", "choose", "compress", "extract",
    "searchsorted", "argwhere", "nonzero", "diag", "diagflat", "tril",
    "triu", "tri", "select", "indices", "unravel_index", "ravel_multi_index",
    # sorting
    "sort", "argsort", "lexsort", "partition", "argpartition",
    "unique", "sort_complex",
    # sets
    "intersect1d", "union1d", "setdiff1d", "setxor1d", "in1d", "isin",
    # statistics / histograms
    "histogram", "histogram2d", "histogram_bin_edges", "bincount",
    "digitize", "corrcoef", "cov", "correlate", "convolve",
    # polynomials / misc
    "interp", "diff", "ediff1d", "gradient", "trapezoid", "i0", "sinc",
    "real", "imag", "conj", "conjugate", "angle",
    # --- round-5 audit closure (docs/np_coverage.md): numpy-2 spelling
    # aliases, window functions, index builders, polynomials, nan-
    # quantiles, bit packing, unique_* views — all with NumPy semantics
    # straight from jax.numpy
    "acos", "acosh", "asin", "asinh", "atan", "atan2", "atanh",
    "pow", "permute_dims", "concat", "matrix_transpose", "vecdot",
    "bitwise_invert", "bitwise_left_shift", "bitwise_right_shift",
    "bitwise_count",
    "apply_along_axis", "apply_over_axes", "array_equiv", "block",
    "divmod", "frexp", "modf", "spacing",
    "bartlett", "blackman", "hamming", "hanning", "kaiser",
    "diag_indices", "diag_indices_from", "tril_indices",
    "tril_indices_from", "triu_indices", "triu_indices_from",
    "mask_indices", "ix_",
    "iscomplex", "isreal", "nanmedian", "nanpercentile", "nanquantile",
    "packbits", "unpackbits", "piecewise",
    "poly", "polyadd", "polyder", "polydiv", "polyfit", "polyint",
    "polymul", "polysub", "polyval", "roots", "vander", "trim_zeros",
    "unique_all", "unique_counts", "unique_inverse", "unique_values",
    "astype",
]

_THIS = globals()
_jnp_mod = None


def _ensure_funcs():
    global _jnp_mod
    if _jnp_mod is not None:
        return
    jnp = _jnp()
    _jnp_mod = jnp
    for fname in _JNP_FUNCS:
        jfn = getattr(jnp, fname, None)
        if jfn is None:
            # removed from modern jax.numpy: resolve through the alias
            # table so every advertised name works (no phantom __all__
            # entries — from mx.np import * must succeed)
            jfn = _jnp_aliases(jnp).get(fname)
            if jfn is None:
                continue
        if fname not in _THIS:
            _THIS[fname] = _np_op(jfn, fname)
    # numpy fix == truncate toward zero; jnp.fix is deprecated for trunc
    _THIS["fix"] = _np_op(jnp.trunc, "fix")
    for alias, f in _legacy_aliases().items():
        _THIS.setdefault(alias, f)


def __getattr__(name):
    """PEP 562: the jnp-generated function table materializes on first
    access, keeping `import incubator_mxnet_tpu` free of jax.numpy."""
    if name.startswith("_"):
        raise AttributeError(name)
    _ensure_funcs()
    try:
        return _THIS[name]
    except KeyError:
        raise AttributeError(
            f"module 'incubator_mxnet_tpu.numpy' has no attribute {name!r}"
        ) from None


# ---------------------------------------------------------------------------
# creation functions (need ctx/device handling, hence explicit)
# ---------------------------------------------------------------------------
def array(object, dtype=None, ctx=None, device=None, order=None):
    """Create an np ndarray (reference: numpy/multiarray.py array).
    NDArray sources stay on device (_nd_array copies device-to-device).
    ``order`` is accepted for numpy signature parity and ignored: device
    arrays carry no strides, so C/F layout is indistinguishable."""
    if order not in (None, "C", "F", "A", "K"):
        raise MXNetError(f"array: unknown order {order!r}")
    return _reclass(_nd_array(object, ctx=device or ctx, dtype=dtype))


def asarray(a, dtype=None, ctx=None, device=None):
    target = device or ctx
    if (isinstance(a, ndarray) and dtype is None
            and (target is None or target == a.context)):
        return a
    return array(a, dtype=dtype, ctx=ctx, device=device)


def _creation(fname, default_dtype="float32"):
    def fn(*args, dtype=None, ctx=None, device=None, **kwargs):
        jnp = _jnp()
        dtype = dtype if dtype is not None else default_dtype
        out = getattr(jnp, fname)(*args, dtype=_onp.dtype(dtype), **kwargs)
        return _reclass(_place(out, device or ctx))
    fn.__name__ = fname
    return fn


zeros = _creation("zeros")
ones = _creation("ones")
empty = _creation("empty")
eye = _creation("eye")
identity = _creation("identity")


def full(shape, fill_value, dtype=None, ctx=None, device=None):
    jnp = _jnp()
    out = jnp.full(shape, fill_value,
                   dtype=_onp.dtype(dtype) if dtype else None)
    return _reclass(_place(out, device or ctx))


def arange(start, stop=None, step=1, dtype=None, ctx=None, device=None):
    jnp = _jnp()
    out = jnp.arange(start, stop, step,
                     dtype=_onp.dtype(dtype) if dtype else None)
    if out.dtype == _onp.float64:
        out = out.astype(_onp.float32)
    return _reclass(_place(out, device or ctx))


def linspace(start, stop, num=50, endpoint=True, retstep=False, dtype=None,
             axis=0, ctx=None, device=None):
    jnp = _jnp()
    out = jnp.linspace(start, stop, num, endpoint=endpoint, retstep=retstep,
                       dtype=_onp.dtype(dtype) if dtype else _onp.float32,
                       axis=axis)
    if retstep:
        return _reclass(_place(out[0], device or ctx)), float(out[1])
    return _reclass(_place(out, device or ctx))


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None,
             axis=0, ctx=None, device=None):
    jnp = _jnp()
    out = jnp.logspace(start, stop, num, endpoint=endpoint, base=base,
                       dtype=_onp.dtype(dtype) if dtype else _onp.float32,
                       axis=axis)
    return _reclass(_place(out, device or ctx))


def meshgrid(*xi, **kwargs):
    jnp = _jnp()
    outs = jnp.meshgrid(*[x._data if isinstance(x, NDArray) else x
                          for x in xi], **kwargs)
    ctx = (xi[0]._ctx if xi and isinstance(xi[0], NDArray)
           else current_context())
    return [_reclass(_place(o, ctx)) for o in outs]


def zeros_like(a, dtype=None):
    return full_like(a, 0, dtype=dtype)


def ones_like(a, dtype=None):
    return full_like(a, 1, dtype=dtype)


def full_like(a, fill_value, dtype=None):
    jnp = _jnp()
    data = a._data if isinstance(a, NDArray) else a
    ctx = a._ctx if isinstance(a, NDArray) else None
    out = jnp.full_like(data, fill_value,
                        dtype=_onp.dtype(dtype) if dtype else None)
    return _reclass(_place(out, ctx))


def empty_like(a, dtype=None):
    return zeros_like(a, dtype=dtype)


def copy(a):
    return asarray(a).copy()


def geomspace(start, stop, num=50, endpoint=True, dtype=None, axis=0,
              ctx=None, device=None):
    jnp = _jnp()
    out = jnp.geomspace(start, stop, num, endpoint=endpoint,
                        dtype=_onp.dtype(dtype) if dtype else _onp.float32,
                        axis=axis)
    return _reclass(_place(out, device or ctx))


def from_dlpack(x):
    """Zero-copy import through the DLPack protocol (reference:
    numpy/multiarray.py from_dlpack; device arrays share the capsule)."""
    jnp = _jnp()
    return _reclass(_place(jnp.from_dlpack(x), None))


# ---------------------------------------------------------------------------
# metadata / introspection / formatting — host-side, never tape-recorded
# (round-5 np-audit closure; see docs/np_coverage.md)
# ---------------------------------------------------------------------------
def _unwrap(x):
    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(i) for i in x)
    return x


def _unwrap_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap_np(i) for i in x)
    return x


def _meta(mod_getter, fname, alias=None):
    def fn(*args, **kwargs):
        f = getattr(mod_getter(), fname)
        return f(*[_unwrap(a) for a in args],
                 **{k: _unwrap(v) for k, v in kwargs.items()})
    fn.__name__ = alias or fname
    fn.__doc__ = (f"Host-side NumPy ``{alias or fname}`` (metadata/"
                  "formatting — returns host objects, never recorded on "
                  "the autograd tape).")
    return fn


def _meta_np(fname, alias=None):
    """numpy-implemented metadata/formatting helper: NDArray args are
    pulled to host first (these functions read values, e.g. reprs)."""
    def fn(*args, **kwargs):
        f = getattr(_onp, fname)
        return f(*[_unwrap_np(a) for a in args],
                 **{k: _unwrap_np(v) for k, v in kwargs.items()})
    fn.__name__ = alias or fname
    fn.__doc__ = (f"Host-side NumPy ``{alias or fname}`` forwarded to "
                  "NumPy itself (value-reading formatter/metadata helper).")
    return fn


# dtype/shape metadata resolved through jax.numpy (device-dtype aware)
_META_JNP = ["can_cast", "isdtype", "issubdtype", "result_type",
             "promote_types", "broadcast_shapes", "einsum_path",
             "iscomplexobj", "isrealobj", "isscalar", "iterable",
             "ndim", "shape", "size", "frompyfunc"]
# value formatters / host metadata resolved through real NumPy
_META_NP = ["array_repr", "array_str", "array2string", "base_repr",
            "binary_repr", "common_type", "mintypecode", "typename",
            "min_scalar_type", "format_float_positional",
            "format_float_scientific", "get_printoptions",
            "set_printoptions", "printoptions", "isfortran"]
for _m in _META_JNP:
    _THIS[_m] = _meta(_jnp, _m)
for _m in _META_NP:
    _THIS[_m] = _meta_np(_m)
_META_FUNCS = _META_JNP + _META_NP


def may_share_memory(a, b, max_work=None):
    """Device arrays are opaque buffers: two distinct NDArrays never
    alias from numpy's point of view (XLA owns layout), so this is an
    identity test — conservative and correct for the functional model."""
    da = a._data if isinstance(a, NDArray) else a
    db = b._data if isinstance(b, NDArray) else b
    return da is db


def shares_memory(a, b, max_work=None):
    return may_share_memory(a, b, max_work)


# ---------------------------------------------------------------------------
# in-place NumPy mutators (put/place/putmask/copyto/fill_diagonal/
# put_along_axis): compute functionally via jax.numpy, then write into the
# destination buffer with the same tape-grafting rules as ``out=``
# ---------------------------------------------------------------------------
def _as_exact(x):
    """Convert to ndarray PRESERVING the host dtype (int stays int, bool
    stays bool) — index/mask arguments must not take the float32 default
    that ``array()`` applies to python sources."""
    if isinstance(x, NDArray):
        return x
    host = _onp.asarray(x)
    narrow = {_onp.dtype(_onp.int64): _onp.int32,
              _onp.dtype(_onp.uint64): _onp.uint32,
              _onp.dtype(_onp.float64): _onp.float32}.get(host.dtype)
    return array(host, dtype=narrow or host.dtype)


def _write_into(dst, res, name):
    if not isinstance(dst, NDArray):
        raise MXNetError(f"{name}: first argument must be an mx.np "
                         f"ndarray, got {type(dst).__name__}")
    _apply_out(res, dst, name)
    return None          # numpy's in-place mutators return None


def put(a, ind, v, mode="raise"):
    """NumPy ``put`` (in place).  ``mode='raise'`` degrades to ``'clip'``:
    bounds checks are host-side in numpy; on device the index is clamped
    (documented divergence, same policy as the reference's GPU take)."""
    jnp = _jnp()
    jmode = "clip" if mode == "raise" else mode
    res = _np_op(lambda x, i, val: jnp.put(x, i, val, mode=jmode,
                                           inplace=False), "put")(
        a, _as_exact(ind), _as_exact(v))
    return _write_into(a, res, "put")


def place(arr, mask, vals):
    jnp = _jnp()
    res = _np_op(lambda x, m, v: jnp.place(x, m, v, inplace=False),
                 "place")(arr, _as_exact(mask), _as_exact(vals))
    return _write_into(arr, res, "place")


def putmask(a, mask, values):
    """``a.flat[n] = values[n % len(values)]`` where ``mask.flat[n]`` —
    values cycle over ABSOLUTE positions, per numpy semantics."""
    jnp = _jnp()

    def _f(x, m, v):
        vals = jnp.resize(v.ravel(), x.size).reshape(x.shape)
        return jnp.where(m.astype(bool), vals.astype(x.dtype), x)

    res = _np_op(_f, "putmask")(a, _as_exact(mask), _as_exact(values))
    return _write_into(a, res, "putmask")


def copyto(dst, src, casting="same_kind", where=True):
    jnp = _jnp()
    src_dt = (_onp.dtype(str(src.dtype)) if isinstance(src, NDArray)
              else _onp.asarray(src).dtype)
    dst_dt = _onp.dtype(str(dst.dtype))
    if not _onp.can_cast(src_dt, dst_dt, casting=casting):
        raise TypeError(
            f"Cannot cast array data from {src_dt} to {dst_dt} "
            f"according to the rule {casting!r}")

    def _f(d, s, w):
        return jnp.where(w, jnp.broadcast_to(s, d.shape).astype(d.dtype),
                         d)

    res = _np_op(_f, "copyto")(dst, _as_exact(src), _as_exact(where))
    return _write_into(dst, res, "copyto")


def fill_diagonal(a, val, wrap=False):
    jnp = _jnp()
    res = _np_op(lambda x, v: jnp.fill_diagonal(x, v, wrap=wrap,
                                                inplace=False),
                 "fill_diagonal")(a, _as_exact(val))
    return _write_into(a, res, "fill_diagonal")


def put_along_axis(arr, indices, values, axis):
    jnp = _jnp()
    res = _np_op(lambda x, i, v: jnp.put_along_axis(
        x, i, v, axis=axis, inplace=False), "put_along_axis")(
        arr, _as_exact(indices), _as_exact(values))
    return _write_into(arr, res, "put_along_axis")


_INPLACE_FUNCS = ["put", "place", "putmask", "copyto", "fill_diagonal",
                  "put_along_axis", "may_share_memory", "shares_memory"]


# ---------------------------------------------------------------------------
# host I/O (.npy/.npz/text) — NumPy formats byte-for-byte (numpy writes
# them); arrays round-trip through host memory, like the reference's
# mx.np save/load (reference: python/mxnet/numpy/io.py analog)
# ---------------------------------------------------------------------------
def save(file, arr, allow_pickle=False):
    _onp.save(file, _unwrap_np(asarray(arr)), allow_pickle=allow_pickle)


def savez(file, *args, **kwds):
    _onp.savez(file, *[_unwrap_np(a) for a in args],
               **{k: _unwrap_np(v) for k, v in kwds.items()})


def savez_compressed(file, *args, **kwds):
    _onp.savez_compressed(file, *[_unwrap_np(a) for a in args],
                          **{k: _unwrap_np(v) for k, v in kwds.items()})


def _from_host(out):
    # structured dtypes have no device representation; hand back the
    # host record array (same policy as loadtxt/genfromtxt/fromregex)
    return out if getattr(out.dtype, "names", None) else array(out)


def load(file, allow_pickle=False, **kwargs):
    out = _onp.load(file, allow_pickle=allow_pickle, **kwargs)
    if isinstance(out, _onp.lib.npyio.NpzFile):
        try:
            return {k: _from_host(out[k]) for k in out.files}
        finally:
            out.close()
    return _from_host(out)


def savetxt(fname, X, **kwargs):
    _onp.savetxt(fname, _unwrap_np(asarray(X)), **kwargs)


def loadtxt(fname, **kwargs):
    out = _onp.loadtxt(fname, **kwargs)
    return out if out.dtype.names else array(out)


def genfromtxt(fname, **kwargs):
    out = _onp.genfromtxt(fname, **kwargs)
    return out if out.dtype.names else array(out)


def fromfile(file, dtype=float, count=-1, sep="", offset=0):
    return array(_onp.fromfile(file, dtype=dtype, count=count, sep=sep,
                               offset=offset))


def frombuffer(buffer, dtype=float, count=-1, offset=0):
    return array(_onp.frombuffer(buffer, dtype=dtype, count=count,
                                 offset=offset))


def fromstring(string, dtype=float, count=-1, sep=""):
    return array(_onp.fromstring(string, dtype=dtype, count=count,
                                 sep=sep))


def fromiter(iter, dtype, count=-1):
    return array(_onp.fromiter(iter, dtype, count=count))


def fromfunction(function, shape, dtype=float, **kwargs):
    return array(_onp.fromfunction(function, shape, dtype=dtype,
                                   **kwargs))


def fromregex(file, regexp, dtype, encoding=None):
    out = _onp.fromregex(file, regexp, dtype, encoding=encoding)
    # structured dtypes have no device representation; hand back the
    # host record array (numpy-compatible behavior for field access)
    return out if out.dtype.names else array(out)


def mask_indices(n, mask_func, k=0):
    """Indices where ``mask_func(ones((n, n)), k)`` is nonzero.  The
    mask_func may be an mx.np function (returns NDArray) or a plain
    numpy/jnp one — both are unwrapped to the raw array."""
    jnp = _jnp()

    def mf(m, kk):
        r = mask_func(m, kk)
        return r._data if isinstance(r, NDArray) else r

    out = jnp.mask_indices(n, mf, k)
    return tuple(_reclass(_place(o, None)) for o in out)


_IO_FUNCS = ["save", "savez", "savez_compressed", "load", "savetxt",
             "loadtxt", "genfromtxt", "fromfile", "frombuffer",
             "fromstring", "fromiter", "fromfunction", "fromregex"]


# ---------------------------------------------------------------------------
# conversion helpers: device arrays are always contiguous and stride-free,
# so the layout-asserting converters collapse to asarray
# ---------------------------------------------------------------------------
def asanyarray(a, dtype=None):
    return asarray(a, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return asarray(a, dtype=dtype)


def asfortranarray(a, dtype=None):
    return asarray(a, dtype=dtype)


def asfarray(a, dtype=None):
    out = asarray(a, dtype=dtype)
    if not _onp.issubdtype(_onp.dtype(str(out.dtype)), _onp.floating):
        out = out.astype("float32")
    return out


def asarray_chkfinite(a, dtype=None):
    out = asarray(a, dtype=dtype)
    host = out.asnumpy()
    if host.dtype.kind in "fc" and not _onp.isfinite(host).all():
        raise ValueError("array must not contain infs or NaNs")
    return out


def require(a, dtype=None, requirements=None):
    # layout requirements (C/F/A/O/W/E) are meaningless for device
    # buffers; only the dtype request has effect
    return asarray(a, dtype=dtype)


def real_if_close(a, tol=100):
    a = asarray(a)
    host = a.asnumpy()
    return array(_onp.real_if_close(host, tol=tol))


_CONVERT_FUNCS = ["asanyarray", "ascontiguousarray", "asfortranarray",
                  "asfarray", "asarray_chkfinite", "require",
                  "real_if_close", "geomspace", "from_dlpack",
                  "histogramdd"]


def histogramdd(sample, bins=10, range=None, density=None, weights=None):
    """Explicit wrapper: the (hist, [edges...]) nested return does not fit
    the generic multi-output funnel."""
    jnp = _jnp()
    h, edges = jnp.histogramdd(
        _unwrap(asarray(sample)), bins=_unwrap(bins), range=range,
        density=density,
        weights=_unwrap(asarray(weights)) if weights is not None else None)
    return _reclass(_place(h, None)), [_reclass(_place(e, None))
                                       for e in edges]


# numpy-1.x spellings the reference era exposed (removed in numpy 2.0)
def _legacy_aliases():
    _ensure_funcs()
    return {
        "alltrue": _THIS["all"], "sometrue": _THIS["any"],
        "product": _THIS["prod"], "cumproduct": _THIS["cumprod"],
        "round_": _THIS["around"], "trapz": _THIS["trapezoid"],
        "msort": lambda a: _THIS["sort"](a, axis=0),
    }


_LEGACY_FUNCS = ["alltrue", "sometrue", "product", "cumproduct",
                 "round_", "trapz", "msort"]


# constants
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
# dtypes re-exported like numpy
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_
dtype = _onp.dtype
# numpy-1.x scalar-type spellings (reference era) + complex on device
complex64 = _onp.complex64
complex128 = _onp.complex128
half = _onp.float16
single = _onp.float32
double = _onp.float64
intc = _onp.intc
uintc = _onp.uintc
byte = _onp.byte
ubyte = _onp.ubyte
short = _onp.short
ushort = _onp.ushort
longlong = _onp.longlong
ulonglong = _onp.ulonglong
intp = _onp.intp
uintp = _onp.uintp
float_ = _onp.float64
int_ = _onp.int64
complex_ = _onp.complex128
uint = _onp.uint64

_DTYPE_ALIASES = ["complex64", "complex128", "half", "single", "double",
                  "intc", "uintc", "byte", "ubyte", "short", "ushort",
                  "longlong", "ulonglong", "intp", "uintp", "float_",
                  "int_", "complex_", "uint"]


def get_include():
    raise MXNetError("get_include is a CPython-extension helper of the "
                     "reference; not applicable to the TPU build")


__all__ = (["ndarray", "array", "asarray", "zeros", "ones", "empty", "full",
            "arange", "linspace", "logspace", "meshgrid", "eye", "identity",
            "zeros_like", "ones_like", "full_like", "empty_like", "copy",
            "pi", "e", "euler_gamma", "inf", "nan", "newaxis", "fix",
            "dtype", "float16", "float32", "float64", "int8", "int16",
            "int32", "int64", "uint8", "uint16", "uint32", "uint64",
            "bool_"]
           + _JNP_FUNCS + _META_FUNCS + _INPLACE_FUNCS + _IO_FUNCS
           + _CONVERT_FUNCS + _LEGACY_FUNCS + _DTYPE_ALIASES)
