"""``mx.np.random`` — NumPy-style samplers (reference:
python/mxnet/numpy/random.py).

Same per-context key stream as ``mx.nd.random`` (incubator_mxnet_tpu.random),
so ``mx.random.seed`` governs both namespaces; NumPy spelling: ``size=``
instead of ``shape=``.
"""
from __future__ import annotations

import numpy as _onp

from .. import random as _random
from ..context import current_context
from ..ndarray import random as _nd_random
from ..ndarray.ndarray import _place
from .multiarray import _reclass

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint",
           "choice", "shuffle", "permutation", "beta", "gamma",
           "exponential", "poisson", "multinomial", "binomial",
           "lognormal", "laplace", "standard_normal",
           # round-5 distribution tail (jax.random-backed)
           "chisquare", "dirichlet", "f", "geometric", "gumbel",
           "logistic", "multivariate_normal", "pareto", "rayleigh",
           "standard_cauchy", "standard_t", "standard_exponential",
           "standard_gamma", "triangular", "wald", "weibull",
           "negative_binomial", "random", "random_sample", "ranf",
           "sample", "bytes"]


def seed(s):
    _random.seed(s)


def _size(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype="float32", ctx=None,
            device=None):
    return _reclass(_nd_random.uniform(low, high, _size(size), dtype=dtype,
                                       ctx=device or ctx))


def normal(loc=0.0, scale=1.0, size=None, dtype="float32", ctx=None,
           device=None):
    return _reclass(_nd_random.normal(loc, scale, _size(size), dtype=dtype,
                                      ctx=device or ctx))


def standard_normal(size=None, dtype="float32"):
    return normal(0.0, 1.0, size=size, dtype=dtype)


def randn(*size, dtype="float32"):
    return normal(0.0, 1.0, size=size or None, dtype=dtype)


def rand(*size, dtype="float32"):
    return uniform(0.0, 1.0, size=size or None, dtype=dtype)


def randint(low, high=None, size=None, dtype="int32", ctx=None, device=None):
    if high is None:
        low, high = 0, low
    return _reclass(_nd_random.randint(low, high, _size(size), dtype=dtype,
                                       ctx=device or ctx))


def poisson(lam=1.0, size=None, ctx=None, device=None):
    return _reclass(_nd_random.poisson(lam, _size(size),
                                       ctx=device or ctx))


def exponential(scale=1.0, size=None, ctx=None, device=None):
    return _reclass(_nd_random.exponential(scale, _size(size),
                                           ctx=device or ctx))


def gamma(shape, scale=1.0, size=None, ctx=None, device=None):
    return _reclass(_nd_random.gamma(shape, scale, _size(size),
                                     ctx=device or ctx))


def beta(a, b, size=None, ctx=None, device=None):
    import jax
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    # size=None keeps jax's parameter-broadcast shape (numpy semantics)
    out = jax.random.beta(key, a, b,
                          None if size is None else _size(size))
    return _reclass(_place(out, ctx))


def laplace(loc=0.0, scale=1.0, size=None, ctx=None, device=None):
    import jax
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    out = loc + scale * jax.random.laplace(key, _size(size))
    return _reclass(_place(out, ctx))


def lognormal(mean=0.0, sigma=1.0, size=None, ctx=None, device=None):
    from . import multiarray as _mnp
    return _mnp.exp(normal(mean, sigma, size=size, ctx=device or ctx))


def binomial(n, p, size=None, ctx=None, device=None):
    import jax
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    out = jax.random.binomial(
        key, n, p, shape=None if size is None else _size(size))
    return _reclass(_place(out, ctx))


def multinomial(n, pvals, size=None):
    import jax
    ctx = current_context()
    key = _random.new_key(ctx)
    pv = _onp.asarray(pvals, dtype="float32")
    # jax's shape= is the FULL result shape including the category axis
    # (p is broadcast to it), so numpy's size + (k,) maps directly
    counts = jax.random.multinomial(
        key, n, jax.numpy.asarray(pv), shape=_size(size) + (len(pv),))
    return _reclass(_place(counts, ctx))


def choice(a, size=None, replace=True, p=None, ctx=None, device=None):
    import jax
    import jax.numpy as jnp
    from ..ndarray.ndarray import NDArray
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    if isinstance(a, NDArray):
        a = a._data
    elif isinstance(a, int):
        a = jnp.arange(a)
    else:
        a = jnp.asarray(a)
    out = jax.random.choice(key, a, shape=_size(size), replace=replace,
                            p=None if p is None else jnp.asarray(p))
    return _reclass(_place(out, ctx))


def permutation(x):
    import jax
    from ..ndarray.ndarray import NDArray
    ctx = x._ctx if isinstance(x, NDArray) else current_context()
    key = _random.new_key(ctx)
    if isinstance(x, NDArray):
        out = jax.random.permutation(key, x._data)
    else:
        out = jax.random.permutation(key, x)
    return _reclass(_place(out, ctx))


def shuffle(x):
    """In-place shuffle along axis 0 (reference: np.random.shuffle)."""
    from ..ndarray.ndarray import NDArray
    if not isinstance(x, NDArray):
        raise TypeError("shuffle expects an ndarray")
    x._set_data(permutation(x)._data)


# ---------------------------------------------------------------------------
# round-5 distribution tail: the rest of the numpy.random function
# surface that jax.random backs directly (reference:
# python/mxnet/numpy/random.py; RandomState/Generator OBJECT machinery is
# out of scope — this framework's RNG is the per-context key stream, see
# docs/np_coverage.md)
# ---------------------------------------------------------------------------
def _draw(sample, size=None, ctx=None):
    """Common tail: new key from the context stream, sample, place.
    ``size=None`` hands jax ``shape=None`` — NumPy semantics: the result
    broadcasts to the distribution parameters' shape, one INDEPENDENT
    draw per element (not one scalar broadcast over them)."""
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    return _reclass(_place(
        sample(key, None if size is None else _size(size)), ctx))


def _param_shape(s, *params):
    """Draw shape for transform-style samplers: the requested size, else
    the broadcast of the parameter shapes (numpy's size=None rule)."""
    import jax.numpy as jnp
    if s is not None:
        return s
    return jnp.broadcast_shapes(*[jnp.shape(p) for p in params])


def chisquare(df, size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: jax.random.chisquare(k, df, shape=s),
                 size, device or ctx)


def dirichlet(alpha, size=None, ctx=None, device=None):
    import jax
    import jax.numpy as jnp
    a = jnp.asarray(alpha, dtype="float32")
    return _draw(lambda k, s: jax.random.dirichlet(k, a, shape=s),
                 size, device or ctx)


def f(dfnum, dfden, size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: jax.random.f(k, dfnum, dfden, shape=s),
                 size, device or ctx)


def geometric(p, size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: jax.random.geometric(k, p, shape=s),
                 size, device or ctx)


def gumbel(loc=0.0, scale=1.0, size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: loc + scale * jax.random.gumbel(
        k, _param_shape(s, loc, scale)), size, device or ctx)


def logistic(loc=0.0, scale=1.0, size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: loc + scale * jax.random.logistic(
        k, _param_shape(s, loc, scale)), size, device or ctx)


def multivariate_normal(mean, cov, size=None, ctx=None, device=None):
    import jax
    import jax.numpy as jnp
    m = jnp.asarray(mean, dtype="float32")
    c = jnp.asarray(cov, dtype="float32")
    return _draw(lambda k, s: jax.random.multivariate_normal(
        k, m, c, shape=s), size, device or ctx)


def pareto(a, size=None, ctx=None, device=None):
    import jax
    # numpy draws the Lomax (shifted Pareto): classical Pareto - 1
    return _draw(lambda k, s: jax.random.pareto(k, a, shape=s) - 1.0,
                 size, device or ctx)


def rayleigh(scale=1.0, size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: jax.random.rayleigh(k, scale, shape=s),
                 size, device or ctx)


def standard_cauchy(size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: jax.random.cauchy(k, s),
                 size, device or ctx)


def standard_t(df, size=None, ctx=None, device=None):
    import jax
    # shape made explicit: jax.random.t does not accept shape=None
    return _draw(lambda k, s: jax.random.t(
        k, df, shape=_param_shape(s, df)), size, device or ctx)


def standard_exponential(size=None, ctx=None, device=None):
    return exponential(1.0, size=size, ctx=device or ctx)


def standard_gamma(shape, size=None, ctx=None, device=None):
    return gamma(shape, 1.0, size=size, ctx=device or ctx)


def triangular(left, mode, right, size=None, ctx=None, device=None):
    import jax
    return _draw(lambda k, s: jax.random.triangular(
        k, left, mode, right, shape=s), size, device or ctx)


def wald(mean, scale, size=None, ctx=None, device=None):
    import jax
    # jax.random.wald samples IG(mu, lambda=1); the inverse-Gaussian
    # scaling law cX ~ IG(c*mu, c*lambda) gives
    # IG(mean, scale) = scale * IG(mean/scale, 1)
    return _draw(lambda k, s: scale * jax.random.wald(
        k, mean / scale, shape=s), size, device or ctx)


def weibull(a, size=None, ctx=None, device=None):
    import jax
    # numpy's standard Weibull: scale 1, concentration a (draw shape
    # made explicit: jax's weibull_min does not broadcast shape=None
    # against the concentration)
    return _draw(lambda k, s: jax.random.weibull_min(
        k, 1.0, a, shape=_param_shape(s, a)), size, device or ctx)


def negative_binomial(n, p, size=None, ctx=None, device=None):
    # numpy counts FAILURES before the n-th success with success prob p;
    # the nd.random sampler uses the same (k, p) convention
    return _reclass(_nd_random.negative_binomial(
        k=n, p=p, shape=_size(size), ctx=device or ctx))


def random(size=None, ctx=None, device=None):
    return uniform(0.0, 1.0, size=size, ctx=device or ctx)


random_sample = random
ranf = random
sample = random


def bytes(length):
    """``length`` random bytes (reference: np.random.bytes)."""
    out = randint(0, 256, size=(int(length),), dtype="int32")
    return _onp.asarray(out.asnumpy(), dtype=_onp.uint8).tobytes()
