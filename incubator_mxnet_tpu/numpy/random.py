"""``mx.np.random`` — NumPy-style samplers (reference:
python/mxnet/numpy/random.py).

Same per-context key stream as ``mx.nd.random`` (incubator_mxnet_tpu.random),
so ``mx.random.seed`` governs both namespaces; NumPy spelling: ``size=``
instead of ``shape=``.
"""
from __future__ import annotations

import numpy as _onp

from .. import random as _random
from ..context import current_context
from ..ndarray import random as _nd_random
from ..ndarray.ndarray import _place
from .multiarray import _reclass

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint",
           "choice", "shuffle", "permutation", "beta", "gamma",
           "exponential", "poisson", "multinomial", "binomial",
           "lognormal", "laplace", "standard_normal"]


def seed(s):
    _random.seed(s)


def _size(size):
    if size is None:
        return ()
    if isinstance(size, int):
        return (size,)
    return tuple(size)


def uniform(low=0.0, high=1.0, size=None, dtype="float32", ctx=None,
            device=None):
    return _reclass(_nd_random.uniform(low, high, _size(size), dtype=dtype,
                                       ctx=device or ctx))


def normal(loc=0.0, scale=1.0, size=None, dtype="float32", ctx=None,
           device=None):
    return _reclass(_nd_random.normal(loc, scale, _size(size), dtype=dtype,
                                      ctx=device or ctx))


def standard_normal(size=None, dtype="float32"):
    return normal(0.0, 1.0, size=size, dtype=dtype)


def randn(*size, dtype="float32"):
    return normal(0.0, 1.0, size=size or None, dtype=dtype)


def rand(*size, dtype="float32"):
    return uniform(0.0, 1.0, size=size or None, dtype=dtype)


def randint(low, high=None, size=None, dtype="int32", ctx=None, device=None):
    if high is None:
        low, high = 0, low
    return _reclass(_nd_random.randint(low, high, _size(size), dtype=dtype,
                                       ctx=device or ctx))


def poisson(lam=1.0, size=None, ctx=None, device=None):
    return _reclass(_nd_random.poisson(lam, _size(size),
                                       ctx=device or ctx))


def exponential(scale=1.0, size=None, ctx=None, device=None):
    return _reclass(_nd_random.exponential(scale, _size(size),
                                           ctx=device or ctx))


def gamma(shape, scale=1.0, size=None, ctx=None, device=None):
    return _reclass(_nd_random.gamma(shape, scale, _size(size),
                                     ctx=device or ctx))


def beta(a, b, size=None, ctx=None, device=None):
    import jax
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    # size=None keeps jax's parameter-broadcast shape (numpy semantics)
    out = jax.random.beta(key, a, b,
                          None if size is None else _size(size))
    return _reclass(_place(out, ctx))


def laplace(loc=0.0, scale=1.0, size=None, ctx=None, device=None):
    import jax
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    out = loc + scale * jax.random.laplace(key, _size(size))
    return _reclass(_place(out, ctx))


def lognormal(mean=0.0, sigma=1.0, size=None, ctx=None, device=None):
    from . import multiarray as _mnp
    return _mnp.exp(normal(mean, sigma, size=size, ctx=device or ctx))


def binomial(n, p, size=None, ctx=None, device=None):
    import jax
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    out = jax.random.binomial(
        key, n, p, shape=None if size is None else _size(size))
    return _reclass(_place(out, ctx))


def multinomial(n, pvals, size=None):
    import jax
    ctx = current_context()
    key = _random.new_key(ctx)
    pv = _onp.asarray(pvals, dtype="float32")
    # jax's shape= is the FULL result shape including the category axis
    # (p is broadcast to it), so numpy's size + (k,) maps directly
    counts = jax.random.multinomial(
        key, n, jax.numpy.asarray(pv), shape=_size(size) + (len(pv),))
    return _reclass(_place(counts, ctx))


def choice(a, size=None, replace=True, p=None, ctx=None, device=None):
    import jax
    import jax.numpy as jnp
    from ..ndarray.ndarray import NDArray
    ctx = device or ctx or current_context()
    key = _random.new_key(ctx)
    if isinstance(a, NDArray):
        a = a._data
    elif isinstance(a, int):
        a = jnp.arange(a)
    else:
        a = jnp.asarray(a)
    out = jax.random.choice(key, a, shape=_size(size), replace=replace,
                            p=None if p is None else jnp.asarray(p))
    return _reclass(_place(out, ctx))


def permutation(x):
    import jax
    from ..ndarray.ndarray import NDArray
    ctx = x._ctx if isinstance(x, NDArray) else current_context()
    key = _random.new_key(ctx)
    if isinstance(x, NDArray):
        out = jax.random.permutation(key, x._data)
    else:
        out = jax.random.permutation(key, x)
    return _reclass(_place(out, ctx))


def shuffle(x):
    """In-place shuffle along axis 0 (reference: np.random.shuffle)."""
    from ..ndarray.ndarray import NDArray
    if not isinstance(x, NDArray):
        raise TypeError("shuffle expects an ndarray")
    x._set_data(permutation(x)._data)
