"""``mx.np``: NumPy-compatible array API on the framework tensor
(reference: python/mxnet/numpy/__init__.py).

Attribute access is lazy (PEP 562): the jnp-backed function table and the
linalg/random submodules materialize on first use so that importing the
package stays jax.numpy-free.
"""
import importlib as _importlib

from . import multiarray as _ma
from .multiarray import ndarray  # noqa: F401 — the array type, always eager

_SUBMODULES = ("linalg", "random", "fft")


def __getattr__(name):
    if name in _SUBMODULES:
        mod = _importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    return getattr(_ma, name)


def __dir__():
    return sorted(set(list(globals()) + list(_ma.__all__)
                      + list(_SUBMODULES)))
