"""Training callbacks (reference: python/mxnet/callback.py)."""
from __future__ import annotations

import logging
import time
from collections import namedtuple

__all__ = ["Speedometer", "do_checkpoint", "log_uniform_checkpoint",
           "module_checkpoint", "log_train_metric", "ProgressBar",
           "LogValidationMetricsCallback", "BatchEndParam"]

BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals"])


class Speedometer:
    """Log samples/sec + metrics every ``frequent`` batches (reference:
    callback.Speedometer — the de-facto training progress readout)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param: BatchEndParam):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (
                    time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                    msg += "\t%s=%f" * len(name_value)
                    logging.info(msg, param.epoch, count, speed,
                                 *sum(name_value, ()))
                else:
                    logging.info(
                        "Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                        param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Text progress bar per epoch (reference: callback.ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param: BatchEndParam):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = int(round(100.0 * count / float(self.total)))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving prefix-symbol.json + prefix-%04d.params
    (reference: callback.do_checkpoint → model.save_checkpoint)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_uniform_checkpoint(prefix, period=1):
    return do_checkpoint(prefix, period)


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch-end callback saving a Module checkpoint (reference:
    callback.module_checkpoint → Module.save_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1,
                                save_optimizer_states)
    return _callback


def log_train_metric(period, auto_reset=False):
    """Batch-end callback logging the train metric every ``period``
    batches (reference: callback.log_train_metric)."""
    import logging

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback
