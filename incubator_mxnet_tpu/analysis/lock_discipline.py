"""lock-discipline: no blocking work under a lock, consistent ordering.

Rule 1 — **no blocking calls while holding a lock**.  A ``with
<lock>:`` body must be pure bookkeeping; anything that can park the
thread (an untimed ``queue.get()``, ``Thread.join()``, ``Event.wait()``,
a socket read, an HTTP round-trip, an engine dispatch, ``retry_call``,
``time.sleep``) starves every other thread contending on the lock — in
the batcher that includes the watchdog, which needs ``_cv`` to even
decide whether the worker is wedged.

Rule 2 — **consistent acquisition order**.  When one ``with`` statement
nests inside another's body, the (outer, inner) lock-name pair is
recorded; if the reversed pair appears anywhere else in the project the
two sites can deadlock against each other and both are flagged (in
``finalize``, so the pairing is project-wide).

Locks are recognized structurally (assignment from
``threading.Lock/RLock/Condition/Semaphore/BoundedSemaphore``) and by
name (``lock``/``cv``/``cond``/``mutex`` or a ``_lock``/``_cv``/
``_cond``/``_mutex`` suffix).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import _astutil
from .core import Checker, FileContext, Finding

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_LOCK_BARE = {"lock", "cv", "cond", "mutex"}
_LOCK_SUFFIXES = ("_lock", "_cv", "_cond", "_mutex")

# attr calls that block when given no timeout argument
_BLOCK_IF_UNTIMED = {"get", "join", "wait", "acquire", "result"}
# attr calls that block, period
_BLOCK_ALWAYS = {"recv", "recv_into", "accept", "makefile", "getresponse",
                 "urlopen", "sleep", "retry_call"}
# engine dispatch entry points (device round-trips); blocking when the
# receiver chain mentions an engine
_ENGINE_DISPATCH = {"prefill", "decode", "verify", "spec_step", "predict",
                    "warmup", "reset"}


def _lockish(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return low in _LOCK_BARE or low.endswith(_LOCK_SUFFIXES)


class LockDisciplineChecker(Checker):
    name = "lock-discipline"

    def __init__(self):
        # (outer, inner) -> list of (relpath, line) witnesses
        self._orders: Dict[Tuple[str, str],
                           List[Tuple[str, int]]] = {}

    def check_file(self, ctx: FileContext) -> List[Finding]:
        structural = self._structural_locks(ctx)
        findings: List[Finding] = []
        for qual, fn in _astutil.iter_functions(ctx.tree):
            findings.extend(self._scan(ctx, qual, fn, structural))
        return findings

    @staticmethod
    def _structural_locks(ctx: FileContext) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            if _astutil.attr_tail(node.value.func) in _LOCK_FACTORIES:
                for tgt in node.targets:
                    tail = _astutil.attr_tail(tgt)
                    if tail:
                        names.add(tail)
        return names

    def _lock_name(self, item: ast.withitem,
                   structural: Set[str]) -> Optional[str]:
        expr = item.context_expr
        # with lock.acquire_timeout(...) style: look at the receiver
        if isinstance(expr, ast.Call):
            expr = expr.func
            if isinstance(expr, ast.Attribute):
                expr = expr.value
        tail = _astutil.attr_tail(expr)
        if tail and (tail in structural or _lockish(tail)):
            return tail
        return None

    def _scan(self, ctx: FileContext, qual: str, fn: ast.AST,
              structural: Set[str]) -> List[Finding]:
        findings: List[Finding] = []

        def visit(node: ast.AST, held: Tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    locks = [n for n in
                             (self._lock_name(i, structural)
                              for i in child.items) if n]
                    for inner in locks:
                        for outer in held:
                            if outer != inner:
                                self._orders.setdefault(
                                    (outer, inner), []).append(
                                    (ctx.relpath, child.lineno))
                    new_held = held + tuple(l for l in locks
                                            if l not in held)
                    if locks and held:
                        pass  # nested acquire itself is fine; order
                        # conflicts surface in finalize()
                    visit(child, new_held)
                    continue
                if held and isinstance(child, ast.Call):
                    what = self._blocking(child)
                    if what:
                        findings.append(Finding(
                            self.name, ctx.relpath, child.lineno,
                            f"{what} while holding `{held[-1]}` in "
                            f"`{qual}` — blocking under a lock starves "
                            "every thread contending on it"))
                visit(child, held)

        visit(fn, ())
        return findings

    @staticmethod
    def _blocking(call: ast.Call) -> Optional[str]:
        fn = call.func
        tail = _astutil.attr_tail(fn)
        if tail is None:
            return None
        kws = {kw.arg for kw in call.keywords}
        if tail in _BLOCK_IF_UNTIMED and isinstance(fn, ast.Attribute):
            # a positional or keyword timeout makes these bounded
            if not call.args and "timeout" not in kws \
                    and "block" not in kws:
                recv = _astutil.attr_tail(fn.value) or ""
                if tail == "acquire" or tail == "result":
                    return f"untimed `.{tail}()`"
                if tail == "get" and not _lockish(recv):
                    return "untimed `.get()` (queue read)"
                if tail == "join":
                    return "untimed `.join()`"
                # cv.wait() under `with cv:` releases the lock — the
                # canonical condition-variable pattern, not a hazard
                if tail == "wait" and not _lockish(recv):
                    return "untimed `.wait()`"
            return None
        if tail in _BLOCK_ALWAYS:
            if tail == "sleep":
                chain = _astutil.attr_parts(fn)
                if chain[:1] not in (["time"], ["sleep"]) \
                        and tail != chain[-1]:
                    return None
                return "`time.sleep`" if len(chain) > 1 else "`sleep`"
            if tail == "retry_call":
                return "`retry_call` (retry loop with backoff sleeps)"
            return f"blocking I/O `.{tail}()`"
        if tail in _ENGINE_DISPATCH and isinstance(fn, ast.Attribute):
            chain = [p.lower() for p in _astutil.attr_parts(fn)[:-1]]
            if any("engine" in p for p in chain):
                return f"engine dispatch `.{tail}()` (device round-trip)"
        return None

    def finalize(self, ctxs) -> List[Finding]:
        findings: List[Finding] = []
        for (outer, inner), sites in sorted(self._orders.items()):
            rev = self._orders.get((inner, outer))
            if not rev or (inner, outer) < (outer, inner):
                continue  # report each conflicting pair once
            path, line = sites[0]
            rpath, rline = rev[0]
            findings.append(Finding(
                self.name, path, line,
                f"lock order `{outer}` -> `{inner}` here conflicts with "
                f"`{inner}` -> `{outer}` at {rpath}:{rline} — the two "
                "sites can deadlock against each other"))
        self._orders.clear()
        return findings
