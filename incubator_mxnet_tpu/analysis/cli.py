"""``mxtpu-lint`` command-line entry point.

Usage::

    mxtpu-lint incubator_mxnet_tpu/            # lint, exit 1 on findings
    mxtpu-lint --checks lock-discipline pkg/   # subset of checkers
    mxtpu-lint --write-baseline pkg/           # snapshot current findings
    mxtpu-lint --format json pkg/              # machine-readable output

The baseline (``.mxtpu-lint-baseline.json`` at the repo root, or
``--baseline PATH``) suppresses known-intentional findings; every entry
carries a one-line justification.  Inline ``# mxtpu-lint:
disable=<check>`` pragmas are applied before the baseline.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .core import (BASELINE_FILENAME, Baseline, collect_files,
                   default_checkers, find_root, line_text_lookup,
                   run_checks)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mxtpu-lint",
        description="JAX/TPU-aware static analysis for mxnet-tpu "
                    "(host-sync, donation, closed-program-set, "
                    "lock-discipline, registry-drift).")
    p.add_argument("paths", nargs="*", default=[],
                   help="files or directories to lint")
    p.add_argument("--checks", default=None,
                   help="comma-separated subset of check names")
    p.add_argument("--list-checks", action="store_true",
                   help="list available checks and exit")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file (default: "
                        f"{BASELINE_FILENAME} at the repo root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--jobs", type=int, default=None,
                   help="parallel file-walk workers")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print baselined findings (marked)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)

    if args.list_checks:
        for c in default_checkers():
            print(c.name)
        return 0
    if not args.paths:
        _parser().error("no paths given")

    files = collect_files(args.paths)
    if not files:
        print("mxtpu-lint: no python files under "
              + ", ".join(args.paths), file=sys.stderr)
        return 2
    root = find_root(files[0])
    checks = None
    if args.checks:
        checks = [c.strip() for c in args.checks.split(",") if c.strip()]
    try:
        findings = run_checks(args.paths, checks=checks, root=root,
                              jobs=args.jobs)
    except ValueError as exc:   # unknown check name
        print(f"mxtpu-lint: {exc}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, BASELINE_FILENAME)
    line_text = line_text_lookup(root)

    if args.write_baseline:
        Baseline.from_findings(findings, line_text).save(baseline_path)
        print(f"mxtpu-lint: wrote {len(findings)} entr"
              f"{'y' if len(findings) == 1 else 'ies'} to "
              f"{baseline_path}")
        return 0

    baselined: List = []
    if not args.no_baseline and os.path.isfile(baseline_path):
        findings, baselined = Baseline.load(baseline_path).filter(
            findings, line_text)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.as_dict() for f in findings],
            "baselined": [f.as_dict() for f in baselined],
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        if args.show_baselined:
            for f in baselined:
                print(f"{f.render()}  [baselined]")
        n, b = len(findings), len(baselined)
        print(f"mxtpu-lint: {n} finding{'' if n == 1 else 's'} "
              f"({b} baselined) across {len(files)} files",
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
