"""Small shared AST helpers for the mxtpu-lint checkers."""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple


def attr_tail(node: ast.expr) -> Optional[str]:
    """Last component of a Name/Attribute chain (``self.a.b`` -> ``b``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def attr_parts(node: ast.expr) -> List[str]:
    """Components of a Name/Attribute chain, outermost first
    (``self.engine.decode`` -> ``["self", "engine", "decode"]``)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def dotted(node: ast.expr) -> str:
    return ".".join(attr_parts(node))


def iter_functions(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """Yield ``(qualname, node)`` for every function/method, including
    nested defs (qualname uses ``.`` between scopes)."""

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def walk_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` over a function body WITHOUT descending into nested
    function/class definitions (those are visited as their own
    scopes)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def is_docstring_const(parent: ast.AST, node: ast.AST) -> bool:
    body = getattr(parent, "body", None)
    return (isinstance(parent, (ast.Module, ast.ClassDef, ast.FunctionDef,
                                ast.AsyncFunctionDef))
            and bool(body)
            and isinstance(body[0], ast.Expr)
            and body[0].value is node)


def string_constants(tree: ast.AST, skip_docstrings: bool = True
                     ) -> Iterator[Tuple[str, int]]:
    """Yield ``(value, lineno)`` for every string literal, optionally
    skipping docstrings.  Implicitly-concatenated adjacent literals are
    one ``ast.Constant`` already."""
    doc_ids = set()
    if skip_docstrings:
        for node in ast.walk(tree):
            body = getattr(node, "body", None)
            if (isinstance(node, (ast.Module, ast.ClassDef,
                                  ast.FunctionDef, ast.AsyncFunctionDef))
                    and body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                doc_ids.add(id(body[0].value))
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if id(node) in doc_ids:
                continue
            yield node.value, node.lineno


def const_int_tuple(node: ast.expr) -> Optional[Tuple[int, ...]]:
    """Evaluate a literal int / tuple-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) \
                    and isinstance(elt.value, int) \
                    and not isinstance(elt.value, bool):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


def call_keywords(call: ast.Call) -> Dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}
