"""donation-hazard: a local reused after being passed at a donated
position of a ``donate_argnums`` dispatch.

XLA donation invalidates the argument buffer the moment the call is
issued; reading it afterwards returns garbage (or deadlocks on TPU).
The sanctioned pattern rebinds the name from the call's result::

    cache, out = self._decode_jit(cache, ...)   # ok: rebound
    out = self._decode_jit(cache, ...)
    use(cache)                                  # HAZARD

Detection is module-local: assignments of ``jax.jit(...,
donate_argnums=...)`` (optionally already wrapped in
``instrument_jit``) register the target name/attribute and its donated
positions; any later call through that name is a dispatch site.  After
a dispatch, the first event on a donated bare-name argument must be a
store — a load is flagged.  Attribute arguments (``self._cache``) are
tracked the same way by their final component.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import _astutil
from .core import Checker, FileContext, Finding


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """``jax.jit(..., donate_argnums=...)`` -> positions, else None.
    Unwraps an ``instrument_jit(...)`` wrapper around the jit call."""
    tail = _astutil.attr_tail(call.func)
    if tail == "instrument_jit":
        for arg in call.args:
            if isinstance(arg, ast.Call):
                pos = _donated_positions(arg)
                if pos:
                    return pos
        return None
    if tail != "jit":
        return None
    kws = _astutil.call_keywords(call)
    if "donate_argnums" not in kws:
        return None
    return _astutil.const_int_tuple(kws["donate_argnums"])


class DonationChecker(Checker):
    name = "donation-hazard"

    def check_file(self, ctx: FileContext) -> List[Finding]:
        donors: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            pos = _donated_positions(node.value)
            if not pos:
                continue
            for tgt in node.targets:
                tail = _astutil.attr_tail(tgt)
                if tail:
                    donors[tail] = pos
        # functions RETURNING a donated jit are donors under their name
        for _, fn in _astutil.iter_functions(ctx.tree):
            for n in _astutil.walk_shallow(fn):
                if isinstance(n, ast.Return) \
                        and isinstance(n.value, ast.Call):
                    pos = _donated_positions(n.value)
                    if pos:
                        donors[fn.name] = pos
        if not donors:
            return []

        findings: List[Finding] = []
        for qual, fn in _astutil.iter_functions(ctx.tree):
            findings.extend(self._scan(ctx, qual, fn, donors))
        return findings

    def _scan(self, ctx: FileContext, qual: str, fn: ast.AST,
              donors: Dict[str, Tuple[int, ...]]) -> List[Finding]:
        events: List[Tuple[Tuple[int, int], str, str]] = []
        calls: List[Tuple[Tuple[int, int], ast.Call,
                          Tuple[int, ...]]] = []
        for n in _astutil.walk_shallow(fn):
            if isinstance(n, ast.Name):
                kind = "store" if isinstance(n.ctx, (ast.Store, ast.Del)) \
                    else "load"
                events.append(((n.lineno, n.col_offset), kind, n.id))
            elif isinstance(n, ast.Attribute):
                kind = "store" if isinstance(n.ctx, (ast.Store, ast.Del)) \
                    else "load"
                events.append(((n.lineno, n.col_offset), kind,
                               "." + n.attr))
            elif isinstance(n, ast.Call):
                tail = _astutil.attr_tail(n.func)
                if tail in donors:
                    calls.append(((n.end_lineno or n.lineno,
                                   n.end_col_offset or 0),
                                  n, donors[tail]))
        if not calls:
            return []
        events.sort(key=lambda e: e[0])

        findings: List[Finding] = []
        for end_pos, call, positions in calls:
            for p in positions:
                if p >= len(call.args):
                    continue
                arg = call.args[p]
                if isinstance(arg, ast.Name):
                    key = arg.id
                elif isinstance(arg, ast.Attribute):
                    key = "." + arg.attr
                else:
                    continue
                # the sanctioned rebind ``c, y = f(c, ...)`` stores the
                # target textually BEFORE the call's end; a store
                # anywhere on the dispatch statement's lines counts
                if any(kind == "store" and name == key
                       and call.lineno <= pos_key[0] <= end_pos[0]
                       for pos_key, kind, name in events):
                    continue
                for pos_key, kind, name in events:
                    if pos_key <= end_pos or name != key:
                        continue
                    if kind == "store":
                        break           # rebound: donation-correct
                    findings.append(Finding(
                        self.name, ctx.relpath, pos_key[0],
                        f"`{key.lstrip('.')}` used after being donated "
                        f"(arg {p} of the dispatch at line "
                        f"{call.lineno}) in `{qual}` — the buffer is "
                        "dead after the call; rebind it from the "
                        "result first"))
                    break
        return findings
