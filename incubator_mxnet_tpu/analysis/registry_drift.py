"""registry-drift: code and docs must agree on the public registries.

Three registries are cross-checked **in both directions** against their
documentation:

* **env vars** — ``MXNET_*`` string literals in the package vs the
  tables in ``docs/env_var.md``.  A name used in code but absent from
  the doc is undocumented surface; a doc row with no code reference is
  a stale promise.
* **metrics** — first-argument literals of
  ``registry.counter/gauge/histogram(...)`` calls (the only way a
  ``mxtpu_*`` series is born) vs the metric tables in
  ``docs/observability.md``.
* **fault sites** — literals reaching ``fault.inject(...)`` /
  ``fault.take(...)``, ``site=``/``*_site=`` keywords and defaults, and
  ``*_SITE`` constants, vs the site table in ``docs/robustness.md``.

This is a ``finalize``-only checker: it needs the whole file set.  When
the docs tree is absent (fixture runs, vendored copies) it is silent.
Doc-side findings point at the table row; code-side findings point at
the first code occurrence.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from . import _astutil
from .core import Checker, FileContext, Finding

_ENV_RE = re.compile(r"\bMXNET_[A-Z][A-Z0-9_]*\b")
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_SITE_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_.]*$")


def _doc_table_cells(lines: Sequence[str]) -> List[Tuple[str, int]]:
    """First-column cell text of every markdown table row (1-based
    line numbers); header/separator rows included — callers filter."""
    out: List[Tuple[str, int]] = []
    for i, line in enumerate(lines, start=1):
        s = line.strip()
        if not s.startswith("|"):
            continue
        cells = [c.strip() for c in s.strip("|").split("|")]
        if cells and cells[0] and not set(cells[0]) <= {"-", ":", " "}:
            out.append((cells[0], i))
    return out


def _strip_md(cell: str) -> str:
    return cell.replace("`", "").strip()


class RegistryDriftChecker(Checker):
    name = "registry-drift"

    def finalize(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        if not ctxs:
            return []
        root = ctxs[0].root
        docs = os.path.join(root, "docs")
        if not os.path.isdir(docs):
            return []
        findings: List[Finding] = []
        findings.extend(self._check_env(ctxs, root))
        findings.extend(self._check_metrics(ctxs, root))
        findings.extend(self._check_faults(ctxs, root))
        return findings

    @staticmethod
    def _read_doc(root: str, rel: str) -> Optional[List[str]]:
        path = os.path.join(root, rel)
        try:
            with open(path, "r", encoding="utf-8") as f:
                return f.read().splitlines()
        except OSError:
            return None

    # -- env vars -------------------------------------------------------
    def _check_env(self, ctxs, root) -> List[Finding]:
        doc_rel = "docs/env_var.md"
        lines = self._read_doc(root, doc_rel)
        if lines is None:
            return []
        # code side: full MXNET_* names in string literals (fragments
        # used for prefix-building end with "_" and are skipped)
        code: Dict[str, Tuple[str, int]] = {}
        for ctx in ctxs:
            for value, lineno in _astutil.string_constants(ctx.tree):
                for m in _ENV_RE.finditer(value):
                    name = m.group(0)
                    if name.endswith("_"):
                        continue
                    code.setdefault(name, (ctx.relpath, lineno))
        # doc side: table rows whose first cell is an env var name
        doc: Dict[str, int] = {}
        for cell, lineno in _doc_table_cells(lines):
            for m in _ENV_RE.finditer(_strip_md(cell)):
                doc.setdefault(m.group(0), lineno)

        findings: List[Finding] = []
        for name in sorted(code):
            if name not in doc:
                path, lineno = code[name]
                findings.append(Finding(
                    self.name, path, lineno,
                    f"env var `{name}` read in code but missing from "
                    f"{doc_rel} — undocumented public surface"))
        for name in sorted(doc):
            if name not in code:
                findings.append(Finding(
                    self.name, doc_rel, doc[name],
                    f"env var `{name}` documented in {doc_rel} but "
                    "never read by the code — stale row"))
        return findings

    # -- metrics --------------------------------------------------------
    def _check_metrics(self, ctxs, root) -> List[Finding]:
        doc_rel = "docs/observability.md"
        lines = self._read_doc(root, doc_rel)
        if lines is None:
            return []
        code: Dict[str, Tuple[str, int]] = {}
        for ctx in ctxs:
            # registration idioms: registry.counter(...) /
            # _telemetry.gauge(...), plus local aliases
            # ``c = registry.counter`` used as ``c("mxtpu_...", ...)``
            aliases = set()
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    pairs = [(tgt, node.value)]
                    if isinstance(tgt, ast.Tuple) \
                            and isinstance(node.value, ast.Tuple) \
                            and len(tgt.elts) == len(node.value.elts):
                        pairs = list(zip(tgt.elts, node.value.elts))
                    for t, v in pairs:
                        if isinstance(t, ast.Name) \
                                and isinstance(v, ast.Attribute) \
                                and v.attr in _METRIC_FACTORIES:
                            aliases.add(t.id)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                is_factory = (isinstance(fn, ast.Attribute)
                              and fn.attr in _METRIC_FACTORIES) \
                    or (isinstance(fn, ast.Name) and fn.id in aliases)
                if not is_factory:
                    continue
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                    if name.startswith("mxtpu_"):
                        code.setdefault(name,
                                        (ctx.relpath, node.lineno))
        doc: Dict[str, int] = {}
        metric_re = re.compile(r"\bmxtpu_[a-z0-9_]+\b")
        for cell, lineno in _doc_table_cells(lines):
            text = _strip_md(cell).split("{")[0].strip()
            m = metric_re.fullmatch(text)
            if m:
                doc.setdefault(m.group(0), lineno)
        doc_text = "\n".join(lines)

        findings: List[Finding] = []
        for name in sorted(code):
            # code->doc: a mention anywhere in the doc is enough
            if name not in doc and name not in doc_text:
                path, lineno = code[name]
                findings.append(Finding(
                    self.name, path, lineno,
                    f"metric `{name}` registered in code but absent "
                    f"from {doc_rel} — undocumented series"))
        for name in sorted(doc):
            if name not in code:
                findings.append(Finding(
                    self.name, doc_rel, doc[name],
                    f"metric `{name}` documented in {doc_rel} but never "
                    "registered — stale row"))
        return findings

    # -- fault sites ----------------------------------------------------
    def _check_faults(self, ctxs, root) -> List[Finding]:
        doc_rel = "docs/robustness.md"
        lines = self._read_doc(root, doc_rel)
        if lines is None:
            return []
        code: Dict[str, Tuple[str, int]] = {}

        def add(value, relpath, lineno):
            if isinstance(value, str) and _SITE_RE.match(value):
                code.setdefault(value, (relpath, lineno))

        for ctx in ctxs:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call):
                    tail = _astutil.attr_tail(node.func)
                    if tail in ("inject", "take") and node.args \
                            and isinstance(node.args[0], ast.Constant):
                        add(node.args[0].value, ctx.relpath,
                            node.lineno)
                    for kw in node.keywords:
                        if kw.arg and (kw.arg == "site"
                                       or kw.arg.endswith("_site")) \
                                and isinstance(kw.value, ast.Constant):
                            add(kw.value.value, ctx.relpath,
                                kw.value.lineno)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    args = node.args
                    defaults = list(args.defaults)
                    pos = args.posonlyargs + args.args
                    for a, d in zip(pos[len(pos) - len(defaults):],
                                    defaults):
                        if a.arg.endswith("_site") \
                                and isinstance(d, ast.Constant):
                            add(d.value, ctx.relpath, d.lineno)
                    for a, d in zip(args.kwonlyargs, args.kw_defaults):
                        if a.arg.endswith("_site") \
                                and isinstance(d, ast.Constant):
                            add(d.value, ctx.relpath, d.lineno)
                elif isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Constant):
                    for tgt in node.targets:
                        tail = _astutil.attr_tail(tgt)
                        if tail and tail.endswith("_SITE"):
                            add(node.value.value, ctx.relpath,
                                node.lineno)

        doc: Dict[str, int] = {}
        for cell, lineno in _doc_table_cells(lines):
            text = _strip_md(cell)
            if _SITE_RE.match(text):
                doc.setdefault(text, lineno)
        doc_text = "\n".join(lines)

        findings: List[Finding] = []
        for name in sorted(code):
            if name not in doc and f"`{name}`" not in doc_text:
                path, lineno = code[name]
                findings.append(Finding(
                    self.name, path, lineno,
                    f"fault site `{name}` instrumented in code but "
                    f"absent from {doc_rel} — operators can't target "
                    "it"))
        for name in sorted(doc):
            if name not in code:
                findings.append(Finding(
                    self.name, doc_rel, doc[name],
                    f"fault site `{name}` documented in {doc_rel} but "
                    "never instrumented — stale row"))
        return findings
