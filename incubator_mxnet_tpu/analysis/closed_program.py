"""closed-program-set: every compiled program must be registered.

Two rules keep the set of compiled programs closed and observable:

1. **Raw ``jax.jit`` must route through ``instrument_jit``** (the
   program registry feeding the compile-cache, XLA-cost and span
   planes).  Accepted shapes:

   * ``instrument_jit("site", jax.jit(...))`` — direct wrap;
   * ``self._x = jax.jit(...)`` later passed to ``instrument_jit(...,
     self._x)`` anywhere in the module (the engine's
     build-then-wrap pattern).

   Anything else is an unregistered program: its compiles, cache
   misses and FLOPs are invisible to telemetry.

2. **No traced-value Python branching in jitted bodies** — a function
   handed to ``jax.jit``/``lax.scan`` must not ``if``/``while`` on its
   traced parameters (that forks the program set per value; use
   ``lax.cond``/``jnp.where``).  Shape/dtype/ndim/len/isinstance
   inspection and ``is None`` checks are static and allowed.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from . import _astutil
from .core import Checker, FileContext, Finding


def _is_jax_jit(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "jit":
        return _astutil.attr_parts(fn)[0:1] == ["jax"]
    return isinstance(fn, ast.Name) and fn.id == "jit"


class ClosedProgramChecker(Checker):
    name = "closed-program-set"

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_registration(ctx))
        findings.extend(self._check_traced_branching(ctx))
        return findings

    # -- rule 1: instrument_jit registration ----------------------------
    def _check_registration(self, ctx: FileContext) -> List[Finding]:
        # names/attrs that appear as instrument_jit arguments anywhere
        wrapped_names: Set[str] = set()
        wrapped_call_ids: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or _astutil.attr_tail(node.func) != "instrument_jit":
                continue
            for arg in ast.walk(node):
                if isinstance(arg, ast.Call) and _is_jax_jit(arg):
                    wrapped_call_ids.add(id(arg))
                tail = _astutil.attr_tail(arg) \
                    if isinstance(arg, (ast.Name, ast.Attribute)) else None
                if tail:
                    wrapped_names.add(tail)

        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_jax_jit(node):
                continue
            if id(node) in wrapped_call_ids:
                continue
            if self._assigned_name(ctx, node) in wrapped_names:
                continue
            findings.append(Finding(
                self.name, ctx.relpath, node.lineno,
                "raw `jax.jit` not routed through "
                "`telemetry.instrument_jit` — the program is invisible "
                "to the compile-cache/cost/span planes"))
        return findings

    @staticmethod
    def _assigned_name(ctx: FileContext,
                       call: ast.Call) -> Optional[str]:
        """Name/attr this jit call is assigned to, if any."""
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and node.value is call:
                for tgt in node.targets:
                    tail = _astutil.attr_tail(tgt)
                    if tail:
                        return tail
        return None

    # -- rule 2: traced-value branching ---------------------------------
    def _check_traced_branching(self, ctx: FileContext) -> List[Finding]:
        funcs = dict(_astutil.iter_functions(ctx.tree))
        by_bare: Dict[str, List[ast.AST]] = {}
        for _, node in funcs.items():
            by_bare.setdefault(node.name, []).append(node)

        jitted: List[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _astutil.attr_tail(node.func)
            if not (_is_jax_jit(node) or tail in ("scan", "while_loop",
                                                  "fori_loop")):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in by_bare:
                    jitted.extend(by_bare[arg.id])

        findings: List[Finding] = []
        seen: Set[int] = set()
        for fn in jitted:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            taint = {a.arg for a in fn.args.args
                     + fn.args.posonlyargs + fn.args.kwonlyargs
                     if a.arg != "self"}
            if fn.args.vararg:
                taint.add(fn.args.vararg.arg)
            for n in _astutil.walk_shallow(fn):
                if not isinstance(n, (ast.If, ast.While)):
                    continue
                bad = self._traced_names_in_test(n.test, taint)
                if bad:
                    findings.append(Finding(
                        self.name, ctx.relpath, n.lineno,
                        f"Python `{type(n).__name__.lower()}` on traced "
                        f"value(s) {sorted(bad)} inside jitted "
                        f"`{fn.name}` — each value forks a new compiled "
                        "program; use lax.cond/jnp.where"))
        return findings

    @staticmethod
    def _traced_names_in_test(test: ast.expr,
                              taint: Set[str]) -> Set[str]:
        static_ids: Set[int] = set()
        for n in ast.walk(test):
            # x.shape / x.dtype / x.ndim / x.size are static under trace
            if isinstance(n, ast.Attribute) \
                    and n.attr in ("shape", "dtype", "ndim", "size") \
                    and isinstance(n.value, ast.Name):
                static_ids.add(id(n.value))
            # len(x) / isinstance(x, T) are static
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in ("len", "isinstance"):
                for a in ast.walk(n):
                    if isinstance(a, ast.Name):
                        static_ids.add(id(a))
            # `x is None` / `x is not None` is an identity check
            elif isinstance(n, ast.Compare) \
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in n.ops) \
                    and all(isinstance(c, ast.Constant)
                            for c in n.comparators):
                for a in ast.walk(n):
                    if isinstance(a, ast.Name):
                        static_ids.add(id(a))
        bad: Set[str] = set()
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and n.id in taint \
                    and id(n) not in static_ids:
                bad.add(n.id)
        return bad
