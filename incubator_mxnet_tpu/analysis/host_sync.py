"""host-sync-in-hot-path: device->host synchronization reachable from an
annotated hot path.

Hot-path roots are functions carrying ``# mxtpu-lint: hot-path`` on (or
directly above) their ``def`` line — the serving decode/verify/burst
loops (``_decode_once``, ``_decode_burst_once``, ``_spec_once``),
``FusedUpdater.step``, ``CompiledLoop`` chunk dispatch.  Reachability is
the same-module call graph: a reference (call or function-as-value, e.g.
a ``lax.scan`` body) to another function defined in the module pulls it
into the hot set, and a nested ``def`` inside a hot function is hot.

Sync indicators flagged inside hot functions:

* ``.item()`` / ``.block_until_ready()``
* ``jax.device_get(...)``
* ``np.asarray(...)`` / ``np.array(...)`` (any numpy alias)
* ``float(x)`` / ``int(x)`` of a bare name or subscript (the classic
  scalar pull; ``float(cfg.attr)`` of config attributes is not flagged)

Intentional sync boundaries (a streaming token emit, a returned host
scalar) carry an inline pragma or a baseline entry with a reason.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from . import _astutil
from .core import Checker, FileContext, Finding

NP_ALIASES = {"np", "_np", "numpy", "onp"}
NP_SYNC_ATTRS = {"asarray", "array"}


class HostSyncChecker(Checker):
    name = "host-sync-in-hot-path"

    def check_file(self, ctx: FileContext) -> List[Finding]:
        funcs = dict(_astutil.iter_functions(ctx.tree))
        if not funcs:
            return []
        by_bare: Dict[str, List[str]] = {}
        for q, node in funcs.items():
            by_bare.setdefault(node.name, []).append(q)

        roots = [q for q, node in funcs.items()
                 if self._is_marked(ctx, node)]
        if not roots:
            return []

        hot: Set[str] = set()
        stack = list(roots)
        while stack:
            q = stack.pop()
            if q in hot:
                continue
            hot.add(q)
            node = funcs[q]
            # nested defs execute in the hot function's dynamic extent
            for sub_q, sub in funcs.items():
                if sub_q.startswith(q + ".") \
                        and sub_q.count(".") == q.count(".") + 1:
                    stack.append(sub_q)
            # any reference to a module function's bare name is an edge
            for n in _astutil.walk_shallow(node):
                bare = None
                if isinstance(n, ast.Name) \
                        and isinstance(n.ctx, ast.Load):
                    bare = n.id
                elif isinstance(n, ast.Attribute):
                    bare = n.attr
                if bare and bare in by_bare:
                    stack.extend(by_bare[bare])

        findings: List[Finding] = []
        for q in sorted(hot):
            findings.extend(self._scan(ctx, q, funcs[q]))
        return findings

    @staticmethod
    def _is_marked(ctx: FileContext, node: ast.AST) -> bool:
        cand = {node.lineno, node.lineno - 1}
        for dec in getattr(node, "decorator_list", ()):
            cand.add(dec.lineno - 1)
        return bool(cand & ctx.hot_lines)

    def _scan(self, ctx: FileContext, qual: str,
              node: ast.AST) -> List[Finding]:
        out: List[Finding] = []

        def flag(n: ast.AST, what: str):
            out.append(Finding(
                self.name, ctx.relpath, n.lineno,
                f"{what} in hot path `{qual}` forces a device->host "
                "sync"))

        for n in _astutil.walk_shallow(node):
            if not isinstance(n, ast.Call):
                continue
            fn = n.func
            if isinstance(fn, ast.Attribute):
                if fn.attr == "item" and not n.args and not n.keywords:
                    flag(n, "`.item()`")
                elif fn.attr == "block_until_ready":
                    flag(n, "`.block_until_ready()`")
                elif fn.attr == "device_get":
                    flag(n, "`jax.device_get`")
                elif fn.attr in NP_SYNC_ATTRS \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id in NP_ALIASES:
                    flag(n, f"`{fn.value.id}.{fn.attr}`")
            elif isinstance(fn, ast.Name):
                if fn.id == "device_get":
                    flag(n, "`device_get`")
                elif fn.id in ("float", "int") and len(n.args) == 1 \
                        and isinstance(n.args[0],
                                       (ast.Name, ast.Subscript)):
                    flag(n, f"`{fn.id}()` of a device value")
        return out
