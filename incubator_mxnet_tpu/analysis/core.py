"""mxtpu-lint core: file contexts, pragmas, baseline, runner.

The checker framework is stdlib-only (``ast`` + ``re``) so the lint can
run in CI without importing jax or the framework itself.  Each checker
sees a parsed :class:`FileContext` per file (walked in parallel) and may
also implement a whole-project ``finalize`` pass (lock-order pairing,
code<->docs registry drift).

Suppression planes, outermost first:

* ``# mxtpu-lint: disable=<check>[,<check>...]`` on the offending line or
  the line above (``disable=all`` silences every check);
* a committed baseline file (``.mxtpu-lint-baseline.json``) whose entries
  carry a one-line justification.  Baseline fingerprints are
  ``(check, path, normalized source line, occurrence index)`` so they
  survive unrelated line-number churn.

``# mxtpu-lint: hot-path`` on (or directly above) a ``def`` marks a
host-sync-checker root; see ``analysis/host_sync.py``.
"""
from __future__ import annotations

import ast
import concurrent.futures
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

PRAGMA_RE = re.compile(
    r"#\s*mxtpu-lint:\s*(disable|hot-path)\s*(?:=\s*([A-Za-z0-9_,\- ]+))?")

BASELINE_FILENAME = ".mxtpu-lint-baseline.json"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured finding: ``<check>: <path>:<line>: <message>``."""
    check: str
    path: str           # repo-root-relative, forward slashes
    line: int
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.check}] {self.message}"

    def as_dict(self) -> dict:
        return {"check": self.check, "path": self.path,
                "line": self.line, "message": self.message}


class FileContext:
    """One parsed source file: tree, lines, pragma maps."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path
        self.relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        # line -> set of disabled check names ("all" disables everything)
        self.disabled: Dict[int, Set[str]] = {}
        # lines carrying a hot-path marker (the marker line itself)
        self.hot_lines: Set[int] = set()
        for i, text in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(text)
            if not m:
                continue
            kind, arg = m.group(1), m.group(2)
            if kind == "hot-path":
                self.hot_lines.add(i)
            else:
                checks = {c.strip() for c in (arg or "all").split(",")
                          if c.strip()}
                # a pragma suppresses its own line and the line below,
                # so it can ride the statement or sit just above it
                for ln in (i, i + 1):
                    self.disabled.setdefault(ln, set()).update(checks)

    def is_disabled(self, check: str, line: int) -> bool:
        d = self.disabled.get(line)
        return bool(d) and (check in d or "all" in d)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


class Checker:
    """Base class: implement ``check_file`` and/or ``finalize``."""

    name = ""

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return []

    def finalize(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        return []


# -- baseline ---------------------------------------------------------------

def _fingerprint(check: str, path: str, text: str, occ: int) -> Tuple:
    return (check, path, text, occ)


class Baseline:
    """Committed suppression file.  Entries are JSON objects with
    ``check``/``path``/``text`` (the normalized source line)/``occ``
    (0-based index among same-text findings in that file) and a
    mandatory one-line ``reason``."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries: List[dict] = entries or []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return cls(list(data.get("entries", [])))

    def save(self, path: str) -> None:
        payload = {
            "comment": "mxtpu-lint baseline; every entry carries a "
                       "one-line justification. Regenerate with "
                       "mxtpu-lint --write-baseline (then fill in "
                       "reasons).",
            "entries": sorted(
                self.entries,
                key=lambda e: (e["path"], e["check"], e["text"],
                               e.get("occ", 0))),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")

    def _index(self) -> Set[Tuple]:
        return {_fingerprint(e["check"], e["path"], e["text"],
                             int(e.get("occ", 0)))
                for e in self.entries}

    def filter(self, findings: Sequence[Finding],
               line_text) -> Tuple[List[Finding], List[Finding]]:
        """Split ``findings`` into (unsuppressed, baselined).
        ``line_text(finding)`` must return the finding's source line."""
        index = self._index()
        occ_seen: Dict[Tuple, int] = {}
        keep: List[Finding] = []
        suppressed: List[Finding] = []
        for f in findings:
            text = line_text(f)
            key = (f.check, f.path, text)
            occ = occ_seen.get(key, 0)
            occ_seen[key] = occ + 1
            if _fingerprint(f.check, f.path, text, occ) in index:
                suppressed.append(f)
            else:
                keep.append(f)
        return keep, suppressed

    @classmethod
    def from_findings(cls, findings: Sequence[Finding], line_text,
                      reason: str = "TODO: justify") -> "Baseline":
        occ_seen: Dict[Tuple, int] = {}
        entries = []
        for f in findings:
            text = line_text(f)
            key = (f.check, f.path, text)
            occ = occ_seen.get(key, 0)
            occ_seen[key] = occ + 1
            entries.append({"check": f.check, "path": f.path,
                            "text": text, "occ": occ, "reason": reason})
        return cls(entries)


# -- runner -----------------------------------------------------------------

def collect_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(set(out))


def find_root(start: str) -> str:
    """Walk up from ``start`` to the repo root (the directory holding
    ``docs/`` or ``.git``); fall back to ``start``'s directory."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    cur = d
    while True:
        if os.path.isdir(os.path.join(cur, "docs")) \
                or os.path.isdir(os.path.join(cur, ".git")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return d
        cur = parent


def build_contexts(files: Sequence[str], root: str,
                   jobs: Optional[int] = None) -> List[FileContext]:
    """Parse every file, in parallel (per-file walk)."""
    if not files:
        return []
    jobs = jobs or min(8, (os.cpu_count() or 2))
    with concurrent.futures.ThreadPoolExecutor(max_workers=jobs) as ex:
        return list(ex.map(lambda p: FileContext(root, p), files))


def default_checkers() -> List[Checker]:
    from .host_sync import HostSyncChecker
    from .donation import DonationChecker
    from .closed_program import ClosedProgramChecker
    from .lock_discipline import LockDisciplineChecker
    from .registry_drift import RegistryDriftChecker
    return [HostSyncChecker(), DonationChecker(), ClosedProgramChecker(),
            LockDisciplineChecker(), RegistryDriftChecker()]


def run_checks(paths: Sequence[str],
               checks: Optional[Sequence[str]] = None,
               root: Optional[str] = None,
               jobs: Optional[int] = None) -> List[Finding]:
    """Run the (selected) checkers over ``paths``; returns findings with
    inline pragmas already applied (baseline filtering is the CLI's
    job)."""
    files = collect_files(paths)
    if root is None:
        root = find_root(files[0]) if files else os.getcwd()
    ctxs = build_contexts(files, root, jobs=jobs)
    checkers = default_checkers()
    if checks:
        wanted = set(checks)
        unknown = wanted - {c.name for c in checkers}
        if unknown:
            raise ValueError(f"unknown check(s): {sorted(unknown)}")
        checkers = [c for c in checkers if c.name in wanted]
    findings: List[Finding] = []
    jobs = jobs or min(8, (os.cpu_count() or 2))
    with concurrent.futures.ThreadPoolExecutor(max_workers=jobs) as ex:
        for per_file in ex.map(
                lambda ctx: [f for c in checkers
                             for f in c.check_file(ctx)], ctxs):
            findings.extend(per_file)
    for c in checkers:
        findings.extend(c.finalize(ctxs))
    by_path = {ctx.relpath: ctx for ctx in ctxs}
    kept = []
    for f in findings:
        ctx = by_path.get(f.path)
        if ctx is not None and ctx.is_disabled(f.check, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.check, f.message))
    return kept


def line_text_lookup(root: str):
    """Return ``line_text(finding)`` backed by a tiny file cache — used
    to fingerprint findings against the baseline (doc findings
    included)."""
    cache: Dict[str, List[str]] = {}

    def lookup(f: Finding) -> str:
        lines = cache.get(f.path)
        if lines is None:
            try:
                with open(os.path.join(root, f.path), "r",
                          encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
            except OSError:
                lines = []
            cache[f.path] = lines
        if 1 <= f.line <= len(lines):
            return lines[f.line - 1].strip()
        return ""

    return lookup
