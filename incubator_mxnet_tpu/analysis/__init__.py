"""mxtpu-lint: JAX/TPU-aware static analysis for this repo.

Stdlib-only (``ast``-based) so it runs in CI without importing jax or
the package under analysis.  See ``docs/static_analysis.md``.
"""
from .core import (BASELINE_FILENAME, Baseline, Checker, FileContext,
                   Finding, collect_files, default_checkers, find_root,
                   run_checks)

__all__ = [
    "BASELINE_FILENAME", "Baseline", "Checker", "FileContext", "Finding",
    "collect_files", "default_checkers", "find_root", "run_checks",
]
