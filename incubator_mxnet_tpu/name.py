"""NameManager / Prefix (reference: python/mxnet/name.py) — scoped control
of the automatic names the symbolic API generates."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def current():
    st = _stack()
    return st[-1] if st else None


class NameManager:
    """``with NameManager():`` — names auto-generate as ``{hint}{n}`` with
    counters scoped to this manager (reference: NameManager)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


class Prefix(NameManager):
    """``with Prefix('resnet_'):`` — auto names gain the prefix
    (reference: name.Prefix)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint) \
            if name is None else name
