"""Unified runtime telemetry: multi-subscriber event bus + cross-layer
metrics registry (reference analog: the reference's profiler counters +
``MXNET_PROFILER_*`` plane, generalized into an always-on, low-overhead
observability spine for the whole runtime).

Two cooperating pieces:

* **Event bus** — named :class:`Topic` objects that any number of
  subscribers can attach to concurrently.  This replaces the single-slot
  ``_op_observer`` hook in ``ndarray/ndarray.py``: the profiler and the
  telemetry collector (and any user code) can observe the same op stream
  at once.  Publishing to a topic with no subscribers is a single list
  truthiness check — the instrumented hot paths stay effectively free
  when nothing is listening.
* **Metrics registry** — process-wide :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` (bounded reservoir with p50/p95/p99/max), exported
  three ways: :func:`render_prometheus` (text exposition format),
  :func:`snapshot` (JSON-ready dict, merged into ``bench.py``'s output
  line), and counter samples woven into the profiler's chrome-trace
  ``dump()`` as ``ph:"C"`` events.

Instrumented layers (see docs/observability.md):

* eager op dispatch — op counts per name, sync-block counts, host<->device
  transfer bytes (``ndarray/ndarray.py``)
* JIT/compile — compile count, cache hit/miss, compile seconds
  (``executor.py``, ``gluon/block.py`` _CachedGraph, ``parallel/spmd.py``,
  ``kvstore.py`` mesh reducer) via :func:`instrument_jit`
* kvstore — push/pull/pushpull calls, bytes, latency histograms
* gluon trainer — step/update timing
* dataloader — per-batch fetch-wait time
* device memory — gauges sampled from ``jax.live_arrays()`` /
  ``device.memory_stats()`` at export time
* resilience — injected faults, retries/give-ups, skipped steps and
  dataloader fallbacks (``fault.py``; FAULT topic, ``mxtpu_retries`` /
  ``mxtpu_giveups`` / ``mxtpu_skipped_steps`` counters)

Three further planes layered on the same spine (this file + satellites):

* **Span tracer** — hierarchical :class:`Span` trees with thread-local
  context propagation (``with trace_span("trainer.step"): ...``,
  ``@traced``).  The training path is instrumented end-to-end (trainer
  step → spmd dispatch → kvstore push/pull → dataloader fetch →
  executor/cached-op compile+dispatch), and finished spans render as
  nested ``ph:"X"`` events in the profiler's chrome-trace ``dump()`` —
  a proper flame graph next to the ``ph:"C"`` counter tracks.
* **Cost-analysis accountant** — :func:`instrument_jit` captures XLA's
  ``jit(...).lower(...).compile().cost_analysis()`` flops/bytes once per
  compiled executable and publishes them per call on the ``XLA_COST``
  topic; the collector accumulates them and, at each trainer-step
  boundary, computes **MFU** = step-window FLOPs / wall seconds /
  :func:`device_peak_flops` (TPU generation table, CPU estimate) into
  the ``mxtpu_mfu`` gauge and the ``mxtpu_step_seconds`` histogram.
* **HTTP exporter** (``telemetry_http.py``) — stdlib ``http.server``
  background thread serving ``/metrics`` (Prometheus text), ``/healthz``
  and ``/trace`` (live span tree as JSON, bounded by ``?limit=`` /
  ``?since=`` and searchable by ``?request_id=``).
* **Flight recorder** (``telemetry_ring.py``) — a lock-cheap bounded
  ring continuously recording recent FAULT events, finished spans and
  metric deltas; it auto-dumps a postmortem JSON on watchdog restarts,
  breaker trips, non-finite-guard skips, SIGTERM drain and worker
  crashes.  :func:`start`/:func:`stop` hold one reference on it.

Control plane: ``MXNET_TELEMETRY=1`` starts collection at import;
``MXNET_TELEMETRY_DUMP=/path`` additionally writes a dump at process exit
(Prometheus text if the path ends in ``.prom``/``.txt``, JSON otherwise);
``MXNET_TELEMETRY_PORT=<port>`` starts collection AND the HTTP exporter.
The ``mxtpu-stats`` console script (``_cli.py``) runs any script under
telemetry and prints the dump (``--serve`` adds the live endpoint).
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .base import MXNetError, getenv, getenv_bool

__all__ = [
    "Topic", "EventBus", "bus",
    "OP_DISPATCH", "OP_TIMED", "SYNC", "TRANSFER", "COMPILE", "KVSTORE",
    "TRAINER", "DATALOADER", "SPAN", "XLA_COST", "FAULT", "HEALTH",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "counter", "gauge", "histogram",
    "merge_states", "render_prometheus_state",
    "Span", "Tracer", "tracer", "trace_span", "traced", "current_span",
    "new_request_id",
    "start", "stop", "enabled", "reset",
    "snapshot", "render_prometheus", "counters_flat", "dump",
    "instrument_jit", "sample_device_memory",
    "dispatch_ledger", "reset_dispatch_ledger",
    "StepHealthRing", "health_ring",
    "TPU_PEAK_FLOPS", "tpu_peak_flops", "cpu_peak_flops",
    "device_peak_flops",
]


# ---------------------------------------------------------------------------
# Event bus
# ---------------------------------------------------------------------------
class Topic:
    """A named event stream.  ``subscribers`` is copy-on-write: mutations
    build a NEW list under ``_lock`` and swap it in atomically, so
    ``publish`` fans out over a stable snapshot without ever taking the
    lock on the hot path — a subscribe/unsubscribe racing a concurrent
    publish can neither drop another subscriber's registration nor
    deliver an event to the same subscriber twice.  A subscriber that
    raises is counted in ``errors`` and skipped — an observer must never
    take the observed program down.

    ``forcing`` counts non-passive subscribers.  Publishers whose
    instrumentation is expensive (OP_TIMED forces a per-op device sync)
    key the decision to pay that cost on ``forcing``, so a passive
    listener (the telemetry collector) can ride along whenever an active
    one (the profiler) turns the firehose on, without turning it on
    itself."""

    __slots__ = ("name", "subscribers", "errors", "last_error", "forcing",
                 "_passive", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.subscribers: List[Callable] = []
        self.errors = 0
        self.last_error: Optional[BaseException] = None
        self.forcing = 0
        self._passive = set()
        self._lock = threading.Lock()

    def subscribe(self, fn: Callable, passive: bool = False) -> Callable:
        with self._lock:
            if fn not in self.subscribers:
                self.subscribers = self.subscribers + [fn]
                if passive:
                    self._passive.add(id(fn))
                else:
                    self.forcing += 1
        return fn

    def unsubscribe(self, fn: Callable) -> None:
        with self._lock:
            # locate by EQUALITY, first occurrence: a re-created bound
            # method (obj.meth is a fresh object per access) must still
            # unsubscribe the one registered earlier — but the passive
            # bookkeeping is keyed on the REGISTERED object's id
            try:
                idx = self.subscribers.index(fn)
            except ValueError:
                return
            registered = self.subscribers[idx]
            fresh = list(self.subscribers)
            del fresh[idx]
            self.subscribers = fresh
            if id(registered) in self._passive:
                self._passive.discard(id(registered))
            else:
                self.forcing -= 1

    def publish(self, *args, **kwargs) -> None:
        # one atomic attribute read = the fan-out snapshot; mutations only
        # ever swap in fresh lists, never modify this one in place
        for fn in self.subscribers:
            try:
                fn(*args, **kwargs)
            except Exception as e:
                self.errors += 1
                self.last_error = e


class EventBus:
    """Registry of Topics; ``topic(name)`` is get-or-create."""

    def __init__(self):
        self._topics: Dict[str, Topic] = {}
        self._lock = threading.Lock()

    def topic(self, name: str) -> Topic:
        t = self._topics.get(name)
        if t is None:
            with self._lock:
                t = self._topics.setdefault(name, Topic(name))
        return t

    def subscribe(self, name: str, fn: Callable,
                  passive: bool = False) -> Callable:
        return self.topic(name).subscribe(fn, passive=passive)

    def unsubscribe(self, name: str, fn: Callable) -> None:
        self.topic(name).unsubscribe(fn)

    def publish(self, name: str, *args, **kwargs) -> None:
        self.topic(name).publish(*args, **kwargs)

    def topics(self) -> List[str]:
        return sorted(self._topics)


bus = EventBus()

# Canonical runtime topics.  Payload contracts:
#   OP_DISPATCH(name)                 — one eager op dispatched (not traced)
#   OP_TIMED(name, seconds)           — op with true synchronous duration;
#                                       subscribing FORCES per-op sync
#   SYNC(kind)                        — a blocking call (wait_to_read/asnumpy)
#   TRANSFER(direction, nbytes)       — "h2d" | "d2h" host<->device bytes
#   COMPILE(where=, event=, seconds=) — event in {"miss","hit"}; miss carries
#                                       trace+compile seconds when measurable
#   KVSTORE(op=, nbytes=, seconds=)   — op in {"push","pull","pushpull"}
#   TRAINER(phase=, seconds=)         — phase in {"step","update"}
#   DATALOADER(seconds=)              — consumer-side batch fetch wait
#   SPAN(span)                        — a finished ROOT span (full subtree)
#   XLA_COST(where=, flops=, nbytes=) — one dispatch of a compiled
#                                       executable, with its cost-analysis
#                                       flops / bytes-accessed
#   FAULT(site=, event=, kind=, ...)  — resilience plane (fault.py): event
#                                       in {"injected","retry","giveup",
#                                       "skipped_step","fallback","anomaly"};
#                                       retry adds attempt=/seconds=
#   HEALTH(kind=, step=, src=, ...)   — health plane (health.py): one
#                                       detected training/decode anomaly;
#                                       kind in {"nonfinite","loss_spike",
#                                       "grad_norm_explosion",
#                                       "nonfinite_generation"}, leaf=
#                                       names the first offending
#                                       parameter by tree path
OP_DISPATCH = bus.topic("op.dispatch")
OP_TIMED = bus.topic("op.timed")
SYNC = bus.topic("op.sync")
TRANSFER = bus.topic("transfer")
COMPILE = bus.topic("compile")
KVSTORE = bus.topic("kvstore")
TRAINER = bus.topic("trainer")
DATALOADER = bus.topic("dataloader")
SPAN = bus.topic("span")
XLA_COST = bus.topic("xla.cost")
FAULT = bus.topic("fault")
HEALTH = bus.topic("health")


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------
def _label_key(labels: dict):
    return tuple(sorted(labels.items()))


def _fmt_num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class Counter:
    """Monotonic counter, optionally broken out by labels
    (``c.inc(3, op="dot")``)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise MXNetError(f"counter {self.name}: negative increment")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    @property
    def value(self) -> float:
        return sum(self._values.values())

    def sample(self):
        """JSON-ready value: plain number when unlabeled, else
        ``{"total": t, "by": {"op=dot": n, ...}}``."""
        with self._lock:
            vals = dict(self._values)
        if not vals or set(vals) == {()}:
            return vals.get((), 0.0)
        return {
            "total": sum(vals.values()),
            "by": {",".join(f"{k}={v}" for k, v in key): val
                   for key, val in sorted(vals.items()) if key},
        }

    def _reset(self):
        with self._lock:
            self._values.clear()


class Gauge:
    """Last-write-wins value, optionally labeled."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    @property
    def value(self) -> float:
        with self._lock:
            return self._values.get((), 0.0) if not self._values else \
                sum(self._values.values())

    def sample(self):
        with self._lock:
            vals = dict(self._values)
        if not vals or set(vals) == {()}:
            return vals.get((), 0.0)
        return {",".join(f"{k}={v}" for k, v in key) or "_": val
                for key, val in sorted(vals.items())}

    def _reset(self):
        with self._lock:
            self._values.clear()


class Histogram:
    """Bounded-reservoir histogram: keeps the last ``max_samples``
    observations for percentiles plus exact count/sum/max over the full
    stream.  Exported in Prometheus summary form (quantile series +
    ``_count``/``_sum``) with an extra ``_max`` series.

    The default reservoir holds 4096 samples so the p99 estimate rests
    on the ~41 largest observations of the window instead of the ~20 a
    2048-deep reservoir would give it — stable enough for the SLO
    engine (serving/slo.py) to alarm on."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", max_samples: int = 4096):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._samples = deque(maxlen=max_samples)
        self._count = 0
        self._sum = 0.0
        self._max = None

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._samples.append(v)
            self._count += 1
            self._sum += v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return None
        idx = min(len(data) - 1, max(0, int(round(q * (len(data) - 1)))))
        return data[idx]

    def stats(self) -> dict:
        with self._lock:
            data = sorted(self._samples)
            count, total, mx = self._count, self._sum, self._max
        if not data:
            return {"count": 0, "sum": 0.0, "p50": None, "p95": None,
                    "p99": None, "max": None}

        def pct(q):
            return data[min(len(data) - 1,
                            max(0, int(round(q * (len(data) - 1)))))]
        return {"count": count, "sum": total, "p50": pct(0.5),
                "p95": pct(0.95), "p99": pct(0.99), "max": mx}

    def sample(self):
        return self.stats()

    def state(self) -> dict:
        """Mergeable export: exact ``count``/``sum``/``max`` plus the raw
        reservoir, so another process can union distributions instead of
        averaging pre-computed quantiles (which under-merges the tail —
        a per-replica p99 of 10ms and 1s does NOT average to a fleet
        p99)."""
        with self._lock:
            return {"count": self._count, "sum": self._sum,
                    "max": self._max, "samples": list(self._samples)}

    @staticmethod
    def merge(states, max_samples: int = 4096) -> dict:
        """Union N :meth:`state` exports into one state.  count/sum/max
        merge exactly; reservoirs concatenate, and when the union
        overflows ``max_samples`` each source is downsampled to its
        proportional share by evenly-spaced picks over its SORTED
        samples — a deterministic quantile sketch (no RNG), so merged
        percentiles are reproducible across runs and processes."""
        srcs = [s for s in states if s and s.get("count")]
        count = sum(int(s["count"]) for s in srcs)
        total = sum(float(s["sum"]) for s in srcs)
        maxes = [s["max"] for s in srcs if s.get("max") is not None]
        pools = [sorted(float(v) for v in (s.get("samples") or ()))
                 for s in srcs]
        pools = [p for p in pools if p]
        kept = sum(len(p) for p in pools)
        if kept <= max_samples:
            merged = sorted(v for p in pools for v in p)
        else:
            merged = []
            for p in pools:
                k = max(1, int(round(max_samples * len(p) / kept)))
                k = min(k, len(p))
                if k == len(p):
                    merged.extend(p)
                elif k == 1:
                    merged.append(p[len(p) // 2])
                else:
                    step = (len(p) - 1) / (k - 1)
                    merged.extend(p[int(round(j * step))]
                                  for j in range(k))
            merged.sort()
            del merged[max_samples:]
        return {"count": count, "sum": total,
                "max": max(maxes) if maxes else None, "samples": merged}

    @staticmethod
    def stats_of(state: dict) -> dict:
        """The :meth:`stats` summary of a :meth:`state`/:meth:`merge`
        export (nearest-rank percentiles over its reservoir)."""
        data = sorted(float(v) for v in (state.get("samples") or ()))
        if not data:
            return {"count": 0, "sum": 0.0, "p50": None, "p95": None,
                    "p99": None, "max": None}

        def pct(q):
            return data[min(len(data) - 1,
                            max(0, int(round(q * (len(data) - 1)))))]
        return {"count": int(state.get("count") or 0),
                "sum": float(state.get("sum") or 0.0),
                "p50": pct(0.5), "p95": pct(0.95), "p99": pct(0.99),
                "max": state.get("max")}

    def _reset(self):
        with self._lock:
            self._samples.clear()
            self._count = 0
            self._sum = 0.0
            self._max = None


class MetricsRegistry:
    """Process-wide name → metric store with get-or-create accessors and
    the three exporters."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, **kw):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, help, **kw)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise MXNetError(
                f"metric {name!r} already registered as {m.kind}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  max_samples: int = 4096) -> Histogram:
        return self._get(Histogram, name, help, max_samples=max_samples)

    def get(self, name: str):
        return self._metrics.get(name)

    def metrics(self):
        return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Zero every metric (registrations survive)."""
        for m in list(self._metrics.values()):
            m._reset()

    # -- exporters ------------------------------------------------------
    def snapshot(self) -> dict:
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            out[m.kind + "s"][m.name] = m.sample()
        return out

    def counters_flat(self) -> Dict[str, float]:
        """name → total value for every counter and gauge (the chrome-trace
        ``ph:"C"`` feed used by profiler.dump())."""
        return {m.name: m.value for m in self.metrics()
                if m.kind in ("counter", "gauge")}

    def export_state(self) -> dict:
        """Lossless JSON-ready export for cross-process federation:
        counters/gauges keep their per-label-set values (label sets as
        ``"k=v,k2=v2"`` strings, ``""`` for unlabeled), histograms export
        their full :meth:`Histogram.state` reservoir.  The router fetches
        this from every replica and folds them with
        :func:`merge_states`."""
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            if m.kind in ("counter", "gauge"):
                with m._lock:
                    vals = dict(m._values)
                out[m.kind + "s"][m.name] = {
                    "help": m.help,
                    "values": {",".join(f"{k}={v}" for k, v in key): val
                               for key, val in sorted(vals.items())}}
            else:
                st = m.state()
                st["help"] = m.help
                out["histograms"][m.name] = st
        return out

    def render_prometheus(self) -> str:
        lines = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            if m.kind in ("counter", "gauge"):
                lines.append(f"# TYPE {m.name} {m.kind}")
                with m._lock:
                    vals = dict(m._values)
                if not vals:
                    lines.append(f"{m.name} 0")
                for key, val in sorted(vals.items()):
                    label = "{" + ",".join(
                        f'{k}="{v}"' for k, v in key) + "}" if key else ""
                    lines.append(f"{m.name}{label} {_fmt_num(val)}")
            else:
                lines.append(f"# TYPE {m.name} summary")
                s = m.stats()
                for q, k in (("0.5", "p50"), ("0.95", "p95"),
                             ("0.99", "p99")):
                    if s[k] is not None:
                        lines.append(
                            f'{m.name}{{quantile="{q}"}} {repr(s[k])}')
                lines.append(f"{m.name}_sum {repr(float(s['sum']))}")
                lines.append(f"{m.name}_count {int(s['count'])}")
                if s["max"] is not None:
                    lines.append(f"{m.name}_max {repr(s['max'])}")
        return "\n".join(lines) + "\n"


registry = MetricsRegistry()


def merge_states(states, max_samples: int = 4096) -> dict:
    """Fold N :meth:`MetricsRegistry.export_state` exports into one
    state of the same shape: counters and gauges sum per label set,
    histograms union via :meth:`Histogram.merge`.  Summing gauges gives
    fleet totals for capacity-style gauges (inflight, queue depth); the
    ratio-style SLO gauges are federated properly by the router's fleet
    ``/slo`` from merged windows, not from here."""
    out = {"counters": {}, "gauges": {}, "histograms": {}}
    for kind in ("counters", "gauges"):
        for st in states:
            for name, m in (st or {}).get(kind, {}).items():
                dst = out[kind].setdefault(
                    name, {"help": m.get("help", ""), "values": {}})
                for label, val in (m.get("values") or {}).items():
                    dst["values"][label] = \
                        dst["values"].get(label, 0.0) + float(val)
    hist_names = {}
    for st in states:
        for name, hs in (st or {}).get("histograms", {}).items():
            hist_names.setdefault(name, []).append(hs)
    for name, parts in hist_names.items():
        merged = Histogram.merge(parts, max_samples=max_samples)
        merged["help"] = next(
            (p.get("help") for p in parts if p.get("help")), "")
        out["histograms"][name] = merged
    return out


def render_prometheus_state(state: dict, extra_labels: dict = None,
                            type_lines: bool = True) -> str:
    """Prometheus text exposition of an :func:`merge_states` /
    :meth:`MetricsRegistry.export_state` state.  ``extra_labels`` are
    appended to every series (the router renders per-replica series with
    ``replica="host:port"`` and stale snapshots with ``stale="true"``)."""
    extra = ",".join(f'{k}="{v}"' for k, v in (extra_labels or {}).items())
    lines = []

    def fmt_labels(label_str):
        parts = [f'{k}="{v}"' for k, v in
                 (kv.split("=", 1) for kv in label_str.split(",") if kv)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    for kind, ptype in (("counters", "counter"), ("gauges", "gauge")):
        for name in sorted((state or {}).get(kind, {})):
            m = state[kind][name]
            if type_lines:
                if m.get("help"):
                    lines.append(f"# HELP {name} {m['help']}")
                lines.append(f"# TYPE {name} {ptype}")
            vals = m.get("values") or {}
            if not vals:
                lines.append(f"{name}{fmt_labels('')} 0")
            for label, val in sorted(vals.items()):
                lines.append(f"{name}{fmt_labels(label)} {_fmt_num(val)}")
    for name in sorted((state or {}).get("histograms", {})):
        hs = state["histograms"][name]
        if type_lines:
            if hs.get("help"):
                lines.append(f"# HELP {name} {hs['help']}")
            lines.append(f"# TYPE {name} summary")
        s = Histogram.stats_of(hs)
        for q, k in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            if s[k] is not None:
                lines.append(f'{name}{{quantile="{q}"'
                             + (f",{extra}" if extra else "")
                             + f'}} {repr(s[k])}')
        tail = fmt_labels("")
        lines.append(f"{name}_sum{tail} {repr(float(s['sum']))}")
        lines.append(f"{name}_count{tail} {int(s['count'])}")
        if s["max"] is not None:
            lines.append(f"{name}_max{tail} {repr(s['max'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def counter(name: str, help: str = "") -> Counter:
    return registry.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return registry.gauge(name, help)


def histogram(name: str, help: str = "",
              max_samples: int = 4096) -> Histogram:
    return registry.histogram(name, help, max_samples=max_samples)


# ---------------------------------------------------------------------------
# Span tracer
# ---------------------------------------------------------------------------
def new_request_id() -> str:
    """A fresh 16-hex request/trace id (the server-generated fallback
    when a client did not supply ``x-request-id``)."""
    import uuid
    return uuid.uuid4().hex[:16]


_span_seq = __import__("itertools").count(1)


class Span:
    """One timed region of the program: name, category, wall window
    (``time.perf_counter`` floats), free-form attrs, child spans, and the
    ident of the thread that opened it.  Spans form trees: a span opened
    while another is current on the same thread (or under an explicit
    ``parent=``) becomes its child.  ``sid`` is a process-unique hex id
    so a span can be referenced from outside its tree (batch-span links,
    ``/trace`` lookups)."""

    __slots__ = ("name", "cat", "t0", "t1", "attrs", "children", "tid",
                 "parent", "sid")

    def __init__(self, name: str, cat: str = "span", attrs: dict = None):
        self.name = name
        self.cat = cat
        self.attrs = attrs or None
        self.t0 = None
        self.t1 = None
        self.tid = 0
        self.sid = f"{next(_span_seq):08x}"
        self.parent: Optional["Span"] = None
        self.children: List["Span"] = []

    @property
    def seconds(self) -> Optional[float]:
        if self.t0 is None or self.t1 is None:
            return None
        return self.t1 - self.t0

    def to_dict(self, epoch: float = 0.0, now: float = None) -> dict:
        d = {"name": self.name, "cat": self.cat, "id": self.sid,
             "start_s": None if self.t0 is None
             else round(self.t0 - epoch, 6)}
        if self.t1 is not None:
            d["duration_s"] = round(self.t1 - self.t0, 6)
        else:
            d["open"] = True
            if now is not None and self.t0 is not None:
                d["duration_s"] = round(now - self.t0, 6)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict(epoch, now)
                             for c in list(self.children)]
        return d


class _SpanCtx:
    """Context manager returned by :func:`trace_span` — a no-op when the
    tracer is inactive, so instrumented hot paths pay one attribute check
    (no generator frame) when nothing is tracing."""

    __slots__ = ("_name", "_cat", "_parent", "_attrs", "span")

    def __init__(self, name, cat, parent, attrs):
        self._name = name
        self._cat = cat
        self._parent = parent
        self._attrs = attrs
        self.span = None

    def __enter__(self):
        if tracer.active:
            self.span = tracer._begin(self._name, self._cat, self._parent,
                                      self._attrs)
        return self.span

    def __exit__(self, *exc):
        if self.span is not None:
            tracer._end(self.span)
        return False


class Tracer:
    """Hierarchical span recorder with thread-local context propagation.

    Enabled by refcount (:meth:`enable` / :meth:`disable`):
    ``telemetry.start()`` and ``profiler.set_state("run")`` each hold one
    reference, so tracing is on whenever either plane collects.  Finished
    ROOT spans (whole subtrees) land in a bounded deque and on the
    ``SPAN`` topic; open roots are tracked for the live ``/trace`` view.

    Cross-thread propagation: capture the current span in the parent
    thread (``ctx = tracer.current()``) and either open child spans with
    ``trace_span(..., parent=ctx)`` or wrap the worker's body in
    ``with tracer.attach(ctx): ...`` so its spans nest under ``ctx``."""

    def __init__(self, max_finished: int = 512):
        self._tl = threading.local()
        self._lock = threading.Lock()
        self._enable_count = 0
        self._live: Dict[int, Span] = {}
        self._finished = deque(maxlen=max_finished)
        self._epoch = time.perf_counter()
        self._main_tid = threading.main_thread().ident

    @property
    def active(self) -> bool:
        return self._enable_count > 0

    def enable(self) -> None:
        with self._lock:
            self._enable_count += 1

    def disable(self) -> None:
        with self._lock:
            self._enable_count = max(0, self._enable_count - 1)

    def clear(self) -> None:
        """Drop recorded spans (live roots stay: their owners still hold
        them open)."""
        with self._lock:
            self._finished.clear()

    # -- span lifecycle -------------------------------------------------
    def _stack(self) -> List[Span]:
        s = getattr(self._tl, "stack", None)
        if s is None:
            s = self._tl.stack = []
        return s

    def _begin(self, name, cat="span", parent=None, attrs=None) -> Span:
        stack = self._stack()
        par = parent if parent is not None else \
            (stack[-1] if stack else None)
        if par is None:
            # a remote parent (another process's span, delivered via
            # X-Trace-Id) can't be a tree edge — record it as linkage
            # attrs so the router's stitcher re-parents this subtree
            rc = getattr(self._tl, "remote", None)
            if rc is not None:
                attrs = dict(attrs) if attrs else {}
                attrs.setdefault("trace_id", rc[0])
                attrs.setdefault("remote_parent", rc[1])
        sp = Span(name, cat, attrs)
        sp.t0 = time.perf_counter()
        sp.tid = threading.get_ident()
        sp.parent = par
        if par is not None:
            par.children.append(sp)     # list.append: atomic under the GIL
        else:
            with self._lock:
                self._live[id(sp)] = sp
        stack.append(sp)
        return sp

    def _end(self, sp: Span) -> None:
        sp.t1 = time.perf_counter()
        stack = self._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        elif sp in stack:               # mis-nested exit: still unwind
            stack.remove(sp)
        if sp.parent is None:
            with self._lock:
                self._live.pop(id(sp), None)
                self._finished.append(sp)
            if SPAN.subscribers:
                SPAN.publish(sp)

    def span(self, name: str, cat: str = "span", parent: Span = None,
             **attrs) -> _SpanCtx:
        return _SpanCtx(name, cat, parent, attrs)

    def current(self) -> Optional[Span]:
        stack = getattr(self._tl, "stack", None)
        return stack[-1] if stack else None

    class _Attach:
        __slots__ = ("_span",)

        def __init__(self, span):
            self._span = span

        def __enter__(self):
            tracer._stack().append(self._span)
            return self._span

        def __exit__(self, *exc):
            stack = tracer._stack()
            if stack and stack[-1] is self._span:
                stack.pop()
            elif self._span in stack:
                stack.remove(self._span)
            return False

    class _RemoteAttach:
        __slots__ = ("_ctx", "_prev")

        def __init__(self, ctx):
            self._ctx = ctx
            self._prev = None

        def __enter__(self):
            self._prev = getattr(tracer._tl, "remote", None)
            tracer._tl.remote = self._ctx
            return self._ctx

        def __exit__(self, *exc):
            tracer._tl.remote = self._prev
            return False

    def remote(self, trace_id: str,
               parent_sid: str) -> "Tracer._RemoteAttach":
        """Adopt a REMOTE parent for root spans opened on this thread
        while the context is held: each such span gets ``trace_id`` and
        ``remote_parent`` attrs naming the upstream hop span it belongs
        under.  This is the replica half of cross-process trace
        propagation — ``serving/server.py`` wraps request handling in
        ``tracer.remote(*parsed_x_trace_id)`` and the router's
        ``GET /trace`` stitcher grafts the resulting subtree under the
        hop span whose sid matches ``remote_parent``."""
        return Tracer._RemoteAttach((str(trace_id), str(parent_sid)))

    def attach(self, span: Span) -> "Tracer._Attach":
        """Adopt ``span`` as this thread's current span (does not close
        it) — the worker-thread half of cross-thread propagation."""
        return Tracer._Attach(span)

    # -- exports --------------------------------------------------------
    def _roots(self) -> List[Span]:
        with self._lock:
            return list(self._finished) + list(self._live.values())

    def tree(self, max_finished: int = 64,
             since: Optional[float] = None) -> dict:
        """JSON-ready view for the HTTP ``/trace`` endpoint: currently
        open root spans plus the most recent finished ones.  Times are
        seconds since tracer creation; ``since`` (same clock) drops
        roots that started before it, so a long-running server can be
        polled incrementally instead of re-serialized whole."""
        now = time.perf_counter()
        with self._lock:
            live = list(self._live.values())
            fin = list(self._finished)
        if since is not None:
            cutoff = self._epoch + float(since)
            live = [s for s in live if s.t0 is None or s.t0 >= cutoff]
            fin = [s for s in fin if s.t0 is None or s.t0 >= cutoff]
        fin = fin[-max(0, int(max_finished)):]
        return {
            "epoch_perf_counter": self._epoch,
            "live": [s.to_dict(self._epoch, now) for s in live],
            "finished": [s.to_dict(self._epoch) for s in fin],
        }

    def find_spans(self, attr: str, value, limit: int = 32) -> List[dict]:
        """Bounded lookup: spans (any depth, newest roots first) whose
        ``attrs[attr] == value``, as JSON-ready subtrees.  The per-request
        ``/trace?request_id=`` view — cost is one walk over the bounded
        finished/live roots, never the whole history."""
        now = time.perf_counter()
        with self._lock:
            roots = list(self._live.values()) + list(self._finished)[::-1]
        out: List[dict] = []

        def walk(sp: Span):
            if len(out) >= limit:
                return
            if sp.attrs and sp.attrs.get(attr) == value:
                out.append(sp.to_dict(self._epoch, now))
                return                  # the subtree already rides along
            for ch in list(sp.children):
                walk(ch)

        for root in roots:
            if len(out) >= limit:
                break
            walk(root)
        return out

    def chrome_events(self, t0: float) -> List[dict]:
        """Finished spans (any depth) overlapping [t0, now) as chrome
        ``ph:"X"`` events with ts/dur in µs relative to ``t0`` — the
        profiler merges these into its ``dump()``.  The main thread maps
        to tid 0 so spans nest with the profiler's own op events."""
        out = []
        tid_map = {self._main_tid: 0}

        def walk(sp: Span):
            if sp.t0 is not None and sp.t1 is not None and sp.t1 >= t0:
                tid = tid_map.setdefault(sp.tid, len(tid_map))
                ev = {"name": sp.name, "ph": "X",
                      "ts": max(0.0, (sp.t0 - t0) * 1e6),
                      "dur": max(0.0, (sp.t1 - max(sp.t0, t0)) * 1e6),
                      "pid": 0, "tid": tid, "cat": sp.cat}
                if sp.attrs:
                    ev["args"] = dict(sp.attrs)
                out.append(ev)
            for ch in list(sp.children):
                walk(ch)

        for root in self._roots():
            walk(root)
        return out


tracer = Tracer()


def trace_span(name: str, cat: str = "span", parent: Span = None,
               **attrs) -> _SpanCtx:
    """``with trace_span("trainer.step"): ...`` — open a span under the
    thread's current one (no-op while tracing is off)."""
    return tracer.span(name, cat, parent, **attrs)


def current_span() -> Optional[Span]:
    return tracer.current()


def traced(arg=None, cat: str = "span"):
    """Decorator form: ``@traced`` or ``@traced("name", cat=...)`` wraps
    the function body in a span."""
    import functools

    def make(fn, name):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not tracer.active:
                return fn(*args, **kwargs)
            with tracer.span(name, cat=cat):
                return fn(*args, **kwargs)
        return wrapper

    if callable(arg):
        return make(arg, getattr(arg, "__qualname__", arg.__name__))
    return lambda fn: make(fn, arg or getattr(fn, "__qualname__",
                                              fn.__name__))


# ---------------------------------------------------------------------------
# Device peak FLOP/s detection (MFU denominator)
# ---------------------------------------------------------------------------
# bf16 peak FLOP/s PER CHIP by TPU generation (public specs); longest key
# wins so 'v5 lite' beats 'v5'.  bench.py delegates here.
TPU_PEAK_FLOPS = {
    "v2": 46e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def tpu_peak_flops(kind: str) -> float:
    """Per-chip bf16 peak for a jax ``device_kind`` string (e.g. 'TPU v5
    lite'); unknown kinds fall back to the v5e-class 197 TFLOP/s."""
    k = (kind or "").lower().replace("tpu", "").strip()
    best = None
    for key, val in TPU_PEAK_FLOPS.items():
        if key in k and (best is None or len(key) > len(best[0])):
            best = (key, val)
    return best[1] if best else 197e12


def cpu_peak_flops() -> float:
    """Order-of-magnitude host fp32 peak: cores x clock x 32 FLOPs/cycle
    (two 256-bit FMA ports).  An ESTIMATE — good enough to make CPU MFU
    finite and comparable across runs on the same box, not across
    machines (see docs/observability.md for the caveats)."""
    cores = os.cpu_count() or 1
    ghz = 2.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    ghz = max(ghz, float(line.split(":")[1]) / 1000.0)
                    break
    except Exception:
        pass
    return cores * ghz * 1e9 * 32.0


def device_peak_flops() -> Optional[float]:
    """Aggregate peak FLOP/s over the LOCAL devices — TPU: per-chip table
    x local chip count (bf16); CPU: one host-wide estimate regardless of
    virtual device count.  None when undetectable (unknown platform)."""
    try:
        import jax
        devs = jax.local_devices()
    except Exception:
        return None
    if not devs:
        return None
    platform = getattr(devs[0], "platform", "")
    if platform == "tpu":
        return tpu_peak_flops(getattr(devs[0], "device_kind", "")) \
            * len(devs)
    if platform == "cpu":
        return cpu_peak_flops()
    return None


# ---------------------------------------------------------------------------
# Device memory gauges
# ---------------------------------------------------------------------------
def sample_device_memory() -> None:
    """Refresh the device-memory gauges from the live jax client.  Never
    raises: backends without memory_stats (CPU) just contribute the
    live-array total."""
    try:
        import jax
    except Exception:
        return
    g_live = registry.gauge(
        "mx_device_live_array_bytes",
        "total bytes of live jax arrays (all devices)")
    try:
        live = jax.live_arrays()
        g_live.set(sum(getattr(a, "nbytes", 0) or 0 for a in live))
    except Exception:
        pass
    try:
        g_use = registry.gauge("mx_device_bytes_in_use",
                               "per-device bytes in use (memory_stats)")
        g_peak = registry.gauge("mx_device_peak_bytes_in_use",
                                "per-device peak bytes (memory_stats)")
        for d in jax.devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                continue
            if not stats:
                continue
            dev = f"{d.platform}:{d.id}"
            if "bytes_in_use" in stats:
                g_use.set(stats["bytes_in_use"], device=dev)
            if "peak_bytes_in_use" in stats:
                g_peak.set(stats["peak_bytes_in_use"], device=dev)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Dispatch ledger (device-plane observability; docs/observability.md)
# ---------------------------------------------------------------------------
# One entry per instrument_jit site, ALWAYS on: per-dispatch count, a
# bounded wall-time reservoir, compile accounting (while the collector
# observes), the wall clock of the last dispatch, and a live handle to
# the pjit cache size.  This is the runtime program-set inventory — the
# dynamic counterpart of mxtpu-lint's static closed-program-set check:
# a site whose cache keeps growing after warmup, or a compiled program
# that is never dispatched, shows up here at runtime.
_LEDGER_RESERVOIR = 512


class _LedgerEntry:
    __slots__ = ("site", "dispatches", "seconds_sum", "seconds_max",
                 "samples", "compiles", "compile_seconds", "last_t",
                 "size_fn", "lock", "_key")

    def __init__(self, site: str):
        self.site = site
        self.dispatches = 0
        self.seconds_sum = 0.0
        self.seconds_max = 0.0
        self.samples = deque(maxlen=_LEDGER_RESERVOIR)
        self.compiles = 0
        self.compile_seconds = 0.0
        self.last_t: Optional[float] = None
        self.size_fn: Optional[Callable[[], int]] = None
        self.lock = threading.Lock()
        self._key = (("site", site),)   # precomputed counter label key

    def record(self, dt: float) -> None:
        c = _ledger_dispatches
        with c._lock:
            c._values[self._key] = c._values.get(self._key, 0.0) + 1.0
        _ledger_seconds.observe(dt)
        with self.lock:
            self.dispatches += 1
            self.seconds_sum += dt
            if dt > self.seconds_max:
                self.seconds_max = dt
            self.samples.append(dt)
            self.last_t = time.time()

    def record_compile(self, dt: float) -> None:
        with self.lock:
            self.compiles += 1
            self.compile_seconds += dt

    def _reset(self) -> None:
        with self.lock:
            self.dispatches = 0
            self.seconds_sum = 0.0
            self.seconds_max = 0.0
            self.samples.clear()
            self.compiles = 0
            self.compile_seconds = 0.0
            self.last_t = None


_ledger_dispatches = registry.counter(
    "mxtpu_dispatches_total",
    "compiled-program dispatches, by instrumented jit site")
_ledger_seconds = registry.histogram(
    "mxtpu_dispatch_seconds",
    "host wall seconds per compiled-program dispatch (all sites)")
_ledger: Dict[str, _LedgerEntry] = {}
_ledger_lock = threading.Lock()


def _ledger_entry(site: str) -> _LedgerEntry:
    e = _ledger.get(site)
    if e is None:
        with _ledger_lock:
            e = _ledger.setdefault(site, _LedgerEntry(site))
    return e


def dispatch_ledger(prefix: Optional[str] = None) -> Dict[str, dict]:
    """JSON-ready snapshot of the per-site dispatch ledger: dispatch
    count, wall-time stats over the bounded reservoir, compile count and
    blocking seconds (counted while the collector observes), seconds
    since the last dispatch, and — when the wrapped pjit exposes its
    cache — the number of executables currently compiled at the site.
    ``prefix`` filters sites (e.g. ``"serving:gen"`` for one engine's
    programs)."""
    now = time.time()
    out: Dict[str, dict] = {}
    for site in sorted(_ledger):
        if prefix is not None and not site.startswith(prefix):
            continue
        e = _ledger[site]
        with e.lock:
            data = sorted(e.samples)
            d = {
                "site": site,
                "dispatches": e.dispatches,
                "seconds_sum": round(e.seconds_sum, 6),
                "seconds_max": round(e.seconds_max, 6),
                "compiles": e.compiles,
                "compile_seconds": round(e.compile_seconds, 6),
                "last_dispatch_age_s": None if e.last_t is None
                else round(now - e.last_t, 3),
            }
        if data:
            d["seconds_p50"] = round(
                data[min(len(data) - 1,
                         int(round(0.5 * (len(data) - 1))))], 6)
            d["seconds_p99"] = round(
                data[min(len(data) - 1,
                         int(round(0.99 * (len(data) - 1))))], 6)
        size_fn = e.size_fn
        compiled = None
        if size_fn is not None:
            try:
                compiled = int(size_fn())
            except Exception:
                compiled = None
        d["compiled"] = compiled
        out[site] = d
    return out


def reset_dispatch_ledger() -> None:
    """Zero every ledger entry in place (test hygiene; the entries stay
    registered — instrument_jit wrappers hold direct references)."""
    with _ledger_lock:
        entries = list(_ledger.values())
    for e in entries:
        e._reset()


# ---------------------------------------------------------------------------
# StepHealth ring (health plane, health.py)
# ---------------------------------------------------------------------------
class StepHealthRing:
    """Bounded ring of per-train-step health records.

    One record per inner step, folded in by :class:`health.HealthMonitor`
    at chunk/step boundaries (the stats themselves are computed inside
    the donated programs — see health.py).  A record is a JSON-ready
    dict: ``step``, ``src``, ``loss`` (None on the eager fused path,
    which never sees the loss), ``grad_norm``, ``max_update_ratio``,
    ``finite`` and — when not finite — ``nonfinite_leaf``, the first
    offending parameter by tree path.

    Capacity comes from ``MXNET_HEALTH_RING`` (default 256; re-read on
    :meth:`clear` so tests can resize)."""

    def __init__(self, size: Optional[int] = None):
        self._size = size
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self._capacity())

    def _capacity(self) -> int:
        from .base import getenv_int
        n = self._size if self._size is not None \
            else getenv_int("MXNET_HEALTH_RING", 256)
        return max(1, int(n))

    def record(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    def entries(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        return out[-int(last):] if last else out

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def clear(self) -> None:
        with self._lock:
            self._ring = deque(maxlen=self._capacity())

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


#: process-wide StepHealth ring — the training twin of the flight
#: recorder's activity ring; telemetry.reset() clears it
health_ring = StepHealthRing()


# ---------------------------------------------------------------------------
# Compile instrumentation + cost accountant
# ---------------------------------------------------------------------------
def _arg_signature(args, kwargs):
    """Hashable (treedef, leaf shapes/dtypes) key identifying which cached
    executable a call hits — python scalars key by type only (jit treats
    them as dynamic weak-typed args, one compilation per type)."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return treedef, tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
        else (type(leaf).__name__,)
        for leaf in leaves)


def _cost_analysis_dict(compiled) -> dict:
    """Normalize ``compiled.cost_analysis()`` — a list of dicts on CPU
    backends, a plain dict elsewhere — to one dict (possibly empty)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def instrument_jit(where: str, jitted: Callable) -> Callable:
    """Wrap a ``jax.jit`` callable so the runtime can observe it three
    ways, each gated on its own consumer and free when nobody listens:

    * **COMPILE topic** — cache hit/miss per call.  When the pjit object
      exposes ``_cache_size``, per-shape recompiles are detected exactly
      (the cache grew across the call → miss, with the blocking
      trace+compile seconds); otherwise the first invocation counts as
      the one miss.
    * **XLA_COST topic** — cost-analysis FLOPs / bytes-accessed of the
      executable this call dispatches, captured once per argument
      signature via AOT ``lower(...).compile().cost_analysis()`` and
      republished on every call (the MFU numerator).  Capture runs
      BEFORE the real call: with ``donate_argnums`` the arguments are
      dead afterwards.  The AOT compile does not warm jit's call cache,
      so each new signature costs one extra trace+compile — only while a
      cost subscriber is attached.
    * **Span tracer** — the dispatch is wrapped in a ``jit:<where>`` span
      while tracing is active, so compiled-call time nests under the
      caller's step/forward span in the flame graph.

    Independent of all three consumers, every call lands in the
    process-wide **dispatch ledger** (:func:`dispatch_ledger`): per-site
    dispatch counts, host wall-time histograms and last-dispatch age —
    the always-on runtime program inventory.  Cost on the unobserved
    fast path: two ``perf_counter`` reads and two dict updates per
    dispatch."""
    size_fn = getattr(jitted, "_cache_size", None)
    lower_fn = getattr(jitted, "lower", None)
    state = {"first": True}
    costs: Dict[tuple, tuple] = {}
    span_name = "jit:" + where
    ledger = _ledger_entry(where)
    ledger.size_fn = size_fn       # latest wrapper wins (re-created jits)

    def _cost(args, kwargs):
        try:
            sig = _arg_signature(args, kwargs)
        except Exception:
            sig = None
        if sig is not None:
            hit = costs.get(sig)
            if hit is not None:
                return hit
        val = (0.0, 0.0)
        if lower_fn is not None:
            try:
                ca = _cost_analysis_dict(lower_fn(*args, **kwargs).compile())
                val = (float(ca.get("flops", 0.0) or 0.0),
                       float(ca.get("bytes accessed", 0.0) or 0.0))
            except Exception:
                pass
        if sig is not None:
            costs[sig] = val
        return val

    def call(*args, **kwargs):
        observing = bool(COMPILE.subscribers)
        costing = bool(XLA_COST.subscribers)
        tracing = tracer.active
        if not (observing or costing or tracing):
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            ledger.record(time.perf_counter() - t0)
            return out
        flops = nbytes = 0.0
        if costing:
            flops, nbytes = _cost(args, kwargs)
        before = None
        if observing and size_fn is not None:
            try:
                before = size_fn()
            except Exception:
                before = None
        sp = tracer._begin(span_name, "jit",
                           attrs={"flops": flops} if flops else None) \
            if tracing else None
        t0 = time.perf_counter()
        try:
            out = jitted(*args, **kwargs)
        finally:
            if sp is not None:
                tracer._end(sp)
        dt = time.perf_counter() - t0
        ledger.record(dt)
        if observing:
            grew = None
            if before is not None:
                try:
                    grew = size_fn() > before
                except Exception:
                    grew = None
            if grew is None:
                grew = state["first"]
            if grew:
                ledger.record_compile(dt)
                COMPILE.publish(where=where, event="miss", seconds=dt)
            else:
                COMPILE.publish(where=where, event="hit")
        state["first"] = False
        if costing:
            XLA_COST.publish(where=where, flops=flops, nbytes=nbytes)
        return out

    call.__wrapped__ = jitted
    return call


# ---------------------------------------------------------------------------
# Collector: the default subscribers that turn bus events into metrics
# ---------------------------------------------------------------------------
_started = False
_m: Dict[str, object] = {}


def _metrics_init():
    c, h = registry.counter, registry.histogram
    _m["ops"] = c("mx_op_dispatch_total",
                  "eager ops dispatched, by op name")
    _m["op_seconds"] = h("mx_op_seconds",
                         "synchronous per-op seconds (profiler-timed path)")
    _m["sync"] = c("mx_sync_block_total",
                   "blocking sync calls (wait_to_read/asnumpy), by kind")
    _m["h2d"] = c("mx_transfer_h2d_bytes_total",
                  "host->device transfer bytes")
    _m["d2h"] = c("mx_transfer_d2h_bytes_total",
                  "device->host transfer bytes")
    _m["compile"] = c("mx_compile_total", "XLA compiles, by site")
    _m["compile_hit"] = c("mx_compile_cache_hits_total",
                          "compiled-executable cache hits, by site")
    _m["compile_miss"] = c("mx_compile_cache_misses_total",
                           "compiled-executable cache misses, by site")
    _m["compile_seconds"] = h("mx_compile_seconds",
                              "blocking trace+compile seconds")
    _m["kv_calls"] = c("mx_kvstore_calls_total",
                       "kvstore calls, by op (push/pull/pushpull)")
    _m["kv_push_bytes"] = c("mx_kvstore_push_bytes_total",
                            "bytes pushed into the kvstore")
    _m["kv_pull_bytes"] = c("mx_kvstore_pull_bytes_total",
                            "bytes pulled out of the kvstore")
    _m["kv_push_seconds"] = h("mx_kvstore_push_seconds",
                              "kvstore push latency")
    _m["kv_pull_seconds"] = h("mx_kvstore_pull_seconds",
                              "kvstore pull latency")
    _m["kv_pushpull_seconds"] = h("mx_kvstore_pushpull_seconds",
                                  "kvstore fused push+pull latency")
    _m["steps"] = c("mx_trainer_steps_total", "trainer optimization steps")
    _m["step_seconds"] = h("mx_trainer_step_seconds",
                           "trainer step dispatch seconds")
    _m["update_seconds"] = h("mx_trainer_update_seconds",
                             "trainer update dispatch seconds")
    _m["batches"] = c("mx_dataloader_batches_total",
                      "dataloader batches fetched")
    _m["fetch_wait"] = h("mx_dataloader_fetch_wait_seconds",
                         "consumer wait per dataloader batch")
    g = registry.gauge
    _m["xla_flops"] = c("mx_xla_flops_total",
                        "cost-analysis FLOPs dispatched to compiled "
                        "executables, by site")
    _m["xla_bytes"] = c("mx_xla_bytes_total",
                        "cost-analysis bytes accessed by compiled "
                        "executables, by site")
    _m["step_wall"] = h("mxtpu_step_seconds",
                        "wall seconds between consecutive trainer step "
                        "boundaries (the MFU window)")
    _m["step_flops"] = g("mxtpu_step_flops",
                         "cost-analysis FLOPs in the last step window")
    _m["peak_flops"] = g("mxtpu_device_peak_flops",
                         "detected aggregate device peak FLOP/s")
    _m["mfu"] = g("mxtpu_mfu",
                  "model FLOPs utilization over the last step window")
    _m["faults"] = c("mxtpu_faults_injected",
                     "deterministic faults injected, by site/kind")
    _m["retries"] = c("mxtpu_retries",
                      "transient failures absorbed by retry, by site")
    _m["giveups"] = c("mxtpu_giveups",
                      "retries exhausted (max attempts/deadline), by site")
    _m["skipped_steps"] = c("mxtpu_skipped_steps",
                            "optimizer steps skipped on non-finite "
                            "gradients")
    _m["dl_fallbacks"] = c("mxtpu_dataloader_fallbacks",
                           "dataloader worker failures absorbed by "
                           "in-process fetch")
    _m["fused_updates"] = c("mxtpu_optimizer_fused_updates",
                            "whole-tree fused optimizer dispatches "
                            "(one jit call updating every parameter)")
    _m["dispatches_per_step"] = g("mxtpu_optimizer_dispatches_per_step",
                                  "optimizer-update dispatches in the "
                                  "last trainer step (1 = fused; "
                                  "num_params = per-param loop)")
    _m["loop_chunks"] = c("mxtpu_loop_chunks",
                          "CompiledLoop chunk dispatches (one donated "
                          "scanned program per k-step chunk)")
    _m["loop_chunk_seconds"] = h("mxtpu_loop_chunk_seconds",
                                 "CompiledLoop chunk dispatch seconds")
    _m["loop_steps_per_chunk"] = g("mxtpu_loop_steps_per_chunk",
                                   "train steps folded into the last "
                                   "CompiledLoop chunk")


_op_keys: Dict[str, tuple] = {}   # op name -> label key, spares the hot
                                  # path the kwargs/sort work of inc()


def _on_op_dispatch(name):
    key = _op_keys.get(name)
    if key is None:
        key = _op_keys[name] = (("op", name),)
    c = _m["ops"]
    with c._lock:
        c._values[key] = c._values.get(key, 0.0) + 1.0


def _on_op_timed(name, seconds):
    _m["op_seconds"].observe(seconds)


def _on_sync(kind):
    _m["sync"].inc(kind=kind)


def _on_transfer(direction, nbytes):
    _m["h2d" if direction == "h2d" else "d2h"].inc(nbytes)


def _on_compile(where="?", event="miss", seconds=None):
    if event == "miss":
        _m["compile"].inc(site=where)
        _m["compile_miss"].inc(site=where)
        if seconds is not None:
            _m["compile_seconds"].observe(seconds)
    else:
        _m["compile_hit"].inc(site=where)


def _on_kvstore(op="push", nbytes=0, seconds=0.0):
    _m["kv_calls"].inc(op=op)
    if op == "push" and nbytes:
        _m["kv_push_bytes"].inc(nbytes)
    elif op == "pull" and nbytes:
        _m["kv_pull_bytes"].inc(nbytes)
    key = f"kv_{op}_seconds"
    if key in _m:
        _m[key].observe(seconds)


# MFU accounting state.  FLOPs accumulate from XLA_COST as executables
# are dispatched; at each trainer-step boundary the window since the
# PREVIOUS boundary is closed: mfu = window FLOPs / wall seconds / peak.
# Wall time between boundaries (not the async dispatch seconds the
# TRAINER event carries) is the honest denominator — the device is busy
# long after dispatch returns.
_mfu = {"flops": 0.0, "last_t": None, "last_flops": 0.0, "peak": None}


def _on_xla_cost(where="?", flops=0.0, nbytes=0.0):
    if flops:
        _m["xla_flops"].inc(flops, site=where)
        _mfu["flops"] += flops
    if nbytes:
        _m["xla_bytes"].inc(nbytes, site=where)


def _on_trainer(phase="step", seconds=0.0, steps=1):
    if phase == "step":
        # steps > 1: a CompiledLoop chunk — k inner steps behind ONE
        # boundary.  Counters advance by k and per-step attribution
        # divides the window evenly; MFU itself is a window ratio, so
        # the formula is unchanged.
        n = max(int(steps), 1)
        _m["steps"].inc(n)
        _m["step_seconds"].observe(seconds / n)
        now = time.perf_counter()
        last_t = _mfu["last_t"]
        if last_t is not None and now > last_t:
            wall = now - last_t
            dflops = _mfu["flops"] - _mfu["last_flops"]
            _m["step_wall"].observe(wall / n)
            _m["step_flops"].set(dflops / n)
            peak = _mfu["peak"]
            if peak is None:
                peak = _mfu["peak"] = device_peak_flops() or 0.0
                _m["peak_flops"].set(peak)
            if peak > 0 and dflops > 0:
                _m["mfu"].set(dflops / wall / peak)
        _mfu["last_t"] = now
        _mfu["last_flops"] = _mfu["flops"]
    elif phase == "chunk":
        _m["loop_chunks"].inc()
        _m["loop_chunk_seconds"].observe(seconds)
        _m["loop_steps_per_chunk"].set(max(int(steps), 1))
    else:
        _m["update_seconds"].observe(seconds)


def _on_dataloader(seconds=0.0):
    _m["batches"].inc()
    _m["fetch_wait"].observe(seconds)


def _on_fault(site="?", event="injected", kind=None, **_kw):
    if event == "injected":
        _m["faults"].inc(site=site, kind=kind or "?")
    elif event == "retry":
        _m["retries"].inc(site=site)
    elif event == "giveup":
        _m["giveups"].inc(site=site)
    elif event == "skipped_step":
        _m["skipped_steps"].inc()
    elif event == "fallback":
        _m["dl_fallbacks"].inc(site=site)


_HANDLERS = (
    (OP_DISPATCH, _on_op_dispatch),
    (OP_TIMED, _on_op_timed),
    (SYNC, _on_sync),
    (TRANSFER, _on_transfer),
    (COMPILE, _on_compile),
    (KVSTORE, _on_kvstore),
    (TRAINER, _on_trainer),
    (DATALOADER, _on_dataloader),
    (XLA_COST, _on_xla_cost),
    (FAULT, _on_fault),
)


def start() -> None:
    """Begin collecting: subscribe the metric handlers to every runtime
    topic and turn the span tracer on.  Idempotent."""
    global _started
    if _started:
        return
    _metrics_init()
    for topic, fn in _HANDLERS:
        # OP_TIMED passively: the collector must never itself force the
        # per-op syncs that feed it — mx_op_seconds only fills while the
        # profiler (an active subscriber) has the timed path on
        topic.subscribe(fn, passive=topic is OP_TIMED)
    tracer.enable()
    _started = True
    # the black-box flight recorder rides whenever the collector does
    # (late import: telemetry_ring imports this module)
    from . import telemetry_ring
    telemetry_ring.recorder.start()


def stop() -> None:
    """Detach the collector (metric values are kept; see reset())."""
    global _started
    for topic, fn in _HANDLERS:
        topic.unsubscribe(fn)
    if _started:
        tracer.disable()
        from . import telemetry_ring
        telemetry_ring.recorder.stop()
    _started = False


def enabled() -> bool:
    return _started


def reset() -> None:
    """Zero all metric values, drop recorded spans, restart the MFU
    window, zero the dispatch ledger."""
    registry.reset()
    tracer.clear()
    reset_dispatch_ledger()
    health_ring.clear()
    _mfu.update(flops=0.0, last_t=None, last_flops=0.0, peak=None)


# ---------------------------------------------------------------------------
# Exporters (module-level conveniences over the default registry)
# ---------------------------------------------------------------------------
def snapshot(include_memory: bool = True) -> dict:
    """JSON-ready dict of every metric; refreshes device-memory gauges
    first (when collecting)."""
    if _started and include_memory:
        sample_device_memory()
    out = registry.snapshot()
    out["enabled"] = _started
    return out


def render_prometheus(include_memory: bool = True) -> str:
    """Prometheus text exposition of every metric."""
    if _started and include_memory:
        sample_device_memory()
    return registry.render_prometheus()


def counters_flat() -> Dict[str, float]:
    return registry.counters_flat()


def dump(path: str, fmt: Optional[str] = None) -> None:
    """Write the current metrics to ``path``: Prometheus text when ``fmt``
    is 'prometheus' (or the path ends in .prom/.txt), JSON otherwise."""
    if fmt is None:
        fmt = "prometheus" if path.endswith((".prom", ".txt")) else "json"
    with open(path, "w") as f:
        if fmt == "prometheus":
            f.write(render_prometheus())
        else:
            json.dump(snapshot(), f, indent=2, default=str)
            f.write("\n")


# ---------------------------------------------------------------------------
# Env autostart (reference parity with MXNET_PROFILER_AUTOSTART)
# ---------------------------------------------------------------------------
_dump_path = getenv("MXNET_TELEMETRY_DUMP")
if _dump_path:
    def _dump_at_exit(path=_dump_path):
        try:
            dump(path)
        except Exception:
            pass
    atexit.register(_dump_at_exit)

if getenv_bool("MXNET_TELEMETRY", False):
    start()

_port = getenv("MXNET_TELEMETRY_PORT")
if _port:
    try:
        start()
        from . import telemetry_http as _telemetry_http
        _telemetry_http.start_server(int(_port))
    except Exception as _e:                       # never break import
        import warnings
        warnings.warn(f"MXNET_TELEMETRY_PORT={_port}: exporter not "
                      f"started ({_e})")
