"""Unified runtime telemetry: multi-subscriber event bus + cross-layer
metrics registry (reference analog: the reference's profiler counters +
``MXNET_PROFILER_*`` plane, generalized into an always-on, low-overhead
observability spine for the whole runtime).

Two cooperating pieces:

* **Event bus** — named :class:`Topic` objects that any number of
  subscribers can attach to concurrently.  This replaces the single-slot
  ``_op_observer`` hook in ``ndarray/ndarray.py``: the profiler and the
  telemetry collector (and any user code) can observe the same op stream
  at once.  Publishing to a topic with no subscribers is a single list
  truthiness check — the instrumented hot paths stay effectively free
  when nothing is listening.
* **Metrics registry** — process-wide :class:`Counter` / :class:`Gauge` /
  :class:`Histogram` (bounded reservoir with p50/p95/max), exported three
  ways: :func:`render_prometheus` (text exposition format),
  :func:`snapshot` (JSON-ready dict, merged into ``bench.py``'s output
  line), and counter samples woven into the profiler's chrome-trace
  ``dump()`` as ``ph:"C"`` events.

Instrumented layers (see docs/observability.md):

* eager op dispatch — op counts per name, sync-block counts, host<->device
  transfer bytes (``ndarray/ndarray.py``)
* JIT/compile — compile count, cache hit/miss, compile seconds
  (``executor.py``, ``gluon/block.py`` _CachedGraph, ``parallel/spmd.py``,
  ``kvstore.py`` mesh reducer) via :func:`instrument_jit`
* kvstore — push/pull/pushpull calls, bytes, latency histograms
* gluon trainer — step/update timing
* dataloader — per-batch fetch-wait time
* device memory — gauges sampled from ``jax.live_arrays()`` /
  ``device.memory_stats()`` at export time

Control plane: ``MXNET_TELEMETRY=1`` starts collection at import;
``MXNET_TELEMETRY_DUMP=/path`` additionally writes a dump at process exit
(Prometheus text if the path ends in ``.prom``/``.txt``, JSON otherwise).
The ``mxtpu-stats`` console script (``_cli.py``) runs any script under
telemetry and prints the dump.
"""
from __future__ import annotations

import atexit
import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .base import MXNetError, getenv, getenv_bool

__all__ = [
    "Topic", "EventBus", "bus",
    "OP_DISPATCH", "OP_TIMED", "SYNC", "TRANSFER", "COMPILE", "KVSTORE",
    "TRAINER", "DATALOADER",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "counter", "gauge", "histogram",
    "start", "stop", "enabled", "reset",
    "snapshot", "render_prometheus", "counters_flat", "dump",
    "instrument_jit", "sample_device_memory",
]


# ---------------------------------------------------------------------------
# Event bus
# ---------------------------------------------------------------------------
class Topic:
    """A named event stream.  ``subscribers`` is copy-on-write so
    ``publish`` iterates a stable snapshot without locking the hot path;
    a subscriber that raises is counted in ``errors`` and skipped — an
    observer must never take the observed program down.

    ``forcing`` counts non-passive subscribers.  Publishers whose
    instrumentation is expensive (OP_TIMED forces a per-op device sync)
    key the decision to pay that cost on ``forcing``, so a passive
    listener (the telemetry collector) can ride along whenever an active
    one (the profiler) turns the firehose on, without turning it on
    itself."""

    __slots__ = ("name", "subscribers", "errors", "last_error", "forcing",
                 "_passive")

    def __init__(self, name: str):
        self.name = name
        self.subscribers: List[Callable] = []
        self.errors = 0
        self.last_error: Optional[BaseException] = None
        self.forcing = 0
        self._passive = set()

    def subscribe(self, fn: Callable, passive: bool = False) -> Callable:
        if fn not in self.subscribers:
            self.subscribers = self.subscribers + [fn]
            if passive:
                self._passive.add(id(fn))
            else:
                self.forcing += 1
        return fn

    def unsubscribe(self, fn: Callable) -> None:
        if fn in self.subscribers:
            self.subscribers = [s for s in self.subscribers if s is not fn]
            if id(fn) in self._passive:
                self._passive.discard(id(fn))
            else:
                self.forcing -= 1

    def publish(self, *args, **kwargs) -> None:
        for fn in self.subscribers:
            try:
                fn(*args, **kwargs)
            except Exception as e:
                self.errors += 1
                self.last_error = e


class EventBus:
    """Registry of Topics; ``topic(name)`` is get-or-create."""

    def __init__(self):
        self._topics: Dict[str, Topic] = {}
        self._lock = threading.Lock()

    def topic(self, name: str) -> Topic:
        t = self._topics.get(name)
        if t is None:
            with self._lock:
                t = self._topics.setdefault(name, Topic(name))
        return t

    def subscribe(self, name: str, fn: Callable,
                  passive: bool = False) -> Callable:
        return self.topic(name).subscribe(fn, passive=passive)

    def unsubscribe(self, name: str, fn: Callable) -> None:
        self.topic(name).unsubscribe(fn)

    def publish(self, name: str, *args, **kwargs) -> None:
        self.topic(name).publish(*args, **kwargs)

    def topics(self) -> List[str]:
        return sorted(self._topics)


bus = EventBus()

# Canonical runtime topics.  Payload contracts:
#   OP_DISPATCH(name)                 — one eager op dispatched (not traced)
#   OP_TIMED(name, seconds)           — op with true synchronous duration;
#                                       subscribing FORCES per-op sync
#   SYNC(kind)                        — a blocking call (wait_to_read/asnumpy)
#   TRANSFER(direction, nbytes)       — "h2d" | "d2h" host<->device bytes
#   COMPILE(where=, event=, seconds=) — event in {"miss","hit"}; miss carries
#                                       trace+compile seconds when measurable
#   KVSTORE(op=, nbytes=, seconds=)   — op in {"push","pull","pushpull"}
#   TRAINER(phase=, seconds=)         — phase in {"step","update"}
#   DATALOADER(seconds=)              — consumer-side batch fetch wait
OP_DISPATCH = bus.topic("op.dispatch")
OP_TIMED = bus.topic("op.timed")
SYNC = bus.topic("op.sync")
TRANSFER = bus.topic("transfer")
COMPILE = bus.topic("compile")
KVSTORE = bus.topic("kvstore")
TRAINER = bus.topic("trainer")
DATALOADER = bus.topic("dataloader")


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------
def _label_key(labels: dict):
    return tuple(sorted(labels.items()))


def _fmt_num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class Counter:
    """Monotonic counter, optionally broken out by labels
    (``c.inc(3, op="dot")``)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise MXNetError(f"counter {self.name}: negative increment")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    @property
    def value(self) -> float:
        return sum(self._values.values())

    def sample(self):
        """JSON-ready value: plain number when unlabeled, else
        ``{"total": t, "by": {"op=dot": n, ...}}``."""
        with self._lock:
            vals = dict(self._values)
        if not vals or set(vals) == {()}:
            return vals.get((), 0.0)
        return {
            "total": sum(vals.values()),
            "by": {",".join(f"{k}={v}" for k, v in key): val
                   for key, val in sorted(vals.items()) if key},
        }

    def _reset(self):
        with self._lock:
            self._values.clear()


class Gauge:
    """Last-write-wins value, optionally labeled."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    @property
    def value(self) -> float:
        with self._lock:
            return self._values.get((), 0.0) if not self._values else \
                sum(self._values.values())

    def sample(self):
        with self._lock:
            vals = dict(self._values)
        if not vals or set(vals) == {()}:
            return vals.get((), 0.0)
        return {",".join(f"{k}={v}" for k, v in key) or "_": val
                for key, val in sorted(vals.items())}

    def _reset(self):
        with self._lock:
            self._values.clear()


class Histogram:
    """Bounded-reservoir histogram: keeps the last ``max_samples``
    observations for percentiles plus exact count/sum/max over the full
    stream.  Exported in Prometheus summary form (quantile series +
    ``_count``/``_sum``) with an extra ``_max`` series."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", max_samples: int = 2048):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._samples = deque(maxlen=max_samples)
        self._count = 0
        self._sum = 0.0
        self._max = None

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._samples.append(v)
            self._count += 1
            self._sum += v
            if self._max is None or v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return None
        idx = min(len(data) - 1, max(0, int(round(q * (len(data) - 1)))))
        return data[idx]

    def stats(self) -> dict:
        with self._lock:
            data = sorted(self._samples)
            count, total, mx = self._count, self._sum, self._max
        if not data:
            return {"count": 0, "sum": 0.0, "p50": None, "p95": None,
                    "max": None}

        def pct(q):
            return data[min(len(data) - 1,
                            max(0, int(round(q * (len(data) - 1)))))]
        return {"count": count, "sum": total, "p50": pct(0.5),
                "p95": pct(0.95), "max": mx}

    def sample(self):
        return self.stats()

    def _reset(self):
        with self._lock:
            self._samples.clear()
            self._count = 0
            self._sum = 0.0
            self._max = None


class MetricsRegistry:
    """Process-wide name → metric store with get-or-create accessors and
    the three exporters."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name, help, **kw):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, help, **kw)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise MXNetError(
                f"metric {name!r} already registered as {m.kind}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  max_samples: int = 2048) -> Histogram:
        return self._get(Histogram, name, help, max_samples=max_samples)

    def get(self, name: str):
        return self._metrics.get(name)

    def metrics(self):
        return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        """Zero every metric (registrations survive)."""
        for m in list(self._metrics.values()):
            m._reset()

    # -- exporters ------------------------------------------------------
    def snapshot(self) -> dict:
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in self.metrics():
            out[m.kind + "s"][m.name] = m.sample()
        return out

    def counters_flat(self) -> Dict[str, float]:
        """name → total value for every counter and gauge (the chrome-trace
        ``ph:"C"`` feed used by profiler.dump())."""
        return {m.name: m.value for m in self.metrics()
                if m.kind in ("counter", "gauge")}

    def render_prometheus(self) -> str:
        lines = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            if m.kind in ("counter", "gauge"):
                lines.append(f"# TYPE {m.name} {m.kind}")
                with m._lock:
                    vals = dict(m._values)
                if not vals:
                    lines.append(f"{m.name} 0")
                for key, val in sorted(vals.items()):
                    label = "{" + ",".join(
                        f'{k}="{v}"' for k, v in key) + "}" if key else ""
                    lines.append(f"{m.name}{label} {_fmt_num(val)}")
            else:
                lines.append(f"# TYPE {m.name} summary")
                s = m.stats()
                for q, k in (("0.5", "p50"), ("0.95", "p95")):
                    if s[k] is not None:
                        lines.append(
                            f'{m.name}{{quantile="{q}"}} {repr(s[k])}')
                lines.append(f"{m.name}_sum {repr(float(s['sum']))}")
                lines.append(f"{m.name}_count {int(s['count'])}")
                if s["max"] is not None:
                    lines.append(f"{m.name}_max {repr(s['max'])}")
        return "\n".join(lines) + "\n"


registry = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return registry.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return registry.gauge(name, help)


def histogram(name: str, help: str = "",
              max_samples: int = 2048) -> Histogram:
    return registry.histogram(name, help, max_samples=max_samples)


# ---------------------------------------------------------------------------
# Device memory gauges
# ---------------------------------------------------------------------------
def sample_device_memory() -> None:
    """Refresh the device-memory gauges from the live jax client.  Never
    raises: backends without memory_stats (CPU) just contribute the
    live-array total."""
    try:
        import jax
    except Exception:
        return
    g_live = registry.gauge(
        "mx_device_live_array_bytes",
        "total bytes of live jax arrays (all devices)")
    try:
        live = jax.live_arrays()
        g_live.set(sum(getattr(a, "nbytes", 0) or 0 for a in live))
    except Exception:
        pass
    try:
        g_use = registry.gauge("mx_device_bytes_in_use",
                               "per-device bytes in use (memory_stats)")
        g_peak = registry.gauge("mx_device_peak_bytes_in_use",
                                "per-device peak bytes (memory_stats)")
        for d in jax.devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                continue
            if not stats:
                continue
            dev = f"{d.platform}:{d.id}"
            if "bytes_in_use" in stats:
                g_use.set(stats["bytes_in_use"], device=dev)
            if "peak_bytes_in_use" in stats:
                g_peak.set(stats["peak_bytes_in_use"], device=dev)
    except Exception:
        pass


# ---------------------------------------------------------------------------
# Compile instrumentation
# ---------------------------------------------------------------------------
def instrument_jit(where: str, jitted: Callable) -> Callable:
    """Wrap a ``jax.jit`` callable so compile-cache behavior is published
    on the COMPILE topic.  When the pjit object exposes ``_cache_size``,
    per-shape recompiles are detected exactly (the cache grew across the
    call → miss, with the blocking trace+compile seconds); otherwise the
    first invocation counts as the one miss.  Zero-subscriber calls go
    straight through."""
    size_fn = getattr(jitted, "_cache_size", None)
    state = {"first": True}

    def call(*args, **kwargs):
        if not COMPILE.subscribers:
            return jitted(*args, **kwargs)
        if size_fn is not None:
            try:
                before = size_fn()
            except Exception:
                before = None
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            dt = time.perf_counter() - t0
            grew = None
            if before is not None:
                try:
                    grew = size_fn() > before
                except Exception:
                    grew = None
            if grew is None:
                grew = state["first"]
            state["first"] = False
            if grew:
                COMPILE.publish(where=where, event="miss", seconds=dt)
            else:
                COMPILE.publish(where=where, event="hit")
            return out
        if state["first"]:
            state["first"] = False
            t0 = time.perf_counter()
            out = jitted(*args, **kwargs)
            COMPILE.publish(where=where, event="miss",
                            seconds=time.perf_counter() - t0)
            return out
        COMPILE.publish(where=where, event="hit")
        return jitted(*args, **kwargs)

    call.__wrapped__ = jitted
    return call


# ---------------------------------------------------------------------------
# Collector: the default subscribers that turn bus events into metrics
# ---------------------------------------------------------------------------
_started = False
_m: Dict[str, object] = {}


def _metrics_init():
    c, h = registry.counter, registry.histogram
    _m["ops"] = c("mx_op_dispatch_total",
                  "eager ops dispatched, by op name")
    _m["op_seconds"] = h("mx_op_seconds",
                         "synchronous per-op seconds (profiler-timed path)")
    _m["sync"] = c("mx_sync_block_total",
                   "blocking sync calls (wait_to_read/asnumpy), by kind")
    _m["h2d"] = c("mx_transfer_h2d_bytes_total",
                  "host->device transfer bytes")
    _m["d2h"] = c("mx_transfer_d2h_bytes_total",
                  "device->host transfer bytes")
    _m["compile"] = c("mx_compile_total", "XLA compiles, by site")
    _m["compile_hit"] = c("mx_compile_cache_hits_total",
                          "compiled-executable cache hits, by site")
    _m["compile_miss"] = c("mx_compile_cache_misses_total",
                           "compiled-executable cache misses, by site")
    _m["compile_seconds"] = h("mx_compile_seconds",
                              "blocking trace+compile seconds")
    _m["kv_calls"] = c("mx_kvstore_calls_total",
                       "kvstore calls, by op (push/pull/pushpull)")
    _m["kv_push_bytes"] = c("mx_kvstore_push_bytes_total",
                            "bytes pushed into the kvstore")
    _m["kv_pull_bytes"] = c("mx_kvstore_pull_bytes_total",
                            "bytes pulled out of the kvstore")
    _m["kv_push_seconds"] = h("mx_kvstore_push_seconds",
                              "kvstore push latency")
    _m["kv_pull_seconds"] = h("mx_kvstore_pull_seconds",
                              "kvstore pull latency")
    _m["kv_pushpull_seconds"] = h("mx_kvstore_pushpull_seconds",
                                  "kvstore fused push+pull latency")
    _m["steps"] = c("mx_trainer_steps_total", "trainer optimization steps")
    _m["step_seconds"] = h("mx_trainer_step_seconds",
                           "trainer step dispatch seconds")
    _m["update_seconds"] = h("mx_trainer_update_seconds",
                             "trainer update dispatch seconds")
    _m["batches"] = c("mx_dataloader_batches_total",
                      "dataloader batches fetched")
    _m["fetch_wait"] = h("mx_dataloader_fetch_wait_seconds",
                         "consumer wait per dataloader batch")


_op_keys: Dict[str, tuple] = {}   # op name -> label key, spares the hot
                                  # path the kwargs/sort work of inc()


def _on_op_dispatch(name):
    key = _op_keys.get(name)
    if key is None:
        key = _op_keys[name] = (("op", name),)
    c = _m["ops"]
    with c._lock:
        c._values[key] = c._values.get(key, 0.0) + 1.0


def _on_op_timed(name, seconds):
    _m["op_seconds"].observe(seconds)


def _on_sync(kind):
    _m["sync"].inc(kind=kind)


def _on_transfer(direction, nbytes):
    _m["h2d" if direction == "h2d" else "d2h"].inc(nbytes)


def _on_compile(where="?", event="miss", seconds=None):
    if event == "miss":
        _m["compile"].inc(site=where)
        _m["compile_miss"].inc(site=where)
        if seconds is not None:
            _m["compile_seconds"].observe(seconds)
    else:
        _m["compile_hit"].inc(site=where)


def _on_kvstore(op="push", nbytes=0, seconds=0.0):
    _m["kv_calls"].inc(op=op)
    if op == "push" and nbytes:
        _m["kv_push_bytes"].inc(nbytes)
    elif op == "pull" and nbytes:
        _m["kv_pull_bytes"].inc(nbytes)
    key = f"kv_{op}_seconds"
    if key in _m:
        _m[key].observe(seconds)


def _on_trainer(phase="step", seconds=0.0):
    if phase == "step":
        _m["steps"].inc()
        _m["step_seconds"].observe(seconds)
    else:
        _m["update_seconds"].observe(seconds)


def _on_dataloader(seconds=0.0):
    _m["batches"].inc()
    _m["fetch_wait"].observe(seconds)


_HANDLERS = (
    (OP_DISPATCH, _on_op_dispatch),
    (OP_TIMED, _on_op_timed),
    (SYNC, _on_sync),
    (TRANSFER, _on_transfer),
    (COMPILE, _on_compile),
    (KVSTORE, _on_kvstore),
    (TRAINER, _on_trainer),
    (DATALOADER, _on_dataloader),
)


def start() -> None:
    """Begin collecting: subscribe the metric handlers to every runtime
    topic.  Idempotent."""
    global _started
    if _started:
        return
    _metrics_init()
    for topic, fn in _HANDLERS:
        # OP_TIMED passively: the collector must never itself force the
        # per-op syncs that feed it — mx_op_seconds only fills while the
        # profiler (an active subscriber) has the timed path on
        topic.subscribe(fn, passive=topic is OP_TIMED)
    _started = True


def stop() -> None:
    """Detach the collector (metric values are kept; see reset())."""
    global _started
    for topic, fn in _HANDLERS:
        topic.unsubscribe(fn)
    _started = False


def enabled() -> bool:
    return _started


def reset() -> None:
    """Zero all metric values."""
    registry.reset()


# ---------------------------------------------------------------------------
# Exporters (module-level conveniences over the default registry)
# ---------------------------------------------------------------------------
def snapshot(include_memory: bool = True) -> dict:
    """JSON-ready dict of every metric; refreshes device-memory gauges
    first (when collecting)."""
    if _started and include_memory:
        sample_device_memory()
    out = registry.snapshot()
    out["enabled"] = _started
    return out


def render_prometheus(include_memory: bool = True) -> str:
    """Prometheus text exposition of every metric."""
    if _started and include_memory:
        sample_device_memory()
    return registry.render_prometheus()


def counters_flat() -> Dict[str, float]:
    return registry.counters_flat()


def dump(path: str, fmt: Optional[str] = None) -> None:
    """Write the current metrics to ``path``: Prometheus text when ``fmt``
    is 'prometheus' (or the path ends in .prom/.txt), JSON otherwise."""
    if fmt is None:
        fmt = "prometheus" if path.endswith((".prom", ".txt")) else "json"
    with open(path, "w") as f:
        if fmt == "prometheus":
            f.write(render_prometheus())
        else:
            json.dump(snapshot(), f, indent=2, default=str)
            f.write("\n")


# ---------------------------------------------------------------------------
# Env autostart (reference parity with MXNET_PROFILER_AUTOSTART)
# ---------------------------------------------------------------------------
_dump_path = getenv("MXNET_TELEMETRY_DUMP")
if _dump_path:
    def _dump_at_exit(path=_dump_path):
        try:
            dump(path)
        except Exception:
            pass
    atexit.register(_dump_at_exit)

if getenv_bool("MXNET_TELEMETRY", False):
    start()
