"""``mx.nd``: the eager NDArray API (reference: python/mxnet/ndarray/).

Where the reference code-generates op wrappers at import time from C-API op
introspection (python/mxnet/ndarray/register.py), here the ops are plain
Python functions in ``ops.py`` re-exported into this namespace — same surface,
no codegen step needed."""
from .ndarray import (NDArray, array, zeros, ones, empty, full, arange, eye,
                      linspace, from_jax, concatenate, waitall)
from .ops import *  # noqa: F401,F403
from .ops import __all__ as _ops_all
from .nn import *  # noqa: F401,F403
from .nn import __all__ as _nn_all
from .optimizer_ops import *  # noqa: F401,F403
from .optimizer_ops import __all__ as _opt_all
from .ops_ext import *  # noqa: F401,F403
from .ops_ext import __all__ as _ext_all
from . import random  # noqa: F401
from . import ops as op  # alias: mx.nd.op.xxx parity
from . import utils  # noqa: F401
from . import contrib  # noqa: F401
from . import sparse  # noqa: F401
from .utils import save, load, load_frombuffer  # noqa: F401

__all__ = (["NDArray", "array", "zeros", "ones", "empty", "full", "arange",
            "eye", "linspace", "from_jax", "concatenate", "waitall", "random",
            "op", "utils", "save", "load", "load_frombuffer", "sparse"]
           + list(_ops_all) + list(_nn_all) + list(_opt_all)
           + list(_ext_all))
