"""NDArray: the imperative tensor, TPU-native.

Re-design of the reference NDArray (reference: include/mxnet/ndarray.h,
src/ndarray/ndarray.cc, python/mxnet/ndarray/ndarray.py).  Design mapping:

* reference ``NDArray::Chunk`` + Storage manager  →  a ``jax.Array`` committed
  to the context's device (XLA/PJRT owns allocation & pooling).
* reference dependency-engine var + async push    →  jax's async dispatch;
  every op call returns immediately with a lazily-computed ``jax.Array``;
  ``wait_to_read`` == ``block_until_ready``.  Engine-thread exceptions
  surface at the next blocking call, matching the reference's deferred
  rethrow (reference: src/engine/threaded_engine.cc ThrowException).
* in-place mutation (``a[:]=``, ``a+=b``)         →  functional replacement of
  the wrapped array (``x.at[...]``-style); recorded autograd closures capture
  values at record time, so later mutation never corrupts the tape — strictly
  safer than the reference's version-counter scheme.
* the per-op engine push overhead that motivated hybridize() in the reference
  is gone: eager jnp ops dispatch pre-compiled XLA executables; ``hybridize``
  still exists and fuses whole graphs (see gluon/block.py).

Autograd integration lives in ``incubator_mxnet_tpu.autograd``; ``_invoke``
below is the single funnel every op goes through (the analog of the reference
``Imperative::Invoke``, reference: src/imperative/imperative.cc).
"""
from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from .. import telemetry as _telemetry

# runtime event topics (multi-subscriber; see telemetry.py).  Bound once at
# import so the hot path pays one attribute load per check.
_OP_DISPATCH = _telemetry.OP_DISPATCH
_OP_TIMED = _telemetry.OP_TIMED
_SYNC = _telemetry.SYNC
_TRANSFER = _telemetry.TRANSFER

__all__ = ["NDArray", "array", "zeros", "ones", "empty", "full", "arange",
           "eye", "linspace", "from_jax", "concatenate", "waitall"]

# set lazily to break the ndarray <-> autograd import cycle
_autograd = None


def _ag():
    global _autograd
    if _autograd is None:
        from .. import autograd as m
        _autograd = m
    return _autograd


def _jnp():
    import jax.numpy as jnp
    return jnp


def _is_inexact(x) -> bool:
    import jax.numpy as jnp
    return jnp.issubdtype(x.dtype, jnp.inexact)


class NDArray:
    """An n-dimensional array on a device context, with autograd support.

    Wraps a ``jax.Array``.  API models the reference's
    python/mxnet/ndarray/ndarray.py NDArray.
    """

    __slots__ = ("_data", "_ctx", "_ag_node", "_ag_idx", "_require_grad",
                 "_grad", "_grad_req", "__weakref__")

    # let our dunders win over numpy's when mixed with np scalars/arrays
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None):
        self._data = data
        self._ctx = ctx if ctx is not None else current_context()
        self._ag_node = None      # tape node that produced this array
        self._ag_idx = 0          # output index within that node
        self._require_grad = False
        self._grad = None
        self._grad_req = "null"

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(_np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context
    device = context

    @property
    def stype(self) -> str:
        return "default"

    def tostype(self, stype: str):
        """Convert storage type (reference: NDArray.tostype)."""
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp._from_dense_jax(self._data, stype, ctx=self._ctx)

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def grad_req(self) -> str:
        return self._grad_req

    @property
    def T(self) -> "NDArray":
        from . import ops
        return ops.transpose(self)

    # ------------------------------------------------------------------
    # materialization / sync
    # ------------------------------------------------------------------
    def asnumpy(self) -> _np.ndarray:
        """Block and copy to host (reference: NDArray::SyncCopyToCPU)."""
        if _SYNC.subscribers:
            _SYNC.publish("asnumpy")
        with _telemetry.trace_span("sync:asnumpy", cat="sync"):
            out = _np.asarray(self._data)
        if _TRANSFER.subscribers:
            _TRANSFER.publish("d2h", out.nbytes)
        return out

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def wait_to_read(self):
        """Block until the async computation producing this array finishes
        (reference: NDArray::WaitToRead via engine WaitForVar)."""
        if _SYNC.subscribers:
            _SYNC.publish("wait_to_read")
        with _telemetry.trace_span("sync:wait_to_read", cat="sync"):
            self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kw):
        return self._data.__dlpack__(**kw)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    # ------------------------------------------------------------------
    # conversion / copies
    # ------------------------------------------------------------------
    def astype(self, dtype, copy: bool = True) -> "NDArray":
        if not copy and _np.dtype(dtype) == self.dtype:
            return self
        from . import ops
        return ops.cast(self, dtype)

    def copy(self) -> "NDArray":
        return self.copyto(self._ctx)

    def copyto(self, other) -> "NDArray":
        """Copy to a Context (new array) or into another NDArray
        (reference: CopyFromTo, src/ndarray/ndarray.cc)."""
        import jax
        if isinstance(other, Context):
            dev = other.jax_device()
            return NDArray(jax.device_put(self._data, dev), ctx=Context(other))
        if isinstance(other, NDArray):
            if other.shape != self.shape:
                raise MXNetError(
                    f"copyto shape mismatch {self.shape} vs {other.shape}")
            dev = other._ctx.jax_device()
            other._set_data(jax.device_put(
                self._data.astype(other._data.dtype), dev))
            # overwriting cuts the target's tape history, like __setitem__
            other._ag_node = None
            return other
        raise TypeError(f"copyto does not support type {type(other)}")

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context
    def to_device(self, ctx):
        return self.as_in_context(ctx)

    def as_nd_ndarray(self):
        return self

    def tolist(self):
        return self.asnumpy().tolist()

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate a gradient buffer and mark this array as a variable
        (reference: python/mxnet/ndarray/ndarray.py attach_grad →
        MXAutogradMarkVariables).  ``stype='row_sparse'`` allocates a sparse
        grad buffer — the Embedding sparse_grad path."""
        if grad_req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {grad_req!r}")
        jnp = _jnp()
        self._require_grad = grad_req != "null"
        self._grad_req = grad_req
        if stype is not None and stype != "default":
            from . import sparse as _sp
            self._grad = _sp.zeros(stype, self.shape, ctx=self._ctx,
                                   dtype=self.dtype)
        else:
            self._grad = NDArray(jnp.zeros(self.shape, self.dtype),
                                 ctx=self._ctx)
        # a variable is a fresh tape leaf: cut any history
        self._ag_node = None
        self._ag_idx = 0

    def detach(self) -> "NDArray":
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph: bool = False,
                 train_mode: bool = True):
        """Run reverse-mode autodiff from this array
        (reference: MXAutogradBackwardEx → Imperative::Backward)."""
        _ag().backward([self], [out_grad] if out_grad is not None else None,
                       retain_graph=retain_graph, train_mode=train_mode)

    def zero_grad(self):
        if self._grad is None:
            return
        from . import sparse as _sp
        if isinstance(self._grad, _sp.BaseSparseNDArray):
            self._grad._replace_with(
                _sp.zeros(self._grad.stype, self.shape, ctx=self._ctx,
                          dtype=self.dtype))
        else:
            self._grad._set_data(_jnp().zeros(self.shape, self.dtype))

    # internal: replace wrapped buffer (in-place semantics)
    def _set_data(self, jarr):
        self._data = jarr

    def _tape_entry_active(self) -> bool:
        """Does grad flow through this array? (it's a marked variable or was
        produced by a recorded op)"""
        return self._require_grad or self._ag_node is not None

    # ------------------------------------------------------------------
    # shape manipulation (methods mirror reference NDArray methods)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape") is not None:
            shape = tuple(kwargs["shape"])
        new_shape = _expand_reshape(self.shape, shape)
        return _invoke(lambda x: _jnp().reshape(x, new_shape), [self],
                       name="reshape")

    def reshape_like(self, other: "NDArray") -> "NDArray":
        return self.reshape(other.shape)

    def flatten(self) -> "NDArray":
        """Collapse to 2D keeping dim0 (reference Flatten op semantics)."""
        n = self.shape[0] if self.ndim else 1
        return self.reshape(n, -1)

    def transpose(self, *axes) -> "NDArray":
        from . import ops
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return ops.transpose(self, axes=axes if axes else None)

    def swapaxes(self, a1: int, a2: int) -> "NDArray":
        from . import ops
        return ops.swapaxes(self, a1, a2)

    def expand_dims(self, axis: int) -> "NDArray":
        from . import ops
        return ops.expand_dims(self, axis=axis)

    def squeeze(self, axis=None) -> "NDArray":
        from . import ops
        return ops.squeeze(self, axis=axis)

    def broadcast_to(self, shape) -> "NDArray":
        from . import ops
        return ops.broadcast_to(self, shape)

    def broadcast_like(self, other) -> "NDArray":
        return self.broadcast_to(other.shape)

    def slice(self, begin, end, step=None) -> "NDArray":
        from . import ops
        return ops.slice(self, begin, end, step)

    def slice_axis(self, axis, begin, end) -> "NDArray":
        from . import ops
        return ops.slice_axis(self, axis, begin, end)

    def take(self, indices, axis=0, mode="clip") -> "NDArray":
        from . import ops
        return ops.take(self, indices, axis=axis, mode=mode)

    def tile(self, reps) -> "NDArray":
        from . import ops
        return ops.tile(self, reps)

    def repeat(self, repeats, axis=None) -> "NDArray":
        from . import ops
        return ops.repeat(self, repeats, axis=axis)

    def flip(self, axis) -> "NDArray":
        from . import ops
        return ops.flip(self, axis)

    def pad(self, mode="constant", pad_width=None, constant_value=0):
        from . import ops
        return ops.pad(self, mode=mode, pad_width=pad_width,
                       constant_value=constant_value)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        from . import ops
        return ops.split(self, num_outputs, axis=axis,
                         squeeze_axis=squeeze_axis)

    def diag(self, k=0):
        from . import ops
        return ops.diag(self, k=k)

    # reductions / math as methods (subset mirroring the reference)
    def _method(opname):  # noqa: N805 - helper used at class build time
        def f(self, *a, **kw):
            from . import ops
            return getattr(ops, opname)(self, *a, **kw)
        f.__name__ = opname
        return f

    sum = _method("sum")
    nansum = _method("nansum")
    mean = _method("mean")
    max = _method("max")
    min = _method("min")
    prod = _method("prod")
    nanprod = _method("nanprod")
    argmax = _method("argmax")
    argmin = _method("argmin")
    argsort = _method("argsort")
    sort = _method("sort")
    topk = _method("topk")
    clip = _method("clip")
    abs = _method("abs")
    sign = _method("sign")
    exp = _method("exp")
    expm1 = _method("expm1")
    log = _method("log")
    log1p = _method("log1p")
    log2 = _method("log2")
    log10 = _method("log10")
    sqrt = _method("sqrt")
    rsqrt = _method("rsqrt")
    cbrt = _method("cbrt")
    square = _method("square")
    reciprocal = _method("reciprocal")
    sin = _method("sin")
    cos = _method("cos")
    tan = _method("tan")
    arcsin = _method("arcsin")
    arccos = _method("arccos")
    arctan = _method("arctan")
    sinh = _method("sinh")
    cosh = _method("cosh")
    tanh = _method("tanh")
    arcsinh = _method("arcsinh")
    arccosh = _method("arccosh")
    arctanh = _method("arctanh")
    relu = _method("relu")
    sigmoid = _method("sigmoid")
    softmax = _method("softmax")
    log_softmax = _method("log_softmax")
    round = _method("round")
    rint = _method("rint")
    floor = _method("floor")
    ceil = _method("ceil")
    trunc = _method("trunc")
    fix = _method("fix")
    norm = _method("norm")
    one_hot = _method("one_hot")
    dot = _method("dot")

    del _method

    # ------------------------------------------------------------------
    # operators
    # ------------------------------------------------------------------
    def _binop(self, other, opname, reverse=False):
        from . import ops
        fn = getattr(ops, opname)
        if reverse:
            return fn(other, self)
        return fn(self, other)

    def __add__(self, o):  return self._binop(o, "add")
    def __radd__(self, o): return self._binop(o, "add", True)
    def __sub__(self, o):  return self._binop(o, "subtract")
    def __rsub__(self, o): return self._binop(o, "subtract", True)
    def __mul__(self, o):  return self._binop(o, "multiply")
    def __rmul__(self, o): return self._binop(o, "multiply", True)
    def __truediv__(self, o):  return self._binop(o, "divide")
    def __rtruediv__(self, o): return self._binop(o, "divide", True)
    def __floordiv__(self, o): return self._binop(o, "floor_divide")
    def __rfloordiv__(self, o): return self._binop(o, "floor_divide", True)
    def __mod__(self, o):  return self._binop(o, "mod")
    def __rmod__(self, o): return self._binop(o, "mod", True)
    def __pow__(self, o):  return self._binop(o, "power")
    def __rpow__(self, o): return self._binop(o, "power", True)
    def __matmul__(self, o): return self._binop(o, "matmul")
    def __rmatmul__(self, o): return self._binop(o, "matmul", True)
    def __neg__(self):
        return self._binop(-1, "multiply")
    def __abs__(self):
        from . import ops
        return ops.abs(self)

    def __eq__(self, o):  return self._binop(o, "equal")            # noqa: E704
    def __ne__(self, o):  return self._binop(o, "not_equal")        # noqa: E704
    def __gt__(self, o):  return self._binop(o, "greater")          # noqa: E704
    def __ge__(self, o):  return self._binop(o, "greater_equal")    # noqa: E704
    def __lt__(self, o):  return self._binop(o, "lesser")           # noqa: E704
    def __le__(self, o):  return self._binop(o, "lesser_equal")     # noqa: E704

    __hash__ = None  # mutable container semantics, same as reference

    # in-place: functional replacement of the buffer
    def _iop(self, other, opname):
        res = self._binop(other, opname)
        self._set_data(res._data.astype(self._data.dtype))
        # in-place result keeps the history of the *result* for autograd
        self._ag_node, self._ag_idx = res._ag_node, res._ag_idx
        return self

    def __iadd__(self, o): return self._iop(o, "add")
    def __isub__(self, o): return self._iop(o, "subtract")
    def __imul__(self, o): return self._iop(o, "multiply")
    def __itruediv__(self, o): return self._iop(o, "divide")
    def __imod__(self, o): return self._iop(o, "mod")

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    def _norm_key(self, key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(k._data if isinstance(k, NDArray) else k for k in key)
        return key

    def __getitem__(self, key):
        key = self._norm_key(key)
        return _invoke(lambda x: x[key], [self], name="getitem")

    def __setitem__(self, key, value):
        """In-place write (reference: NDArray slice assign).  Functional
        under the hood via ``.at[key].set``."""
        jnp = _jnp()
        key = self._norm_key(key)
        if isinstance(value, NDArray):
            value = value._data
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            new = jnp.broadcast_to(jnp.asarray(value, self._data.dtype),
                                   self.shape)
        else:
            new = self._data.at[key].set(
                jnp.asarray(value).astype(self._data.dtype))
        self._set_data(new)
        # plain write outside a recorded op cuts this array's tape history
        self._ag_node = None

    # ------------------------------------------------------------------
    def __repr__(self):
        return (f"\n{self.asnumpy()!r}\n<NDArray {'x'.join(map(str, self.shape))}"
                f" @{self._ctx}>")


# ---------------------------------------------------------------------------
# reshape with MXNet's special codes (reference:
# python/mxnet/ndarray/ndarray.py NDArray.reshape doc: 0, -1, -2, -3, -4)
# ---------------------------------------------------------------------------
def _expand_reshape(old: Sequence[int], new: Sequence[int]):
    out = []
    i = 0  # index into old
    j = 0
    new = list(new)
    while j < len(new):
        d = new[j]
        if d == 0:           # copy this dim
            out.append(old[i]); i += 1
        elif d == -2:        # copy all remaining dims
            out.extend(old[i:]); i = len(old)
        elif d == -3:        # merge two consecutive dims
            out.append(old[i] * old[i + 1]); i += 2
        elif d == -4:        # split one dim into the next two new dims
            a, b = new[j + 1], new[j + 2]
            if a == -1:
                a = old[i] // b
            if b == -1:
                b = old[i] // a
            out.extend([a, b]); i += 1; j += 2
        elif d == -1:
            out.append(-1); i += 1
        else:
            out.append(d); i += 1
        j += 1
    return tuple(out)


# ---------------------------------------------------------------------------
# _invoke: the op funnel (analog of Imperative::Invoke,
# reference: src/imperative/imperative.cc + imperative_utils.h PushFCompute)
# ---------------------------------------------------------------------------
# Dispatch instrumentation (reference analogs: profiler hooks bracket
# ThreadedEngine::ExecuteOprBlock, src/profiler/profiler.h; and
# MXNET_ENGINE_TYPE=NaiveEngine forces synchronous execution as the
# debugging oracle, src/engine/naive_engine.cc).  Observation is
# multi-subscriber via the telemetry event bus: OP_TIMED subscribers
# (the profiler) force every op to block until computed so measured time
# = true op time; OP_DISPATCH subscribers (the telemetry collector) get a
# cheap count-only event that never forces a sync.  The legacy
# single-slot ``_op_observer`` is still honored for third-party code.
_op_observer = None       # legacy single slot: callback(op_name, seconds)
_sync_dispatch = False    # set by mx.engine for NaiveEngine parity
_TRACER = None            # jax.core.Tracer, bound on first instrumented op


def _tracer_cls():
    global _TRACER
    if _TRACER is None:
        import jax
        _TRACER = jax.core.Tracer
    return _TRACER


def _invoke(fun: Callable, inputs: Sequence[NDArray], *,
            name: str = "op", differentiable: bool = True):
    # the timed path below costs a per-op device sync — enter it only for
    # subscribers that asked to force it (the profiler), not for passive
    # listeners like the telemetry collector
    if _op_observer is None and not _sync_dispatch \
            and not _OP_TIMED.forcing:
        out = _invoke_async(fun, inputs, name=name,
                            differentiable=differentiable)
        if _OP_DISPATCH.subscribers:
            first = out[0] if type(out) is list else out
            # traced ops run once at compile time, not per step — counting
            # them would skew dispatch rates (all outputs of one op are
            # tracers or none are, so checking the first suffices)
            if not isinstance(first._data, _TRACER or _tracer_cls()):
                _OP_DISPATCH.publish(name)
        return out
    import time as _time
    t0 = _time.perf_counter()
    out = _invoke_async(fun, inputs, name=name,
                        differentiable=differentiable)
    outs = out if isinstance(out, list) else [out]
    # inside a jit trace the outputs are Tracers: blocking is impossible
    # and per-op timing meaningless — the compiled program is profiled as
    # one unit (XLA trace), so skip instrumentation there
    if any(isinstance(o._data, _TRACER or _tracer_cls()) for o in outs):
        return out
    for o in outs:
        # block directly: routing through wait_to_read would count every
        # profiler-forced sync as a user sync in the SYNC stream
        o._data.block_until_ready()
    seconds = _time.perf_counter() - t0
    if _op_observer is not None:
        _op_observer(name, seconds)
    if _OP_TIMED.subscribers:
        _OP_TIMED.publish(name, seconds)
    if _OP_DISPATCH.subscribers:
        _OP_DISPATCH.publish(name)
    return out


def _invoke_async(fun: Callable, inputs: Sequence[NDArray], *,
                  name: str = "op", differentiable: bool = True):
    """Run ``fun(*jax_arrays) -> jax_array | tuple`` eagerly, recording on the
    autograd tape when needed.  Returns NDArray or list of NDArrays (list iff
    ``fun`` returns a tuple/list)."""
    ag = _ag()
    jarrs = [i._data for i in inputs]
    ctx = inputs[0]._ctx if inputs else current_context()

    record = (differentiable and ag.is_recording()
              and any(i._tape_entry_active() for i in inputs))
    if not record:
        try:
            out = fun(*jarrs)
        except Exception as e:  # normalize backend errors
            raise MXNetError(f"{name}: {e}") from e
        return _wrap_out(out, ctx)

    # --- recorded path: only inexact-dtype inputs participate in grad
    diff_idx = [k for k, a in enumerate(jarrs) if _is_inexact(a)]

    def fun_diff(*diff_args):
        full = list(jarrs)
        for k, a in zip(diff_idx, diff_args):
            full[k] = a
        return fun(*full)

    import jax
    diff_args = [jarrs[k] for k in diff_idx]
    out, vjp_fn = jax.vjp(fun_diff, *diff_args)
    node = ag._TapeNode(
        fun=fun_diff,
        inputs=[inputs[k] for k in diff_idx],
        vjp_fn=vjp_fn,
        out_is_tuple=isinstance(out, (tuple, list)),
        name=name,
    )
    outs = _wrap_out(out, ctx)
    out_list = outs if isinstance(outs, list) else [outs]
    node.out_avals = [(o.shape, o.dtype) for o in out_list]
    for i, o in enumerate(out_list):
        if _is_inexact(o._data):
            o._ag_node = node
            o._ag_idx = i
    return outs


def _wrap_out(out, ctx):
    if isinstance(out, (tuple, list)):
        return [NDArray(o, ctx=ctx) for o in out]
    return NDArray(out, ctx=ctx)


# ---------------------------------------------------------------------------
# creation functions (reference: python/mxnet/ndarray/ndarray.py + utils)
# ---------------------------------------------------------------------------
def _place(jarr, ctx: Optional[Context]):
    import jax
    ctx = ctx if ctx is not None else current_context()
    return NDArray(jax.device_put(jarr, ctx.jax_device()), ctx=ctx)


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create from python/numpy data.  Parity: the reference defaults to
    float32 for non-ndarray sources (python/mxnet/ndarray/ndarray.py array);
    numpy sources keep their dtype (64-bit narrowed to 32 — jax x64 is off)."""
    jnp = _jnp()
    if isinstance(source, NDArray):
        src = source._data
        if dtype is None:
            dtype = src.dtype
    elif isinstance(source, _np.ndarray):
        src = source
        if dtype is None:
            dtype = {_np.dtype(_np.float64): _np.float32,
                     _np.dtype(_np.int64): _np.int32,
                     _np.dtype(_np.uint64): _np.uint32}.get(src.dtype,
                                                            src.dtype)
    else:
        src = _np.asarray(source)
        if dtype is None:
            dtype = (_np.float32 if src.dtype.kind in "fiu"
                     else src.dtype)
    if _telemetry.tracer.active:
        with _telemetry.trace_span("transfer:h2d", cat="transfer"):
            out = _place(jnp.asarray(src, dtype=dtype), ctx)
    else:
        out = _place(jnp.asarray(src, dtype=dtype), ctx)
    if _TRANSFER.subscribers and not isinstance(source, NDArray):
        _TRANSFER.publish("h2d", out._data.nbytes)
    return out


def from_jax(jarr, ctx: Optional[Context] = None) -> NDArray:
    """Zero-copy wrap of an existing jax.Array."""
    return NDArray(jarr, ctx=ctx if ctx is not None else current_context())


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None) -> NDArray:
    jnp = _jnp()
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.zeros(shape, dtype or _np.float32), ctx)


def ones(shape, ctx=None, dtype=None) -> NDArray:
    jnp = _jnp()
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.ones(shape, dtype or _np.float32), ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    jnp = _jnp()
    if isinstance(shape, int):
        shape = (shape,)
    return _place(jnp.full(shape, val, dtype or _np.float32), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    jnp = _jnp()
    a = jnp.arange(start, stop, step, dtype or _np.float32)
    if repeat != 1:
        a = jnp.repeat(a, repeat)
    return _place(a, ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    jnp = _jnp()
    return _place(jnp.eye(N, M if M else N, k=k, dtype=dtype or _np.float32), ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None) -> NDArray:
    jnp = _jnp()
    return _place(jnp.linspace(start, stop, num, endpoint=endpoint,
                               dtype=dtype or _np.float32), ctx)


def concatenate(arrays, axis=0):
    from . import ops
    return ops.concat(*arrays, dim=axis)


def waitall():
    """Block until all async computation completes (reference:
    MXNDArrayWaitAll / Engine WaitForAll)."""
    import jax
    (jax.device_put(0.0) + 0).block_until_ready()
