"""Operator-corpus extensions: linalg family, flat samplers, spatial
ops, and assorted tensor ops (reference: src/operator/tensor/la_op.cc,
src/operator/random/sample_op.cc, src/operator/spatial_transformer.cc,
bilinear_sampler.cc, roi_pooling.cc, correlation.cc, lrn.cc,
src/operator/tensor/matrix_op.cc depth/space ops, contrib fft).

Same design as ops.py: each op is a pure jnp/lax function funneled
through ``_invoke`` (async dispatch + tape autograd via jax VJP).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _invoke

__all__: list = []  # populated at bottom


def _jnp():
    import jax.numpy as jnp
    return jnp


def _nd(x):
    from .ndarray import array as _array
    return x if isinstance(x, NDArray) else _array(x)


# ---------------------------------------------------------------------------
# linalg_* family (reference: src/operator/tensor/la_op.cc).  Batched over
# leading dims like the reference; compute in the input dtype.
# ---------------------------------------------------------------------------
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    """C_out = alpha * op(A) @ op(B) + beta * C.  ``axis`` relocates the
    matrix-row axis as in the reference (default -2)."""
    def fn(a, b, c):
        jnp = _jnp()
        if axis != -2:
            a = jnp.moveaxis(a, axis, -2)
            b = jnp.moveaxis(b, axis, -2)
            c = jnp.moveaxis(c, axis, -2)
        a = jnp.swapaxes(a, -1, -2) if transpose_a else a
        b = jnp.swapaxes(b, -1, -2) if transpose_b else b
        out = alpha * jnp.matmul(a, b) + beta * c
        return jnp.moveaxis(out, -2, axis) if axis != -2 else out
    return _invoke(fn, [_nd(A), _nd(B), _nd(C)], name="linalg_gemm")


def linalg_syrk(A, transpose=False, alpha=1.0):
    """alpha * A @ A^T (or A^T @ A when transpose)."""
    def fn(a):
        jnp = _jnp()
        at = jnp.swapaxes(a, -1, -2)
        return alpha * (jnp.matmul(at, a) if transpose
                        else jnp.matmul(a, at))
    return _invoke(fn, [_nd(A)], name="linalg_syrk")


def linalg_potrf(A):
    """Cholesky factor (lower) of a PD matrix."""
    def fn(a):
        import jax
        return jax.numpy.linalg.cholesky(a)
    return _invoke(fn, [_nd(A)], name="linalg_potrf")


def linalg_potri(A):
    """Inverse from a Cholesky factor L: (L L^T)^-1."""
    def fn(l):
        jnp = _jnp()
        eye = jnp.broadcast_to(jnp.eye(l.shape[-1], dtype=l.dtype),
                               l.shape)
        import jax
        linv = jax.scipy.linalg.solve_triangular(l, eye, lower=True)
        return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)
    return _invoke(fn, [_nd(A)], name="linalg_potri")


def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B when rightside)."""
    def fn(a, b):
        import jax
        jnp = _jnp()
        if rightside:
            # X A = B  <=>  A^T X^T = B^T
            sol = jax.scipy.linalg.solve_triangular(
                jnp.swapaxes(a, -1, -2), jnp.swapaxes(b, -1, -2),
                lower=not lower, trans=1 if transpose else 0)
            return alpha * jnp.swapaxes(sol, -1, -2)
        return alpha * jax.scipy.linalg.solve_triangular(
            a, b, lower=lower, trans=1 if transpose else 0)
    return _invoke(fn, [_nd(A), _nd(B)], name="linalg_trsm")


def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """alpha * op(tri(A)) @ B (or B @ op(tri(A)))."""
    def fn(a, b):
        jnp = _jnp()
        tri = jnp.tril(a) if lower else jnp.triu(a)
        tri = jnp.swapaxes(tri, -1, -2) if transpose else tri
        return alpha * (jnp.matmul(b, tri) if rightside
                        else jnp.matmul(tri, b))
    return _invoke(fn, [_nd(A), _nd(B)], name="linalg_trmm")


def linalg_det(A):
    def fn(a):
        return _jnp().linalg.det(a)
    return _invoke(fn, [_nd(A)], name="linalg_det")


def linalg_slogdet(A):
    def fn(a):
        sign, logabs = _jnp().linalg.slogdet(a)
        return sign, logabs
    return _invoke(fn, [_nd(A)], name="linalg_slogdet")


def linalg_inverse(A):
    def fn(a):
        return _jnp().linalg.inv(a)
    return _invoke(fn, [_nd(A)], name="linalg_inverse")


def linalg_extractdiag(A, offset=0):
    def fn(a):
        return _jnp().diagonal(a, offset=offset, axis1=-2, axis2=-1)
    return _invoke(fn, [_nd(A)], name="linalg_extractdiag")


def linalg_makediag(A, offset=0):
    def fn(a):
        jnp = _jnp()
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        return base.at[..., r, c].set(a)
    return _invoke(fn, [_nd(A)], name="linalg_makediag")


def _trian_indices(n, offset, lower):
    """Reference semantics (la_op.cc): the offset SIGN selects the
    triangle — offset>0 the upper triangle starting at that diagonal,
    offset<0 the lower one; ``lower`` applies only at offset 0."""
    if offset > 0:
        return _np.triu_indices(n, k=offset)
    if offset < 0:
        return _np.tril_indices(n, k=offset)
    return _np.tril_indices(n) if lower else _np.triu_indices(n)


def linalg_extracttrian(A, offset=0, lower=True):
    """Pack a triangle into a vector (row-major packing)."""
    def fn(a):
        rows, cols = _trian_indices(a.shape[-1], offset, lower)
        return a[..., rows, cols]
    return _invoke(fn, [_nd(A)], name="linalg_extracttrian")


def linalg_maketrian(A, offset=0, lower=True):
    def fn(a):
        jnp = _jnp()
        m = a.shape[-1]
        # m = q(q+1)/2 where q = n - |offset|
        q = int((_np.sqrt(8 * m + 1) - 1) / 2)
        n = q + abs(offset)
        rows, cols = _trian_indices(n, offset, lower)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        return base.at[..., rows, cols].set(a)
    return _invoke(fn, [_nd(A)], name="linalg_maketrian")


# ---------------------------------------------------------------------------
# Flat samplers (reference: src/operator/random/sample_op.cc): per-element
# distribution params as arrays; output shape = param shape (+ shape tail).
# ---------------------------------------------------------------------------
def _sample(name, draw, params, shape=None, dtype="float32"):
    from .. import random as _random
    from ..context import current_context
    nds = [_nd(p) for p in params]
    ctx = nds[0]._ctx if nds else current_context()
    key = _random.new_key(ctx)
    tail = () if shape is None else (
        tuple(shape) if isinstance(shape, (tuple, list)) else (shape,))

    def fn(*ps):
        jnp = _jnp()
        out = draw(key, ps, tail)
        return out.astype(_np.dtype(dtype))
    return _invoke(fn, nds, name=name, differentiable=False)


def sample_uniform(low, high, shape=None, dtype="float32", **kw):
    def draw(key, ps, tail):
        import jax
        low, high = ps
        out_shape = tuple(low.shape) + tail
        u = jax.random.uniform(key, out_shape)
        return low.reshape(low.shape + (1,) * len(tail)) + u * (
            (high - low).reshape(low.shape + (1,) * len(tail)))
    return _sample("sample_uniform", draw, [low, high], shape, dtype)


def sample_normal(mu, sigma, shape=None, dtype="float32", **kw):
    def draw(key, ps, tail):
        import jax
        mu, sigma = ps
        out_shape = tuple(mu.shape) + tail
        z = jax.random.normal(key, out_shape)
        ex = (1,) * len(tail)
        return mu.reshape(mu.shape + ex) + z * sigma.reshape(
            sigma.shape + ex)
    return _sample("sample_normal", draw, [mu, sigma], shape, dtype)


def sample_gamma(alpha, beta, shape=None, dtype="float32", **kw):
    def draw(key, ps, tail):
        import jax
        alpha, beta = ps
        ex = (1,) * len(tail)
        out_shape = tuple(alpha.shape) + tail
        g = jax.random.gamma(key, alpha.reshape(alpha.shape + ex),
                             shape=out_shape)
        return g * beta.reshape(beta.shape + ex)
    return _sample("sample_gamma", draw, [alpha, beta], shape, dtype)


def sample_exponential(lam, shape=None, dtype="float32", **kw):
    def draw(key, ps, tail):
        import jax
        (lam,) = ps
        out_shape = tuple(lam.shape) + tail
        e = jax.random.exponential(key, out_shape)
        return e / lam.reshape(lam.shape + (1,) * len(tail))
    return _sample("sample_exponential", draw, [lam], shape, dtype)


def sample_poisson(lam, shape=None, dtype="float32", **kw):
    def draw(key, ps, tail):
        import jax
        (lam,) = ps
        out_shape = tuple(lam.shape) + tail
        return jax.random.poisson(
            key, lam.reshape(lam.shape + (1,) * len(tail)),
            shape=out_shape).astype(_np.float32)
    return _sample("sample_poisson", draw, [lam], shape, dtype)


def sample_negative_binomial(k, p, shape=None, dtype="float32", **kw):
    def draw(key, ps, tail):
        import jax
        k_, p_ = ps
        ex = (1,) * len(tail)
        out_shape = tuple(k_.shape) + tail
        k1, k2 = jax.random.split(key)
        # NB(k,p) = Poisson(Gamma(k, (1-p)/p))
        lam = jax.random.gamma(key=k1, a=k_.reshape(k_.shape + ex),
                               shape=out_shape) \
            * ((1.0 - p_) / p_).reshape(p_.shape + ex)
        return jax.random.poisson(k2, lam).astype(_np.float32)
    return _sample("sample_negative_binomial", draw, [k, p], shape, dtype)


def sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                       **kw):
    """Categorical draws from (..., K) probabilities (reference:
    sample_multinomial)."""
    from . import random as _rnd
    return _rnd.multinomial(_nd(data), shape=shape, get_prob=get_prob,
                            dtype=dtype)


# ---------------------------------------------------------------------------
# Spatial ops
# ---------------------------------------------------------------------------
def _bilinear_gather(x, gx, gy):
    """Sample (B,C,H,W) at per-pixel float coords gx/gy (B,Ho,Wo), with
    zero padding outside — the shared kernel of BilinearSampler /
    SpatialTransformer / GridGenerator (reference:
    bilinear_sampler.cc BilinearSamplerForward)."""
    jnp = _jnp()
    B, C, H, W = x.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def tap(xi, yi):
        inb = ((xi >= 0) & (xi < W) & (yi >= 0) & (yi < H))
        xi_ = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yi_ = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        # gather per batch: (B,Ho,Wo) indices into (B,C,H,W)
        bidx = jnp.arange(B)[:, None, None]
        v = x[bidx, :, yi_, xi_]          # (B,Ho,Wo,C)
        return v * inb[..., None]
    v00 = tap(x0, y0)
    v01 = tap(x0 + 1, y0)
    v10 = tap(x0, y0 + 1)
    v11 = tap(x0 + 1, y0 + 1)
    wx_ = wx[..., None]
    wy_ = wy[..., None]
    out = (v00 * (1 - wx_) * (1 - wy_) + v01 * wx_ * (1 - wy_)
           + v10 * (1 - wx_) * wy_ + v11 * wx_ * wy_)
    return out.transpose(0, 3, 1, 2)      # (B,C,Ho,Wo)


def BilinearSampler(data, grid, **kw):
    """data (B,C,H,W), grid (B,2,Ho,Wo) with normalized coords in
    [-1,1] (x then y) — reference: bilinear_sampler.cc."""
    def fn(x, g):
        jnp = _jnp()
        B, C, H, W = x.shape
        gx = (g[:, 0] + 1.0) * (W - 1) / 2.0
        gy = (g[:, 1] + 1.0) * (H - 1) / 2.0
        return _bilinear_gather(x, gx, gy)
    return _invoke(fn, [_nd(data), _nd(grid)], name="BilinearSampler")


def GridGenerator(data, transform_type="affine", target_shape=(0, 0),
                  **kw):
    """affine: data (B,6) -> sampling grid (B,2,H,W) over target_shape;
    warp: data (B,2,H,W) flow -> grid (reference: grid_generator.cc)."""
    H, W = target_shape

    def fn(d):
        jnp = _jnp()
        if transform_type == "affine":
            B = d.shape[0]
            theta = d.reshape(B, 2, 3)
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            ones = jnp.ones_like(gx)
            base = jnp.stack([gx, gy, ones], 0).reshape(3, -1)  # (3,HW)
            out = jnp.einsum("bij,jk->bik", theta, base)        # (B,2,HW)
            return out.reshape(B, 2, H, W)
        # warp: displacement field added to the identity grid,
        # normalized per reference (flow in pixels)
        B, _, Hf, Wf = d.shape
        ys = jnp.arange(Hf, dtype=d.dtype)
        xs = jnp.arange(Wf, dtype=d.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        px = gx + d[:, 0]
        py = gy + d[:, 1]
        nx = 2.0 * px / max(Wf - 1, 1) - 1.0
        ny = 2.0 * py / max(Hf - 1, 1) - 1.0
        return jnp.stack([nx, ny], 1)
    return _invoke(fn, [_nd(data)], name="GridGenerator")


def SpatialTransformer(data, loc, target_shape=(0, 0),
                       transform_type="affine", sampler_type="bilinear",
                       **kw):
    """Affine spatial transformer = GridGenerator + BilinearSampler
    (reference: spatial_transformer.cc)."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports affine + bilinear")
    grid = GridGenerator(loc, "affine", target_shape)
    return BilinearSampler(data, grid)


def ROIPooling(data, rois, pooled_size, spatial_scale, **kw):
    """Max-pooling over ROI bins (reference: roi_pooling.cc).  data
    (B,C,H,W); rois (R,5) [batch_idx,x0,y0,x1,y1] image coords."""
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))

    def fn(x, r):
        import jax
        jnp = _jnp()
        B, C, H, W = x.shape
        neg = jnp.finfo(x.dtype).min

        def one_roi(roi):
            bidx = roi[0].astype(jnp.int32)
            x0 = jnp.round(roi[1] * spatial_scale)
            y0 = jnp.round(roi[2] * spatial_scale)
            x1 = jnp.round(roi[3] * spatial_scale)
            y1 = jnp.round(roi[4] * spatial_scale)
            rw = jnp.maximum(x1 - x0 + 1, 1.0)
            rh = jnp.maximum(y1 - y0 + 1, 1.0)
            img = x[bidx]                  # (C,H,W)
            iy = jnp.arange(H, dtype=x.dtype)
            ix = jnp.arange(W, dtype=x.dtype)
            # reference bins OVERLAP on shared boundary pixels:
            # bin i covers [floor(i*rh/ph), ceil((i+1)*rh/ph))
            bins = []
            for i in range(ph):
                ys = y0 + jnp.floor(i * rh / ph)
                ye = y0 + jnp.ceil((i + 1) * rh / ph)
                my = (iy >= ys) & (iy < ye) & (iy >= y0) & (iy <= y1)
                for j in range(pw):
                    xs = x0 + jnp.floor(j * rw / pw)
                    xe = x0 + jnp.ceil((j + 1) * rw / pw)
                    mxv = (ix >= xs) & (ix < xe) & (ix >= x0) & (ix <= x1)
                    m = my[:, None] & mxv[None, :]        # (H,W)
                    # where+max fuses into one reduction under XLA; no
                    # (ph,pw,C,H,W) intermediate is materialized
                    v = jnp.max(jnp.where(m[None], img, neg),
                                axis=(-1, -2))            # (C,)
                    bins.append(jnp.where(m.any(), v, 0.0))
            out = jnp.stack(bins, -1)                     # (C, ph*pw)
            return out.reshape(C, ph, pw)
        return jax.vmap(one_roi)(r)        # (R,C,ph,pw)
    return _invoke(fn, [_nd(data), _nd(rois)], name="ROIPooling")


def Correlation(data1, data2, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True, **kw):
    """Optical-flow correlation layer (reference: correlation.cc),
    single-pixel kernel form: output channel (dy, dx) holds
    mean_c a(y, x) * b(y+dy, x+dx) over the (2m+1)^2 displacement
    window (is_multiply=False: mean |a - b| as in the reference)."""
    if kernel_size != 1 or stride1 != 1 or stride2 != 1 or pad_size != 0:
        raise MXNetError(
            "Correlation: only kernel_size=1, stride1=stride2=1, "
            "pad_size=0 are implemented in this build")
    m = max_displacement

    def fn(a, b):
        jnp = _jnp()
        H, W = b.shape[2], b.shape[3]
        outs = []
        for dy in range(-m, m + 1):
            for dx in range(-m, m + 1):
                # out(y,x) pairs a(y,x) with b(y+dy, x+dx):
                # roll by (-dy,-dx) brings b[y+dy, x+dx] to (y, x)
                shifted = jnp.roll(b, (-dy, -dx), axis=(2, 3))
                # zero positions whose partner fell outside the image
                mask = jnp.ones((H, W), b.dtype)
                if dy > 0:
                    mask = mask.at[H - dy:, :].set(0)
                elif dy < 0:
                    mask = mask.at[:-dy, :].set(0)
                if dx > 0:
                    mask = mask.at[:, W - dx:].set(0)
                elif dx < 0:
                    mask = mask.at[:, :-dx].set(0)
                prod = a * shifted * mask if is_multiply \
                    else jnp.abs(a - shifted) * mask
                outs.append(jnp.mean(prod, axis=1))
        return jnp.stack(outs, 1)
    return _invoke(fn, [_nd(data1), _nd(data2)], name="Correlation")


def DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3),
                          stride=(1, 1), pad=(1, 1), dilate=(1, 1),
                          num_deformable_group=1, num_filter=0, **kw):
    """Deformable convolution v1 (reference:
    src/operator/contrib/deformable_convolution.cc, Dai et al. 2017).

    data (B,C,H,W); offset (B, 2*G*kh*kw, Ho, Wo) — per-output-position
    (dy, dx) displacement for every kernel tap, G deformable groups over
    the channel dim; weight (Cout, C, kh, kw).

    TPU-first shape: one bilinear gather per kernel tap (static kh*kw
    loop) + a single einsum onto the MXU — no im2col buffer, no
    data-dependent control flow."""
    kh, kw = kernel
    G = num_deformable_group
    w_shape = tuple(_nd(weight).shape)
    if num_filter not in (0, w_shape[0]):
        raise MXNetError(
            f"DeformableConvolution: num_filter={num_filter} does not "
            f"match weight.shape[0]={w_shape[0]}")

    def fn(x, off, w, *rest):
        jnp = _jnp()
        B, C, H, W = x.shape
        Ho = (H + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
        Wo = (W + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1
        oy = jnp.arange(Ho) * stride[0] - pad[0]
        ox = jnp.arange(Wo) * stride[1] - pad[1]
        base_y = oy[:, None]                      # (Ho,1)
        base_x = ox[None, :]                      # (1,Wo)
        off = off.reshape(B, G, kh * kw, 2, Ho, Wo)
        cg = C // G
        taps = []
        for k in range(kh * kw):
            ky, kx = divmod(k, kw)
            groups = []
            for g in range(G):
                dy = off[:, g, k, 0]              # (B,Ho,Wo)
                dx = off[:, g, k, 1]
                gy = base_y[None] + ky * dilate[0] + dy
                gx = base_x[None] + kx * dilate[1] + dx
                xg = x[:, g * cg:(g + 1) * cg]
                groups.append(_bilinear_gather(xg, gx, gy))
            taps.append(jnp.concatenate(groups, 1))  # (B,C,Ho,Wo)
        stacked = jnp.stack(taps, 2)              # (B,C,kh*kw,Ho,Wo)
        out = jnp.einsum("bckhw,ock->bohw",
                         stacked, w.reshape(w.shape[0], C, kh * kw))
        if rest:
            out = out + rest[0][None, :, None, None]
        return out
    inputs = [_nd(data), _nd(offset), _nd(weight)]
    if bias is not None:
        inputs.append(_nd(bias))
    return _invoke(fn, inputs, name="DeformableConvolution")


def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    """Local response normalization across channels (reference:
    lrn.cc / AlexNet)."""
    def fn(x):
        jnp = _jnp()
        sq = x * x
        half = nsize // 2
        pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
        acc = sum(pad[:, i:i + x.shape[1]] for i in range(nsize))
        return x / (knorm + alpha / nsize * acc) ** beta
    return _invoke(fn, [_nd(data)], name="LRN")


# ---------------------------------------------------------------------------
# Tensor-op odds and ends
# ---------------------------------------------------------------------------
def depth_to_space(data, block_size):
    def fn(x):
        jnp = _jnp()
        B, C, H, W = x.shape
        b = block_size
        y = x.reshape(B, b, b, C // (b * b), H, W)
        y = y.transpose(0, 3, 4, 1, 5, 2)
        return y.reshape(B, C // (b * b), H * b, W * b)
    return _invoke(fn, [_nd(data)], name="depth_to_space")


def space_to_depth(data, block_size):
    def fn(x):
        jnp = _jnp()
        B, C, H, W = x.shape
        b = block_size
        y = x.reshape(B, C, H // b, b, W // b, b)
        y = y.transpose(0, 3, 5, 1, 2, 4)
        return y.reshape(B, C * b * b, H // b, W // b)
    return _invoke(fn, [_nd(data)], name="space_to_depth")


def unravel_index(data, shape):
    def fn(x):
        jnp = _jnp()
        out = jnp.unravel_index(x.astype(jnp.int64), tuple(shape))
        return jnp.stack(out, 0).astype(x.dtype)
    return _invoke(fn, [_nd(data)], name="unravel_index",
                   differentiable=False)


def ravel_multi_index(data, shape):
    def fn(x):
        jnp = _jnp()
        idx = tuple(x[i].astype(jnp.int64) for i in range(x.shape[0]))
        return jnp.ravel_multi_index(idx, tuple(shape),
                                     mode="clip").astype(x.dtype)
    return _invoke(fn, [_nd(data)], name="ravel_multi_index",
                   differentiable=False)


def logsumexp(data, axis=None, keepdims=False):
    def fn(x):
        import jax
        return jax.scipy.special.logsumexp(x, axis=axis,
                                           keepdims=keepdims)
    return _invoke(fn, [_nd(data)], name="logsumexp")


def cumprod(data, axis=None):
    def fn(x):
        jnp = _jnp()
        return jnp.cumprod(x if axis is not None else x.ravel(),
                           axis=axis if axis is not None else 0)
    return _invoke(fn, [_nd(data)], name="cumprod")


def trace(data, offset=0, axis1=-2, axis2=-1):
    def fn(x):
        return _jnp().trace(x, offset=offset, axis1=axis1, axis2=axis2)
    return _invoke(fn, [_nd(data)], name="trace")


def hard_sigmoid(data, alpha=0.2, beta=0.5):
    def fn(x):
        return _jnp().clip(alpha * x + beta, 0.0, 1.0)
    return _invoke(fn, [_nd(data)], name="hard_sigmoid")


def multi_all_finite(*data, num_arrays=None, init_output=True):
    """1 if every element of every input is finite (reference:
    multi_all_finite.cc, the AMP overflow check)."""
    nds = [_nd(d) for d in data]

    def fn(*xs):
        jnp = _jnp()
        ok = jnp.array(True)
        for x in xs:
            ok = ok & jnp.isfinite(x).all()
        return ok.astype(jnp.float32).reshape(1)
    return _invoke(fn, nds, name="multi_all_finite",
                   differentiable=False)


def im2col(data, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Extract sliding patches: (B,C,H,W) -> (B, C*kh*kw, L) (reference:
    src/operator/nn/im2col.h)."""
    kh, kw = kernel

    def fn(x):
        import jax
        jnp = _jnp()
        B, C, H, W = x.shape
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), tuple(stride),
            padding=((pad[0], pad[0]), (pad[1], pad[1])),
            rhs_dilation=tuple(dilate))
        # patches: (B, C*kh*kw, Ho, Wo)
        return patches.reshape(B, C * kh * kw, -1)
    return _invoke(fn, [_nd(data)], name="im2col")


def col2im(data, output_size, kernel, stride=(1, 1), dilate=(1, 1),
           pad=(0, 0)):
    """Scatter-add patches back to an image — the adjoint of im2col
    (reference: src/operator/nn/im2col.h col2im)."""
    kh, kw = kernel
    H, W = output_size

    def fn(cols):
        import jax
        jnp = _jnp()
        B, CKK, L = cols.shape
        C = CKK // (kh * kw)

        # adjoint of im2col = VJP of im2col at a zero image
        def fwd(img):
            p = jax.lax.conv_general_dilated_patches(
                img, (kh, kw), tuple(stride),
                padding=((pad[0], pad[0]), (pad[1], pad[1])),
                rhs_dilation=tuple(dilate))
            return p.reshape(B, CKK, -1)
        zero = jnp.zeros((B, C, H, W), cols.dtype)
        _, vjp = jax.vjp(fwd, zero)
        return vjp(cols)[0]
    return _invoke(fn, [_nd(data)], name="col2im")


def fft(data, compute_size=128):
    """Real-to-complex FFT over the last axis, packed interleaved
    [re, im] like the reference (contrib fft.cc): (..., d) -> (..., 2d)."""
    def fn(x):
        jnp = _jnp()
        out = jnp.fft.fft(x.astype(jnp.complex64), axis=-1)
        re = jnp.real(out)
        im = jnp.imag(out)
        return jnp.stack([re, im], -1).reshape(*x.shape[:-1],
                                               2 * x.shape[-1])
    return _invoke(fn, [_nd(data)], name="fft")


def ifft(data, compute_size=128):
    """Inverse of ``fft``'s packed layout: (..., 2d) -> (..., d)."""
    def fn(x):
        jnp = _jnp()
        d = x.shape[-1] // 2
        z = x.reshape(*x.shape[:-1], d, 2)
        comp = z[..., 0] + 1j * z[..., 1]
        return jnp.real(jnp.fft.ifft(comp, axis=-1)) * d
    return _invoke(fn, [_nd(data)], name="ifft")


# ---------------------------------------------------------------------------
# remaining flat-name parity ops
# ---------------------------------------------------------------------------
def cast_storage(data, stype):
    """Convert storage type (reference: cast_storage op).  Always
    produces a fresh output (the reference op never aliases its
    input)."""
    arr = _nd(data)
    if arr.stype == stype:
        if stype == "default":
            return _invoke(lambda x: x + 0, [arr], name="cast_storage")
        return arr.tostype("default").tostype(stype)
    return arr.tostype(stype)


def crop(data, begin, end, step=None, **kw):
    """Legacy alias of slice (reference: crop/slice)."""
    if kw:
        raise MXNetError(f"crop: unsupported arguments {sorted(kw)}")
    from .ops import slice as _slice
    if step is not None:
        return _slice(_nd(data), begin=begin, end=end, step=step)
    return _slice(_nd(data), begin=begin, end=end)


def moments(data, axes=None, keepdims=False):
    """Mean and variance over ``axes`` (reference: moments op)."""
    def fn(x):
        jnp = _jnp()
        ax = tuple(axes) if isinstance(axes, (tuple, list)) \
            else (axes,) if axes is not None else None
        mean = jnp.mean(x, axis=ax, keepdims=keepdims)
        var = jnp.var(x, axis=ax, keepdims=keepdims)
        return mean, var
    return _invoke(fn, [_nd(data)], name="moments")


def softmin(data, axis=-1):
    """softmax of -x (reference: softmin op)."""
    def fn(x):
        import jax
        return jax.nn.softmax(-x, axis=axis)
    return _invoke(fn, [_nd(data)], name="softmin")


def argwhere(data):
    """Indices of non-zero elements, (N, ndim), int32 (reference-era
    contrib.boolean ops; note: data-dependent output shape, so this op
    is eager-only — inside jit use topk/where patterns instead)."""
    from .ndarray import array as _array
    return _array(_np.argwhere(_nd(data).asnumpy()), dtype=_np.int32)


def normal(loc=0.0, scale=1.0, shape=None, **kw):
    """Flat alias of mx.nd.random.normal (reference: nd.normal)."""
    from . import random as _rnd
    return _rnd.normal(loc=loc, scale=scale, shape=shape, **kw)


__all__ = [n for n in dir() if not n.startswith("_") and n not in
           ("NDArray", "MXNetError", "annotations")]
