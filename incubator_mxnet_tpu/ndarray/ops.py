"""The operator corpus, eager namespace (``mx.nd.*``).

TPU-native re-design of the reference operator layer (reference:
src/operator/ — tensor/elemwise_*, broadcast, reductions, matrix ops,
indexing, nn activation/softmax, sequence ops; registered via
NNVM_REGISTER_OP and dispatched through Imperative::Invoke).  Here every op
is a thin pure function over jax arrays funneled through
``ndarray._invoke`` which handles async dispatch + autograd recording.
Gradients come from jax's VJP of the same pure function — the analog of the
reference's per-op FGradient registrations, but derived automatically.

Naming/behavior follows python/mxnet/ndarray (e.g. comparison ops return
float arrays; ``dot`` contracts last axis of lhs with first of rhs;
reductions accept axis/keepdims; ``topk`` mirrors the ret_typ variants).
"""
from __future__ import annotations

import builtins
from typing import Optional

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _invoke, array as _array

__all__: list = []  # populated at bottom


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lax():
    from jax import lax
    return lax


def _nd(x, ref: Optional[NDArray] = None) -> NDArray:
    if isinstance(x, NDArray):
        return x
    ctx = ref.ctx if ref is not None else None
    return _array(_np.asarray(x), ctx=ctx)


# ---------------------------------------------------------------------------
# elementwise binary (+ broadcast_* aliases, reference:
# src/operator/tensor/elemwise_binary_broadcast_op_basic.cc)
# ---------------------------------------------------------------------------
def _binary(name, fn, differentiable=True):
    def op(lhs, rhs):
        if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
            return _invoke(fn, [lhs, rhs], name=name,
                           differentiable=differentiable)
        if isinstance(lhs, NDArray):
            return _invoke(lambda x: fn(x, rhs), [lhs], name=name,
                           differentiable=differentiable)
        if isinstance(rhs, NDArray):
            return _invoke(lambda y: fn(lhs, y), [rhs], name=name,
                           differentiable=differentiable)
        raise TypeError(f"{name}: at least one NDArray operand required")
    op.__name__ = name
    return op


def _cmp_fn(jfn):
    # reference comparison ops return float arrays, not bool
    def fn(a, b):
        jnp = _jnp()
        out_dt = a.dtype if jnp.issubdtype(a.dtype, jnp.inexact) else jnp.float32
        return jfn(a, b).astype(out_dt)
    return fn


add = _binary("add", lambda a, b: a + b)
subtract = _binary("subtract", lambda a, b: a - b)
multiply = _binary("multiply", lambda a, b: a * b)
divide = _binary("divide", lambda a, b: a / b)
floor_divide = _binary("floor_divide", lambda a, b: a // b, differentiable=False)
mod = _binary("mod", lambda a, b: a % b)
power = _binary("power", lambda a, b: a ** b)
maximum = _binary("maximum", lambda a, b: _jnp().maximum(a, b))
minimum = _binary("minimum", lambda a, b: _jnp().minimum(a, b))
hypot = _binary("hypot", lambda a, b: _jnp().hypot(a, b))
arctan2 = _binary("arctan2", lambda a, b: _jnp().arctan2(a, b))
equal = _binary("equal", _cmp_fn(lambda a, b: a == b), differentiable=False)
not_equal = _binary("not_equal", _cmp_fn(lambda a, b: a != b), differentiable=False)
greater = _binary("greater", _cmp_fn(lambda a, b: a > b), differentiable=False)
greater_equal = _binary("greater_equal", _cmp_fn(lambda a, b: a >= b), differentiable=False)
lesser = _binary("lesser", _cmp_fn(lambda a, b: a < b), differentiable=False)
lesser_equal = _binary("lesser_equal", _cmp_fn(lambda a, b: a <= b), differentiable=False)
logical_and = _binary("logical_and", _cmp_fn(lambda a, b: (a != 0) & (b != 0)), differentiable=False)
logical_or = _binary("logical_or", _cmp_fn(lambda a, b: (a != 0) | (b != 0)), differentiable=False)
logical_xor = _binary("logical_xor", _cmp_fn(lambda a, b: (a != 0) ^ (b != 0)), differentiable=False)

# broadcast_* spellings are first-class names in the reference
broadcast_add = broadcast_plus = add
broadcast_sub = broadcast_minus = subtract
broadcast_mul = multiply
broadcast_div = divide
broadcast_mod = mod
broadcast_power = power
broadcast_maximum = maximum
broadcast_minimum = minimum
broadcast_hypot = hypot
broadcast_equal = equal
broadcast_not_equal = not_equal
broadcast_greater = greater
broadcast_greater_equal = greater_equal
broadcast_lesser = lesser
broadcast_lesser_equal = lesser_equal
broadcast_logical_and = logical_and
broadcast_logical_or = logical_or
broadcast_logical_xor = logical_xor
elemwise_add = add
elemwise_sub = subtract
elemwise_mul = multiply
elemwise_div = divide


# ---------------------------------------------------------------------------
# elementwise unary (reference: src/operator/tensor/elemwise_unary_op_basic.cc)
# ---------------------------------------------------------------------------
def _unary(name, fn, differentiable=True):
    def op(data, **kw):
        return _invoke(lambda x: fn(x, **kw), [_nd(data)], name=name,
                       differentiable=differentiable)
    op.__name__ = name
    return op


abs = _unary("abs", lambda x: _jnp().abs(x))
sign = _unary("sign", lambda x: _jnp().sign(x))
negative = _unary("negative", lambda x: -x)
reciprocal = _unary("reciprocal", lambda x: 1.0 / x)
square = _unary("square", lambda x: x * x)
sqrt = _unary("sqrt", lambda x: _jnp().sqrt(x))
rsqrt = _unary("rsqrt", lambda x: 1.0 / _jnp().sqrt(x))
cbrt = _unary("cbrt", lambda x: _jnp().cbrt(x))
rcbrt = _unary("rcbrt", lambda x: 1.0 / _jnp().cbrt(x))
exp = _unary("exp", lambda x: _jnp().exp(x))
expm1 = _unary("expm1", lambda x: _jnp().expm1(x))
log = _unary("log", lambda x: _jnp().log(x))
log10 = _unary("log10", lambda x: _jnp().log10(x))
log2 = _unary("log2", lambda x: _jnp().log2(x))
log1p = _unary("log1p", lambda x: _jnp().log1p(x))
sin = _unary("sin", lambda x: _jnp().sin(x))
cos = _unary("cos", lambda x: _jnp().cos(x))
tan = _unary("tan", lambda x: _jnp().tan(x))
arcsin = _unary("arcsin", lambda x: _jnp().arcsin(x))
arccos = _unary("arccos", lambda x: _jnp().arccos(x))
arctan = _unary("arctan", lambda x: _jnp().arctan(x))
sinh = _unary("sinh", lambda x: _jnp().sinh(x))
cosh = _unary("cosh", lambda x: _jnp().cosh(x))
tanh = _unary("tanh", lambda x: _jnp().tanh(x))
arcsinh = _unary("arcsinh", lambda x: _jnp().arcsinh(x))
arccosh = _unary("arccosh", lambda x: _jnp().arccosh(x))
arctanh = _unary("arctanh", lambda x: _jnp().arctanh(x))
degrees = _unary("degrees", lambda x: _jnp().degrees(x))
radians = _unary("radians", lambda x: _jnp().radians(x))
floor = _unary("floor", lambda x: _jnp().floor(x))
ceil = _unary("ceil", lambda x: _jnp().ceil(x))
trunc = _unary("trunc", lambda x: _jnp().trunc(x))
round = _unary("round", lambda x: _jnp().round(x))
rint = _unary("rint", lambda x: _jnp().rint(x))
fix = _unary("fix", lambda x: _jnp().trunc(x))
logical_not = _unary("logical_not", lambda x: (x == 0).astype(_jnp().float32),
                     differentiable=False)
isnan = _unary("isnan", lambda x: _jnp().isnan(x), differentiable=False)
isinf = _unary("isinf", lambda x: _jnp().isinf(x), differentiable=False)
isfinite = _unary("isfinite", lambda x: _jnp().isfinite(x), differentiable=False)


def _special(name):
    def fn(x):
        import jax.scipy.special as sp
        return getattr(sp, name)(x)
    return fn


gamma = _unary("gamma", lambda x: _jnp().exp(_special("gammaln")(x)))
gammaln = _unary("gammaln", _special("gammaln"))
digamma = _unary("digamma", _special("digamma"))
erf = _unary("erf", _special("erf"))
erfinv = _unary("erfinv", _special("erfinv"))


def identity(data):
    return _invoke(lambda x: x, [_nd(data)], name="identity")


copy = identity


def stop_gradient(data):
    """reference: BlockGrad (src/operator/tensor/elemwise_unary_op_basic.cc)."""
    d = _nd(data)
    return _invoke(lambda x: x, [d], name="stop_gradient", differentiable=False)


BlockGrad = stop_gradient


def cast(data, dtype):
    d = _nd(data)
    return _invoke(lambda x: x.astype(dtype), [d], name="cast")


Cast = cast


def zeros_like(data):
    return _invoke(lambda x: _jnp().zeros_like(x), [_nd(data)],
                   name="zeros_like", differentiable=False)


def ones_like(data):
    return _invoke(lambda x: _jnp().ones_like(x), [_nd(data)],
                   name="ones_like", differentiable=False)


def full_like(data, fill_value):
    return _invoke(lambda x: _jnp().full_like(x, fill_value), [_nd(data)],
                   name="full_like", differentiable=False)


def shape_array(data):
    return _array(_np.asarray(_nd(data).shape, dtype=_np.int64))


def size_array(data):
    return _array(_np.asarray([_nd(data).size], dtype=_np.int64))


# ---------------------------------------------------------------------------
# activations (reference: src/operator/nn/activation.cc, leaky_relu.cc,
# softmax.cc)
# ---------------------------------------------------------------------------
relu = _unary("relu", lambda x: _jnp().maximum(x, 0))
sigmoid = _unary("sigmoid", lambda x: _jax_nn("sigmoid")(x))
softsign = _unary("softsign", lambda x: x / (1 + _jnp().abs(x)))
softrelu = _unary("softrelu", lambda x: _jax_nn("softplus")(x))
softplus = softrelu
erf_gelu = _unary("erf_gelu", lambda x: _jax_nn("gelu")(x, approximate=False))


def _jax_nn(name):
    import jax.nn
    return getattr(jax.nn, name)


def gelu(data, approximate=False):
    return _invoke(lambda x: _jax_nn("gelu")(x, approximate=approximate),
                   [_nd(data)], name="gelu")


def leaky_relu(data, act_type="leaky", slope=0.25, gamma=None, **kw):
    """reference: LeakyReLU op (src/operator/leaky_relu.cc): leaky/elu/selu/
    gelu variants."""
    jnp = _jnp()
    d = _nd(data)
    if act_type == "leaky":
        return _invoke(lambda x: jnp.where(x > 0, x, slope * x), [d],
                       name="leaky_relu")
    if act_type == "elu":
        return _invoke(lambda x: jnp.where(x > 0, x, slope * jnp.expm1(x)),
                       [d], name="elu")
    if act_type == "selu":
        return _invoke(lambda x: _jax_nn("selu")(x), [d], name="selu")
    if act_type == "gelu":
        return _invoke(lambda x: _jax_nn("gelu")(x, approximate=False), [d],
                       name="gelu")
    if act_type == "prelu":
        g = _nd(gamma, d)
        return _invoke(lambda x, gm: jnp.where(x > 0, x, gm * x), [d, g],
                       name="prelu")
    raise MXNetError(f"LeakyReLU: unknown act_type {act_type}")


LeakyReLU = leaky_relu


def Activation(data, act_type="relu"):
    table = {"relu": relu, "sigmoid": sigmoid, "tanh": tanh,
             "softrelu": softrelu, "softsign": softsign,
             "log_sigmoid": lambda d: _invoke(
                 lambda x: _jax_nn("log_sigmoid")(x), [_nd(d)],
                 name="log_sigmoid"),
             "mish": lambda d: _invoke(
                 lambda x: x * _jnp().tanh(_jax_nn("softplus")(x)), [_nd(d)],
                 name="mish")}
    if act_type not in table:
        raise MXNetError(f"Activation: unknown act_type {act_type}")
    return table[act_type](data)


def softmax(data, axis=-1, temperature=None, length=None):
    """reference: src/operator/nn/softmax.cc (with optional masking by valid
    ``length`` along ``axis``)."""
    jnp = _jnp()
    d = _nd(data)
    if length is not None:
        ln = _nd(length, d)

        def fn(x, lv):
            t = x / temperature if temperature else x
            idx = jnp.arange(x.shape[axis])
            shp = [1] * x.ndim
            shp[axis] = x.shape[axis]
            mask = idx.reshape(shp) < jnp.expand_dims(lv, axis=axis)
            t = jnp.where(mask, t, -jnp.inf)
            out = _jax_nn("softmax")(t, axis=axis)
            return jnp.where(mask, out, 0.0)
        return _invoke(fn, [d, ln], name="softmax")

    def fn(x):
        t = x / temperature if temperature else x
        return _jax_nn("softmax")(t, axis=axis)
    return _invoke(fn, [d], name="softmax")


def log_softmax(data, axis=-1, temperature=None):
    def fn(x):
        t = x / temperature if temperature else x
        return _jax_nn("log_softmax")(t, axis=axis)
    return _invoke(fn, [_nd(data)], name="log_softmax")


def softmax_cross_entropy(data, label):
    """reference: src/operator/loss_binary_op.cc softmax_cross_entropy:
    summed CE over the batch, integer labels."""
    d, l = _nd(data), _nd(label)

    def fn(x, y):
        jnp = _jnp()
        logp = _jax_nn("log_softmax")(x, axis=-1)
        picked = jnp.take_along_axis(
            logp, y.astype(jnp.int32)[..., None], axis=-1)
        return -picked.sum()
    return _invoke(fn, [d, l], name="softmax_cross_entropy")


def smooth_l1(data, scalar=1.0):
    def fn(x):
        jnp = _jnp()
        s2 = scalar * scalar
        return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * x * x,
                         jnp.abs(x) - 0.5 / s2)
    return _invoke(fn, [_nd(data)], name="smooth_l1")


def l2_normalization(data, eps=1e-10, mode="instance"):
    jnp = _jnp()

    def fn(x):
        if mode == "instance":
            ax = tuple(range(1, x.ndim))
        elif mode == "channel":
            ax = (1,)
        else:
            ax = tuple(range(x.ndim))
        n = jnp.sqrt((x * x).sum(axis=ax, keepdims=True) + eps)
        return x / n
    return _invoke(fn, [_nd(data)], name="l2_normalization")


L2Normalization = l2_normalization


# ---------------------------------------------------------------------------
# reductions (reference: src/operator/tensor/broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------
def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return int(axis)


def _reduce(name, jname, differentiable=True):
    def op(data, axis=None, keepdims=False, **kw):
        ax = _norm_axis(axis)
        return _invoke(
            lambda x: getattr(_jnp(), jname)(x, axis=ax, keepdims=keepdims),
            [_nd(data)], name=name, differentiable=differentiable)
    op.__name__ = name
    return op


sum = _reduce("sum", "sum")
nansum = _reduce("nansum", "nansum")
mean = _reduce("mean", "mean")
prod = _reduce("prod", "prod")
nanprod = _reduce("nanprod", "nanprod")
max = _reduce("max", "max")
min = _reduce("min", "min")
sum_axis = sum
max_axis = max
min_axis = min


def argmax(data, axis=None, keepdims=False):
    """Returns float indices, matching the reference."""
    def fn(x):
        jnp = _jnp()
        r = jnp.argmax(x, axis=axis)
        if keepdims and axis is not None:
            r = jnp.expand_dims(r, axis)
        return r.astype(jnp.float32)
    return _invoke(fn, [_nd(data)], name="argmax", differentiable=False)


def argmin(data, axis=None, keepdims=False):
    def fn(x):
        jnp = _jnp()
        r = jnp.argmin(x, axis=axis)
        if keepdims and axis is not None:
            r = jnp.expand_dims(r, axis)
        return r.astype(jnp.float32)
    return _invoke(fn, [_nd(data)], name="argmin", differentiable=False)


def argmax_channel(data):
    return argmax(data, axis=1)


def norm(data, ord=2, axis=None, keepdims=False):
    def fn(x):
        jnp = _jnp()
        ax = _norm_axis(axis)
        if ord == 1:
            return jnp.abs(x).sum(axis=ax, keepdims=keepdims)
        return jnp.sqrt((x * x).sum(axis=ax, keepdims=keepdims))
    return _invoke(fn, [_nd(data)], name="norm")


def cumsum(data, axis=None, dtype=None):
    return _invoke(lambda x: _jnp().cumsum(x, axis=axis, dtype=dtype),
                   [_nd(data)], name="cumsum")


# ---------------------------------------------------------------------------
# linear algebra (reference: src/operator/tensor/dot.cc, la ops)
# ---------------------------------------------------------------------------
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """reference ``dot``: contract lhs's last axis with rhs's first axis
    (after optional transposes)."""
    l, r = _nd(lhs), _nd(rhs)

    def fn(a, b):
        jnp = _jnp()
        if transpose_a:
            a = jnp.moveaxis(a, 0, -1) if a.ndim > 1 else a
        if transpose_b:
            b = jnp.moveaxis(b, -1, 0) if b.ndim > 1 else b
        return jnp.tensordot(a, b, axes=1)
    return _invoke(fn, [l, r], name="dot")


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """reference: batch_dot (src/operator/tensor/dot.cc) — batched matmul
    over leading dims; the attention workhorse.  Maps directly onto the MXU."""
    l, r = _nd(lhs), _nd(rhs)

    def fn(a, b):
        jnp = _jnp()
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return _invoke(fn, [l, r], name="batch_dot")


def matmul(lhs, rhs):
    return _invoke(lambda a, b: _jnp().matmul(a, b), [_nd(lhs), _nd(rhs)],
                   name="matmul")


def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    def fn(a, b):
        jnp = _jnp()
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return alpha * jnp.matmul(a, b)
    return _invoke(fn, [_nd(A), _nd(B)], name="linalg_gemm2")


def khatri_rao(*args):
    mats = [_nd(a) for a in args]

    def fn(*ms):
        jnp = _jnp()
        out = ms[0]
        for m in ms[1:]:
            out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
        return out
    return _invoke(fn, mats, name="khatri_rao")


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------
def reshape(data, shape=None, reverse=False, **kw):
    """reference: reshape op with special codes 0/-1/-2/-3/-4; ``reverse=True``
    applies the codes right-to-left (matching the reference's semantics for
    trailing-dim-anchored reshapes)."""
    d = _nd(data)
    if not reverse:
        return d.reshape(shape)
    spec = list(shape)
    if -4 in spec:
        raise MXNetError("reshape: reverse=True with -4 is not supported")
    from .ndarray import _expand_reshape
    new_shape = _expand_reshape(d.shape[::-1], spec[::-1])[::-1]
    return d.reshape(new_shape)


def reshape_like(data, other):
    return _nd(data).reshape(_nd(other).shape)


def flatten(data):
    return _nd(data).flatten()


Flatten = flatten


def transpose(data, axes=None):
    ax = tuple(axes) if axes else None
    return _invoke(lambda x: _jnp().transpose(x, ax), [_nd(data)],
                   name="transpose")


def swapaxes(data, dim1=0, dim2=0):
    return _invoke(lambda x: _jnp().swapaxes(x, dim1, dim2), [_nd(data)],
                   name="swapaxes")


SwapAxis = swapaxes


def expand_dims(data, axis):
    return _invoke(lambda x: _jnp().expand_dims(x, axis), [_nd(data)],
                   name="expand_dims")


def squeeze(data, axis=None):
    ax = _norm_axis(axis)
    return _invoke(lambda x: _jnp().squeeze(x, axis=ax), [_nd(data)],
                   name="squeeze")


def broadcast_to(data, shape):
    shape = tuple(shape)
    d = _nd(data)
    # reference semantics: 0 in target shape means "keep source dim"
    tgt = tuple(s if s != 0 else d.shape[i] for i, s in enumerate(shape))
    return _invoke(lambda x: _jnp().broadcast_to(x, tgt), [d],
                   name="broadcast_to")


def broadcast_axis(data, axis=None, size=None):
    d = _nd(data)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    sizes = size if isinstance(size, (list, tuple)) else [size]
    tgt = list(d.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return broadcast_to(d, tgt)


broadcast_axes = broadcast_axis


def broadcast_like(lhs, rhs):
    return broadcast_to(lhs, _nd(rhs).shape)


def concat(*data, dim=1):
    arrs = [_nd(d) for d in (data[0] if len(data) == 1 and
                             isinstance(data[0], (list, tuple)) else data)]
    return _invoke(lambda *xs: _jnp().concatenate(xs, axis=dim), arrs,
                   name="concat")


Concat = concat


def stack(*data, axis=0):
    arrs = [_nd(d) for d in (data[0] if len(data) == 1 and
                             isinstance(data[0], (list, tuple)) else data)]
    return _invoke(lambda *xs: _jnp().stack(xs, axis=axis), arrs, name="stack")


def split(data, num_outputs, axis=1, squeeze_axis=False):
    d = _nd(data)

    def fn(x):
        jnp = _jnp()
        parts = jnp.split(x, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    out = _invoke(fn, [d], name="split")
    return out if num_outputs > 1 else out[0]


SliceChannel = split


def split_v2(data, indices_or_sections, axis=0, squeeze_axis=False):
    d = _nd(data)
    ios = indices_or_sections

    def fn(x):
        jnp = _jnp()
        parts = jnp.split(x, ios if isinstance(ios, int) else list(ios),
                          axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    return _invoke(fn, [d], name="split_v2")


def slice(data, begin, end, step=None):
    """reference: slice op — begin/end may contain None."""
    d = _nd(data)
    begin = tuple(begin) if isinstance(begin, (list, tuple)) else (begin,)
    end = tuple(end) if isinstance(end, (list, tuple)) else (end,)
    step = tuple(step) if step else (None,) * len(begin)
    key = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    return _invoke(lambda x: x[key], [d], name="slice")


def slice_axis(data, axis, begin, end):
    d = _nd(data)
    if end is None:
        end = d.shape[axis]
    key = [builtins.slice(None)] * d.ndim
    key[axis] = builtins.slice(begin, end)
    key = tuple(key)
    return _invoke(lambda x: x[key], [d], name="slice_axis")


def slice_like(data, shape_like, axes=None):
    d, s = _nd(data), _nd(shape_like)
    axes = axes if axes is not None else range(d.ndim)
    key = [builtins.slice(None)] * d.ndim
    for a in axes:
        key[a] = builtins.slice(0, s.shape[a])
    key = tuple(key)
    return _invoke(lambda x: x[key], [d], name="slice_like")


def Crop(data, crop_like=None, offset=(0, 0), h_w=(0, 0),
         center_crop=False, num_args=1, **_ignored):
    """Legacy 2D crop on NCHW (reference: src/operator/crop.cc).  Target
    (h, w) comes from ``h_w`` or from a second input's spatial dims;
    position from ``offset`` or, with ``center_crop``, the center."""
    d = _nd(data)
    if crop_like is not None:
        th, tw = _nd(crop_like).shape[2], _nd(crop_like).shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
        if th <= 0 or tw <= 0:
            raise MXNetError("Crop needs h_w or a crop_like input")
    H, W = d.shape[2], d.shape[3]
    if th > H or tw > W:
        raise MXNetError(f"Crop target ({th},{tw}) exceeds input "
                         f"({H},{W})")
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    if oy < 0 or ox < 0 or oy + th > H or ox + tw > W:
        raise MXNetError(f"Crop offset {offset} with size ({th},{tw}) "
                         f"leaves the ({H},{W}) input")
    key = (builtins.slice(None), builtins.slice(None),
           builtins.slice(oy, oy + th), builtins.slice(ox, ox + tw))
    return _invoke(lambda x: x[key], [d], name="Crop")


def tile(data, reps):
    return _invoke(lambda x: _jnp().tile(x, tuple(reps)), [_nd(data)],
                   name="tile")


def repeat(data, repeats, axis=None):
    return _invoke(lambda x: _jnp().repeat(x, repeats, axis=axis), [_nd(data)],
                   name="repeat")


def flip(data, axis):
    ax = _norm_axis(axis)
    return _invoke(lambda x: _jnp().flip(x, axis=ax), [_nd(data)], name="flip")


reverse = flip


def pad(data, mode="constant", pad_width=None, constant_value=0):
    """reference: src/operator/pad.cc — pad_width is the flat
    (before,after)-per-axis tuple."""
    d = _nd(data)
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(d.ndim)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]

    def fn(x):
        jnp = _jnp()
        if jmode == "constant":
            return jnp.pad(x, pw, mode="constant",
                           constant_values=constant_value)
        return jnp.pad(x, pw, mode=jmode)
    return _invoke(fn, [d], name="pad")


Pad = pad


def diag(data, k=0):
    d = _nd(data)

    def fn(x):
        jnp = _jnp()
        if x.ndim == 1:
            return jnp.diag(x, k)
        return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)
    return _invoke(fn, [d], name="diag")


# ---------------------------------------------------------------------------
# indexing (reference: src/operator/tensor/indexing_op.cc)
# ---------------------------------------------------------------------------
def take(a, indices, axis=0, mode="clip"):
    d, idx = _nd(a), _nd(indices, _nd(a))

    def fn(x, i):
        jnp = _jnp()
        i = i.astype(jnp.int32)
        if mode == "clip":
            i = jnp.clip(i, 0, x.shape[axis] - 1)
        elif mode == "wrap":
            i = i % x.shape[axis]
        return jnp.take(x, i, axis=axis)
    return _invoke(fn, [d, idx], name="take")


def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    d, idx = _nd(data), _nd(index, _nd(data))

    def fn(x, i):
        jnp = _jnp()
        i = jnp.clip(i.astype(jnp.int32), 0, x.shape[axis] - 1)
        picked = jnp.take_along_axis(x, jnp.expand_dims(i, axis), axis=axis)
        return picked if keepdims else jnp.squeeze(picked, axis=axis)
    return _invoke(fn, [d, idx], name="pick")


def gather_nd(data, indices):
    d, idx = _nd(data), _nd(indices, _nd(data))

    def fn(x, i):
        jnp = _jnp()
        i = i.astype(jnp.int32)
        # reference layout: indices shape (M, ...), first axis indexes dims
        return x[tuple(i[k] for k in range(i.shape[0]))]
    return _invoke(fn, [d, idx], name="gather_nd")


def scatter_nd(data, indices, shape):
    d, idx = _nd(data), _nd(indices, _nd(data))
    shape = tuple(shape)

    def fn(x, i):
        jnp = _jnp()
        i = i.astype(jnp.int32)
        out = jnp.zeros(shape, x.dtype)
        return out.at[tuple(i[k] for k in range(i.shape[0]))].set(x)
    return _invoke(fn, [d, idx], name="scatter_nd")


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    idx = _nd(indices)

    def fn(i):
        jnp = _jnp()
        oh = _jax_nn("one_hot")(i.astype(jnp.int32), depth)
        return (oh * (on_value - off_value) + off_value).astype(dtype)
    return _invoke(fn, [idx], name="one_hot", differentiable=False)


def where(condition, x, y):
    c, a, b = _nd(condition), _nd(x), _nd(y)
    return _invoke(lambda cc, aa, bb: _jnp().where(cc != 0, aa, bb), [c, a, b],
                   name="where")


def boolean_mask(data, index, axis=0):
    # data-dependent shape: materialize on host (documented XLA limitation)
    d, i = _nd(data), _nd(index)
    mask = i.asnumpy().astype(bool)
    return _array(_np.compress(mask, d.asnumpy(), axis=axis), ctx=d.ctx)


def Embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    """reference: Embedding op (src/operator/tensor/indexing_op.cc).

    ``sparse_grad=True`` records a custom tape node whose backward emits a
    ``RowSparseNDArray`` gradient holding only the touched rows (reference:
    EmbeddingOpBackward row_sparse output) — eager-only, since nnz is
    data-dependent; under a jit trace it falls back to the dense VJP."""
    idx, w = _nd(data), _nd(weight)
    dense = lambda i, ww: _jnp().take(ww, i.astype(_jnp().int32), axis=0)
    if sparse_grad:
        import jax
        if not (isinstance(idx._data, jax.core.Tracer)
                or isinstance(w._data, jax.core.Tracer)):
            return _embedding_sparse_grad(idx, w)
    return _invoke(dense, [idx, w], name="Embedding")


def _embedding_sparse_grad(idx: NDArray, w: NDArray) -> NDArray:
    from .. import autograd as _ag_mod
    jnp = _jnp()
    out = NDArray(jnp.take(w._data, idx._data.astype(jnp.int32), axis=0),
                  ctx=w.ctx)
    if _ag_mod.is_recording() and w._tape_entry_active():
        idx_dev = idx._data  # host sync deferred to backward time
        wshape, wctx = w.shape, w.ctx

        def sparse_vjp(cot):
            from . import sparse as _sp
            return (_sp.embedding_row_sparse_grad(_np.asarray(idx_dev), cot,
                                                  wshape, ctx=wctx),)

        node = _ag_mod._TapeNode(fun=None, inputs=[w], vjp_fn=sparse_vjp,
                                 out_is_tuple=False,
                                 name="Embedding(sparse_grad)", custom=True)
        node.out_avals = [(out.shape, out.dtype)]
        out._ag_node = node
        out._ag_idx = 0
    return out


embedding = Embedding


# ---------------------------------------------------------------------------
# sorting (reference: src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------
def sort(data, axis=-1, is_ascend=True):
    def fn(x):
        jnp = _jnp()
        s = jnp.sort(x, axis=axis)
        return s if is_ascend else jnp.flip(s, axis=axis)
    return _invoke(fn, [_nd(data)], name="sort")


def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    def fn(x):
        jnp = _jnp()
        s = jnp.argsort(x, axis=axis)
        if not is_ascend:
            s = jnp.flip(s, axis=axis)
        return s.astype(dtype)
    return _invoke(fn, [_nd(data)], name="argsort", differentiable=False)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    """reference: topk — ret_typ in {value, indices, mask, both}."""
    d = _nd(data)

    def prep(x):
        jnp = _jnp()
        from jax import lax
        xm = jnp.moveaxis(x, axis, -1)
        vals, idxs = lax.top_k(-xm if is_ascend else xm, k)
        if is_ascend:
            vals = -vals
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idxs, -1, axis)

    if ret_typ == "value":
        return _invoke(lambda x: prep(x)[0], [d], name="topk")
    if ret_typ == "indices":
        return _invoke(lambda x: prep(x)[1].astype(dtype), [d], name="topk",
                       differentiable=False)
    if ret_typ == "both":
        def fn(x):
            v, i = prep(x)
            return v, i.astype(dtype)
        return _invoke(fn, [d], name="topk")
    if ret_typ == "mask":
        def fn(x):
            jnp = _jnp()
            _, i = prep(x)
            im = jnp.moveaxis(i, axis, -1)
            oh = _jax_nn("one_hot")(im, x.shape[axis]).sum(-2)
            return jnp.moveaxis(oh, -1, axis).astype(x.dtype)
        return _invoke(fn, [d], name="topk", differentiable=False)
    raise MXNetError(f"topk: unknown ret_typ {ret_typ}")


def clip(data, a_min=None, a_max=None):
    return _invoke(lambda x: _jnp().clip(x, a_min, a_max), [_nd(data)],
                   name="clip")


# ---------------------------------------------------------------------------
# sequence ops (reference: src/operator/sequence_mask.cc / _last / _reverse —
# the era's long-sequence handling; see SURVEY §5.7)
# ---------------------------------------------------------------------------
def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):
    d = _nd(data)
    if not use_sequence_length or sequence_length is None:
        return identity(d)
    sl = _nd(sequence_length, d)

    def fn(x, l):
        jnp = _jnp()
        T = x.shape[axis]
        idx = jnp.arange(T)
        shp = [1] * x.ndim
        shp[axis] = T
        bshp = [1] * x.ndim
        bshp[1 - axis] = x.shape[1 - axis]
        mask = idx.reshape(shp) < l.reshape(bshp)
        return jnp.where(mask, x, value)
    return _invoke(fn, [d, sl], name="SequenceMask")


def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0):
    d = _nd(data)
    if not use_sequence_length or sequence_length is None:
        return slice_axis(d, axis, d.shape[axis] - 1, d.shape[axis]).squeeze(
            axis=axis)
    sl = _nd(sequence_length, d)

    def fn(x, l):
        jnp = _jnp()
        last = (l.astype(jnp.int32) - 1)
        xm = jnp.moveaxis(x, axis, 0)         # (T, B, ...)
        return jnp.take_along_axis(
            xm, last.reshape((1, -1) + (1,) * (xm.ndim - 2)), axis=0)[0]
    return _invoke(fn, [d, sl], name="SequenceLast")


def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0):
    d = _nd(data)
    if not use_sequence_length or sequence_length is None:
        return flip(d, axis)
    sl = _nd(sequence_length, d)

    def fn(x, l):
        jnp = _jnp()
        T = x.shape[axis]
        xm = jnp.moveaxis(x, axis, 0)
        idx = jnp.arange(T)[:, None]
        li = l.astype(jnp.int32)[None, :]
        rev = jnp.where(idx < li, li - 1 - idx, idx)
        out = jnp.take_along_axis(
            xm, rev.reshape(rev.shape + (1,) * (xm.ndim - 2)), axis=0)
        return jnp.moveaxis(out, 0, axis)
    return _invoke(fn, [d, sl], name="SequenceReverse")


sequence_mask = SequenceMask
sequence_last = SequenceLast
sequence_reverse = SequenceReverse


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------
def add_n(*args):
    """reference: ElementWiseSum/add_n."""
    arrs = [_nd(a) for a in (args[0] if len(args) == 1 and
                             isinstance(args[0], (list, tuple)) else args)]
    def fn(*xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out
    return _invoke(fn, arrs, name="add_n")


ElementWiseSum = add_n


def dropout(data, p=0.5, mode="training", axes=None):
    """Eager dropout; gluon.nn.Dropout handles train/test mode."""
    from .. import random as _random
    d = _nd(data)
    if p <= 0 or mode != "training":
        return identity(d)
    key = _random.new_key(d.ctx)

    def fn(x):
        import jax
        jnp = _jnp()
        shape = x.shape if axes is None else tuple(
            x.shape[i] if i in axes else 1 for i in range(x.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return _invoke(fn, [d], name="dropout")


Dropout = dropout


def linalg_norm(data, **kw):
    return norm(data, **kw)


def make_loss(data):
    return identity(data)


def batch_take(a, indices):
    d, i = _nd(a), _nd(indices)

    def fn(x, idx):
        jnp = _jnp()
        return jnp.take_along_axis(
            x, idx.astype(jnp.int32)[:, None], axis=1)[:, 0]
    return _invoke(fn, [d, i], name="batch_take")


__all__ = [n for n in dir() if not n.startswith("_") and n not in
           ("annotations", "builtins", "Optional", "NDArray", "MXNetError")]
