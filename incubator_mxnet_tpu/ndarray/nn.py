"""Neural-network operator family (``mx.nd`` NN ops), TPU-native.

Re-design of the reference NN operators (reference: src/operator/nn/ —
fully_connected.cc, convolution.cc, deconvolution.cc, pooling.cc,
batch_norm.cc, layer_norm.cc, group_norm.cc, instance_norm.cc, rnn.cc).
The reference dispatches to mshadow/cuDNN/oneDNN kernels; here each op is a
pure jax function lowered by XLA onto the MXU (conv_general_dilated,
dot_general) with autograd via the ``_invoke`` VJP funnel.

Design notes (TPU-first):
  * Convs/matmuls stay in the input dtype (bf16-friendly) and map onto the
    MXU; layouts are the reference's NCHW/NCW/NCDHW, handled by XLA's
    layout assignment rather than manual transposes.
  * Pooling is ``lax.reduce_window`` — fused by XLA, no im2col.
  * The fused RNN op is a ``lax.scan`` over time — compiler-friendly
    (single compiled loop, no per-step dispatch), replacing the reference's
    cuDNN RNN descriptor machinery while keeping MXNet's flat parameter
    vector layout for checkpoint parity.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _invoke

__all__ = ["FullyConnected", "fully_connected", "Convolution", "convolution",
           "Deconvolution", "deconvolution", "Pooling", "pooling",
           "BatchNorm", "batch_norm", "LayerNorm", "layer_norm",
           "InstanceNorm", "instance_norm", "GroupNorm", "group_norm",
           "RNN", "rnn", "rnn_param_size", "SoftmaxOutput", "softmax_output",
           "LinearRegressionOutput", "MAERegressionOutput",
           "LogisticRegressionOutput", "UpSampling", "SVMOutput",
           "svm_output", "Convolution_v1"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _lax():
    from jax import lax
    return lax


def _tup(x, n, default=1) -> Tuple[int, ...]:
    """Normalize a kernel/stride/pad spec to an n-tuple."""
    if x is None:
        return (default,) * n if n else ()
    if isinstance(x, int):
        return (x,) * n
    t = tuple(int(v) for v in x)
    if len(t) == 1:
        return t * n
    if len(t) != n:
        raise MXNetError(f"expected spec of length {n}, got {t}")
    return t


# ---------------------------------------------------------------------------
# FullyConnected (reference: src/operator/nn/fully_connected.cc)
# ---------------------------------------------------------------------------
def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):
    """out = data @ weight.T + bias.  weight: (num_hidden, in_units).

    flatten=True collapses data to (batch, -1) first (reference semantics);
    flatten=False applies to the last axis only.
    """
    jnp = _jnp()

    if no_bias or bias is None:
        def fn(x, w):
            xx = x.reshape(x.shape[0], -1) if flatten else x
            return jnp.matmul(xx, w.T)
        return _invoke(fn, [data, weight], name="FullyConnected")

    def fnb(x, w, b):
        xx = x.reshape(x.shape[0], -1) if flatten else x
        return jnp.matmul(xx, w.T) + b
    return _invoke(fnb, [data, weight, bias], name="FullyConnected")


# ---------------------------------------------------------------------------
# Convolution (reference: src/operator/nn/convolution.cc; layouts NCW/NCHW/
# NCDHW, weight OIHW-style (num_filter, C/group, *kernel))
# ---------------------------------------------------------------------------
_CONV_DN = {1: ("NCW", "OIW", "NCW"),
            2: ("NCHW", "OIHW", "NCHW"),
            3: ("NCDHW", "OIDHW", "NCDHW")}


def Convolution(data, weight, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=None, num_group=1,
                no_bias=False, layout=None, **_ignored):
    lax = _lax()
    nd = len(kernel) if kernel is not None else data.ndim - 2
    stride_, dilate_, pad_ = _tup(stride, nd), _tup(dilate, nd), _tup(pad, nd, 0)
    dn = _CONV_DN[nd]
    padding = [(p, p) for p in pad_]

    def conv(x, w):
        # lax.conv requires matching dtypes; after net.cast('bfloat16') the
        # activations may still arrive fp32 — follow the weight dtype (the
        # reference's cudnn path casts the same way under AMP)
        if x.dtype != w.dtype:
            x = x.astype(w.dtype)
        return lax.conv_general_dilated(
            x, w, window_strides=stride_, padding=padding,
            lhs_dilation=None, rhs_dilation=dilate_,
            dimension_numbers=dn, feature_group_count=num_group,
            preferred_element_type=None)

    if no_bias or bias is None:
        return _invoke(conv, [data, weight], name="Convolution")

    def convb(x, w, b):
        out = conv(x, w)
        return out + b.reshape((1, -1) + (1,) * nd)
    return _invoke(convb, [data, weight, bias], name="Convolution")


# ---------------------------------------------------------------------------
# Deconvolution / transposed conv (reference: src/operator/nn/deconvolution.cc
# — weight layout (C_in, num_filter/group, *kernel); out = (in-1)*s - 2p + k + adj)
# ---------------------------------------------------------------------------
def Deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, num_filter=None,
                  num_group=1, no_bias=False, target_shape=None,
                  layout=None, **_ignored):
    lax = _lax()
    jnp = _jnp()
    nd = len(kernel) if kernel is not None else data.ndim - 2
    k_, s_, d_, p_ = (_tup(kernel, nd), _tup(stride, nd), _tup(dilate, nd),
                      _tup(pad, nd, 0))
    adj_ = _tup(adj, nd) if adj is not None else (0,) * nd
    if target_shape is not None:
        # solve adj from the requested spatial output shape
        tgt = _tup(target_shape, nd)
        adj_ = tuple(
            t - ((i - 1) * s - 2 * p + (d * (k - 1) + 1))
            for t, i, s, p, d, k in zip(tgt, data.shape[2:], s_, p_, d_, k_))
    dn = _CONV_DN[nd]
    # transposed conv == conv with lhs_dilation=stride over a flipped,
    # IO-swapped kernel, padded with (dilated_k - 1 - pad) per side
    padding = [(d * (k - 1) - p, d * (k - 1) - p + a)
               for k, p, d, a in zip(k_, p_, d_, adj_)]

    def deconv(x, w):
        w_flip = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if num_group == 1:
            w_t = jnp.swapaxes(w_flip, 0, 1)   # (in, out, *k) -> (out, in, *k)
        else:
            cin, cog = w_flip.shape[0], w_flip.shape[1]
            wg = w_flip.reshape((num_group, cin // num_group, cog)
                                + w_flip.shape[2:])
            wg = jnp.swapaxes(wg, 1, 2)        # (g, out/g, in/g, *k)
            w_t = wg.reshape((cog * num_group, cin // num_group)
                             + w_flip.shape[2:])
        return lax.conv_general_dilated(
            x, w_t, window_strides=(1,) * nd, padding=padding,
            lhs_dilation=s_, rhs_dilation=d_, dimension_numbers=dn,
            feature_group_count=num_group)

    if no_bias or bias is None:
        return _invoke(deconv, [data, weight], name="Deconvolution")

    def deconvb(x, w, b):
        return deconv(x, w) + b.reshape((1, -1) + (1,) * nd)
    return _invoke(deconvb, [data, weight, bias], name="Deconvolution")


# ---------------------------------------------------------------------------
# Pooling (reference: src/operator/nn/pooling.cc — max/avg/sum/lp,
# pooling_convention valid|full|same, global_pool, count_include_pad)
# ---------------------------------------------------------------------------
def _pool_out_dim(i, k, s, p, convention):
    if convention == "full":
        return int(math.ceil((i + 2 * p - k) / s)) + 1
    return (i + 2 * p - k) // s + 1


def Pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, p_value=2, layout=None, **_ignored):
    lax = _lax()
    jnp = _jnp()
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, 2 + nd))
        if pool_type == "max":
            return _invoke(lambda x: jnp.max(x, axis=axes, keepdims=True),
                           [data], name="Pooling")
        if pool_type in ("avg", "sum"):
            red = jnp.mean if pool_type == "avg" else jnp.sum
            return _invoke(lambda x: red(x, axis=axes, keepdims=True),
                           [data], name="Pooling")
        raise MXNetError(f"global pool_type {pool_type} unsupported")

    k_ = _tup(kernel, nd)
    s_ = _tup(stride, nd)
    p_ = _tup(pad, nd, 0)
    # extra high-side padding for 'full' (ceil) convention
    extra = []
    for i, k, s, p in zip(data.shape[2:], k_, s_, p_):
        o = _pool_out_dim(i, k, s, p, pooling_convention)
        need = (o - 1) * s + k - (i + 2 * p)
        extra.append(max(0, need))
    window = (1, 1) + k_
    strides = (1, 1) + s_
    padding = ((0, 0), (0, 0)) + tuple(
        (p, p + e) for p, e in zip(p_, extra))

    if pool_type == "max":
        def fn(x):
            return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                     padding)
        return _invoke(fn, [data], name="Pooling")

    if pool_type in ("avg", "sum"):
        def fn(x):
            ssum = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            if pool_type == "sum":
                return ssum
            if count_include_pad:
                denom = float(_np.prod(k_))
                return ssum / denom
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                    padding)
            return ssum / cnt
        return _invoke(fn, [data], name="Pooling")

    if pool_type == "lp":
        def fn(x):
            xp = jnp.abs(x) ** p_value
            ssum = lax.reduce_window(xp, 0.0, lax.add, window, strides,
                                     padding)
            return ssum ** (1.0 / p_value)
        return _invoke(fn, [data], name="Pooling")

    raise MXNetError(f"unknown pool_type {pool_type!r}")


# ---------------------------------------------------------------------------
# BatchNorm (reference: src/operator/nn/batch_norm.cc).  Pure-functional:
# returns (out, batch_mean, batch_var); the gluon layer owns the moving-stat
# update (the reference mutates aux states inside the op — anti-functional,
# re-designed here).
# ---------------------------------------------------------------------------
def BatchNorm(data, gamma, beta, moving_mean=None, moving_var=None,
              eps=1e-5, momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, **_ignored):
    jnp = _jnp()
    ax = axis if axis >= 0 else data.ndim + axis
    red_axes = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1
                   for i in range(data.ndim))

    if use_global_stats:
        def fn(x, g, b, mm, mv):
            gg = jnp.ones_like(g) if fix_gamma else g
            inv = 1.0 / jnp.sqrt(mv + eps)
            out = (x - mm.reshape(bshape)) * (gg * inv).reshape(bshape) \
                + b.reshape(bshape)
            return out, mm, mv
        res = _invoke(fn, [data, gamma, beta, moving_mean, moving_var],
                      name="BatchNorm")
    else:
        def fn(x, g, b):
            gg = jnp.ones_like(g) if fix_gamma else g
            mean = jnp.mean(x, axis=red_axes)
            var = jnp.mean(
                (x - mean.reshape(bshape)) ** 2, axis=red_axes)
            inv = 1.0 / jnp.sqrt(var + eps)
            out = (x - mean.reshape(bshape)) * (gg * inv).reshape(bshape) \
                + b.reshape(bshape)
            return out, mean, var
        res = _invoke(fn, [data, gamma, beta], name="BatchNorm")
    if output_mean_var:
        return res
    return res[0]


# ---------------------------------------------------------------------------
# LayerNorm (reference: src/operator/nn/layer_norm.cc)
# ---------------------------------------------------------------------------
def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False,
              **_ignored):
    jnp = _jnp()
    ax = axis if axis >= 0 else data.ndim + axis
    bshape = tuple(data.shape[ax] if i == ax else 1
                   for i in range(data.ndim))

    def fn(x, g, b):
        mean = jnp.mean(x, axis=ax, keepdims=True)
        var = jnp.mean((x - mean) ** 2, axis=ax, keepdims=True)
        out = (x - mean) / jnp.sqrt(var + eps) * g.reshape(bshape) \
            + b.reshape(bshape)
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    res = _invoke(fn, [data, gamma, beta], name="LayerNorm")
    if output_mean_var:
        return res
    return res[0]


# ---------------------------------------------------------------------------
# InstanceNorm (reference: src/operator/instance_norm.cc — normalize over
# spatial dims per (n, c))
# ---------------------------------------------------------------------------
def InstanceNorm(data, gamma, beta, eps=1e-3, **_ignored):
    jnp = _jnp()
    axes = tuple(range(2, data.ndim))
    bshape = (1, -1) + (1,) * (data.ndim - 2)

    def fn(x, g, b):
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
        return (x - mean) / jnp.sqrt(var + eps) * g.reshape(bshape) \
            + b.reshape(bshape)
    return _invoke(fn, [data, gamma, beta], name="InstanceNorm")


# ---------------------------------------------------------------------------
# GroupNorm (reference: src/operator/nn/group_norm.cc)
# ---------------------------------------------------------------------------
def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5, **_ignored):
    jnp = _jnp()
    bshape = (1, -1) + (1,) * (data.ndim - 2)

    def fn(x, g, b):
        n, c = x.shape[0], x.shape[1]
        xg = x.reshape((n, num_groups, c // num_groups) + x.shape[2:])
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.mean((xg - mean) ** 2, axis=axes, keepdims=True)
        out = ((xg - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
        return out * g.reshape(bshape) + b.reshape(bshape)
    return _invoke(fn, [data, gamma, beta], name="GroupNorm")


# ---------------------------------------------------------------------------
# Fused RNN op (reference: src/operator/rnn.cc + rnn-inl.h).
#
# Keeps MXNet's flat parameter-vector layout so checkpoints trained against
# the reference load unchanged: for each layer, for each direction:
# all i2h weights, then all h2h weights (gate-major); then all biases in the
# same order.  Gate order: LSTM [i, f, g, o]; GRU [r, z, n] (reference uses
# cuDNN order).  Data layout TNC (seq_len, batch, input).
# ---------------------------------------------------------------------------
_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, input_size, state_size, num_layers=1,
                   bidirectional=False, projection_size=None):
    """Total flat parameter count (reference: rnn-inl.h GetRnnParamSize)."""
    ng = _GATES[mode]
    ndir = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * ndir
        size += ndir * ng * state_size * (in_sz + state_size
                                          + 2)  # +2 -> two bias vectors
    return size


def _slice_rnn_params(params, mode, input_size, state_size, num_layers,
                      bidirectional):
    """Split the flat vector into per-(layer, dir) weight/bias arrays."""
    jnp = _jnp()
    ng = _GATES[mode]
    ndir = 2 if bidirectional else 1
    out = []
    off = 0
    # weights first for ALL layers, then biases (cuDNN/MXNet layout)
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * ndir
        for d in range(ndir):
            wi = params[off: off + ng * state_size * in_sz].reshape(
                ng * state_size, in_sz)
            off += ng * state_size * in_sz
            wh = params[off: off + ng * state_size * state_size].reshape(
                ng * state_size, state_size)
            off += ng * state_size * state_size
            out.append({"wi": wi, "wh": wh})
    for layer in range(num_layers):
        for d in range(ndir):
            bi = params[off: off + ng * state_size]; off += ng * state_size
            bh = params[off: off + ng * state_size]; off += ng * state_size
            out[layer * ndir + d]["bi"] = bi
            out[layer * ndir + d]["bh"] = bh
    return out


def _cell_step(mode, state_size):
    """Return step(carry, x_t, w) -> (carry, out_t) for one direction."""
    jnp = _jnp()

    if mode == "lstm":
        def step(carry, xt, w):
            h, c = carry
            gates = xt @ w["wi"].T + w["bi"] + h @ w["wh"].T + w["bh"]
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = (jnp.reciprocal(1 + jnp.exp(-i)),
                       jnp.reciprocal(1 + jnp.exp(-f)),
                       jnp.reciprocal(1 + jnp.exp(-o)))
            g = jnp.tanh(g)
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2
        return step

    if mode == "gru":
        def step(carry, xt, w):
            (h,) = carry
            gi = xt @ w["wi"].T + w["bi"]
            gh = h @ w["wh"].T + w["bh"]
            ir, iz, inn = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jnp.reciprocal(1 + jnp.exp(-(ir + hr)))
            z = jnp.reciprocal(1 + jnp.exp(-(iz + hz)))
            n = jnp.tanh(inn + r * hn)
            h2 = (1 - z) * n + z * h
            return (h2,), h2
        return step

    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

    def step(carry, xt, w):
        (h,) = carry
        h2 = act(xt @ w["wi"].T + w["bi"] + h @ w["wh"].T + w["bh"])
        return (h2,), h2
    return step


def RNN(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, bidirectional=False, mode="lstm", p=0.0,
        state_outputs=False, projection_size=None, **_ignored):
    """Fused multi-layer RNN over TNC data (reference: src/operator/rnn.cc).

    data: (T, N, C); state: (L*D, N, H); state_cell (lstm): (L*D, N, H).
    Returns out (T, N, H*D), or (out, h_n[, c_n]) with state_outputs=True.
    Dropout ``p`` between layers is applied only under autograd training
    mode (matching the reference's mode-dependent dropout).
    """
    from jax import lax as jlax
    jnp = _jnp()
    from .. import autograd as ag
    from . import ops as _ops

    T, N, C = data.shape
    H = state_size if state_size is not None else state.shape[-1]
    ndir = 2 if bidirectional else 1
    has_cell = mode == "lstm"
    step = _cell_step(mode, H)
    train = ag.is_training()

    inputs = [data, parameters, state] + ([state_cell] if has_cell else [])

    def fn(x, params, h0, *rest):
        c0 = rest[0] if has_cell else None
        ws = _slice_rnn_params(params, mode, C, H, num_layers, bidirectional)
        inp = x
        h_finals, c_finals = [], []
        for layer in range(num_layers):
            outs_dir = []
            for d in range(ndir):
                w = ws[layer * ndir + d]
                idx = layer * ndir + d
                init = ((h0[idx], c0[idx]) if has_cell else (h0[idx],))
                seq = inp if d == 0 else jnp.flip(inp, 0)

                def scan_step(carry, xt, _w=w):
                    return step(carry, xt, _w)
                carry, ys = jlax.scan(scan_step, init, seq)
                if d == 1:
                    ys = jnp.flip(ys, 0)
                outs_dir.append(ys)
                h_finals.append(carry[0])
                if has_cell:
                    c_finals.append(carry[1])
            inp = (jnp.concatenate(outs_dir, axis=-1) if ndir == 2
                   else outs_dir[0])
        hn = jnp.stack(h_finals, 0)
        if has_cell:
            return inp, hn, jnp.stack(c_finals, 0)
        return inp, hn

    res = _invoke(fn, inputs, name="RNN")
    out, hn = res[0], res[1]
    if p > 0 and train:
        out = _ops.dropout(out, p=p)
    if not state_outputs:
        return out
    if has_cell:
        return out, hn, res[2]
    return out, hn


# ---------------------------------------------------------------------------
# SoftmaxOutput (legacy symbolic-era op: softmax fwd, (p - onehot(label))/N
# bwd — reference: src/operator/softmax_output.cc).  Modeled as a custom-VJP
# pure function.
# ---------------------------------------------------------------------------
def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1,
                  use_ignore=False, multi_output=False, normalization="null",
                  **_ignored):
    import jax
    jnp = _jnp()

    @jax.custom_vjp
    def so(x, lab):
        m = jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(x - m)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    def so_fwd(x, lab):
        p = so(x, lab)
        return p, (p, lab)

    def so_bwd(resid, g):
        p, lab = resid
        onehot = (lab[..., None] ==
                  jnp.arange(p.shape[-1], dtype=lab.dtype)).astype(p.dtype)
        gx = (p - onehot) * grad_scale
        if use_ignore:
            gx = jnp.where((lab == ignore_label)[..., None],
                           jnp.zeros_like(gx), gx)
        if normalization == "batch":
            gx = gx / p.shape[0]
        elif normalization == "valid" and use_ignore:
            nvalid = jnp.maximum(jnp.sum(lab != ignore_label), 1)
            gx = gx / nvalid.astype(gx.dtype)
        return gx, jnp.zeros_like(lab)

    so.defvjp(so_fwd, so_bwd)
    return _invoke(lambda x, lab: so(x, lab), [data, label],
                   name="SoftmaxOutput")


# ---------------------------------------------------------------------------
# Regression output heads (reference: src/operator/regression_output-inl.h).
# Forward is the prediction (identity / sigmoid); backward is the analytic
# loss gradient scaled by grad_scale, with the head cotangent ignored —
# modeled as custom-VJP functions like SoftmaxOutput above.
# ---------------------------------------------------------------------------
def _regression_output(name, fwd_fn, grad_fn):
    def op(data, label, grad_scale=1.0, **_ignored):
        import jax
        jnp = _jnp()

        @jax.custom_vjp
        def ro(x, lab):
            return fwd_fn(jnp, x)

        def ro_fwd(x, lab):
            out = fwd_fn(jnp, x)
            return out, (out, lab)

        def ro_bwd(resid, g):
            out, lab = resid
            lab = lab.reshape(out.shape).astype(out.dtype)
            # reference scales by grad_scale / num_output where num_output
            # is the per-example output count (regression_output-inl.h)
            num_output = out.size // out.shape[0] if out.ndim > 0 else 1
            gx = grad_fn(jnp, out, lab) * (grad_scale / num_output)
            return gx, jnp.zeros(resid[1].shape, resid[1].dtype)

        ro.defvjp(ro_fwd, ro_bwd)
        return _invoke(lambda x, lab: ro(x, lab), [data, label], name=name)
    op.__name__ = name
    return op


LinearRegressionOutput = _regression_output(
    "LinearRegressionOutput", lambda jnp, x: x,
    lambda jnp, out, lab: out - lab)
MAERegressionOutput = _regression_output(
    "MAERegressionOutput", lambda jnp, x: x,
    lambda jnp, out, lab: jnp.sign(out - lab))
LogisticRegressionOutput = _regression_output(
    "LogisticRegressionOutput",
    lambda jnp, x: 1.0 / (1.0 + jnp.exp(-x)),
    lambda jnp, out, lab: out - lab)


def SVMOutput(data, label, margin=1.0, regularization_coefficient=1.0,
              use_linear=False, **_ignored):
    """Multiclass SVM output head (reference: src/operator/
    svm_output.cc).  Forward is the identity on the scores; backward is
    the analytic hinge gradient — per class j != y the margin violation
    is l_j = max(0, margin + x_j - x_y), and

    * L2-SVM (default):       dx_j = 2*c*l_j,     dx_y = -2*c*sum_j l_j
    * L1-SVM (use_linear):    dx_j = c*[l_j > 0], dx_y = -c*#{l_j > 0}

    with the incoming head cotangent ignored, like the other legacy
    output ops (SoftmaxOutput/regression heads)."""
    import jax
    jnp = _jnp()
    c = regularization_coefficient

    @jax.custom_vjp
    def svm(x, lab):
        return x

    def svm_fwd(x, lab):
        return x, (x, lab)

    def svm_bwd(resid, g):
        x, lab = resid
        n_class = x.shape[-1]
        onehot = (lab[..., None] ==
                  jnp.arange(n_class, dtype=lab.dtype)).astype(x.dtype)
        x_y = jnp.sum(x * onehot, axis=-1, keepdims=True)
        viol = jnp.maximum(0.0, margin + x - x_y) * (1.0 - onehot)
        if use_linear:
            active = (viol > 0).astype(x.dtype)
            gx = c * (active - onehot * jnp.sum(active, -1, keepdims=True))
        else:
            gx = 2.0 * c * (viol - onehot * jnp.sum(viol, -1,
                                                    keepdims=True))
        return gx, jnp.zeros_like(lab)

    svm.defvjp(svm_fwd, svm_bwd)
    return _invoke(lambda x, lab: svm(x, lab), [data, label],
                   name="SVMOutput")


def Convolution_v1(data, weight=None, bias=None, **kwargs):
    """Legacy pre-nnvm convolution (reference: src/operator/
    convolution_v1.cc).  Semantically the modern op minus the features
    v1 never had; delegates to Convolution after rejecting them."""
    for bad in ("dilate",):
        d = kwargs.get(bad)
        if d is not None and any(int(v) != 1 for v in
                                 (d if isinstance(d, (tuple, list))
                                  else (d,))):
            raise MXNetError(f"Convolution_v1 does not support {bad}"
                             " (use Convolution)")
    return Convolution(data, weight, bias, **kwargs)


def UpSampling(*data, scale=1, sample_type="nearest", num_args=1,
               **_ignored):
    """Nearest-neighbor upsampling (reference: src/operator/upsampling.cc).
    Only the ``nearest`` sample_type of the reference is supported; bilinear
    maps to jax.image.resize."""
    d = data[0]

    def fn(x):
        jnp = _jnp()
        if sample_type == "nearest":
            return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        import jax
        n, c, h, w = x.shape
        return jax.image.resize(x, (n, c, h * scale, w * scale), "bilinear")
    return _invoke(fn, [d], name="UpSampling")


# lower-case aliases (the reference registers both spellings)
fully_connected = FullyConnected
convolution = Convolution
deconvolution = Deconvolution
pooling = Pooling
batch_norm = BatchNorm
layer_norm = LayerNorm
instance_norm = InstanceNorm
group_norm = GroupNorm
rnn = RNN
softmax_output = SoftmaxOutput
svm_output = SVMOutput
