"""Optimizer update ops (reference: src/operator/optimizer_op.cc —
sgd_update, sgd_mom_update, adam_update, mp_* multi-precision variants,
signsgd/signum, ftrl, rmsprop, nag, lamb phase1/2).

Update rules match the reference's kernels term for term (tested against
hand NumPy in tests/test_optimizer.py).  Each op mutates ``weight`` (and
state arrays) in place via functional buffer replacement — one fused XLA
computation per call.
"""
from __future__ import annotations

from .ndarray import NDArray

__all__ = ["sgd_update", "sgd_mom_update", "nag_mom_update", "adam_update",
           "rmsprop_update", "rmspropalex_update", "ftrl_update",
           "signsgd_update", "signum_update", "mp_sgd_update",
           "mp_sgd_mom_update", "lamb_update_phase1", "lamb_update_phase2",
           "adagrad_update", "adadelta_update", "sgld_update"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _c():
    # shared pure update cores (lazy: optimizer package imports ndarray)
    from ..optimizer import cores
    return cores


def _prep_grad(g, rescale_grad, clip_gradient, wd=0.0, w=None):
    return _c().prep_grad(
        g, rescale_grad,
        clip_gradient if clip_gradient is not None
        and clip_gradient > 0 else None,
        wd if wd else None, w)


def _row_sparse_grad(grad, lazy_update=True):
    """(rows, data) for a row_sparse grad under lazy_update, else None →
    caller uses the dense path (reference: sgd/adam FComputeEx dispatch on
    grad stype, src/operator/optimizer_op.cc)."""
    from .sparse import RowSparseNDArray
    if isinstance(grad, RowSparseNDArray):
        if lazy_update:
            return grad._rs_indices, grad._rs_data
        return None  # densified by _as_dense_grad
    return None


def _as_dense_grad(grad):
    from .sparse import BaseSparseNDArray
    if isinstance(grad, BaseSparseNDArray):
        return grad.tostype("default")
    return grad


def sgd_update(weight: NDArray, grad: NDArray, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True, out=None):
    clip = clip_gradient if clip_gradient > 0 else None
    rs = _row_sparse_grad(grad, lazy_update)
    if rs is not None:
        # lazy row-sparse update: only touched rows move (reference:
        # SGDUpdateRspImpl — wd applies to the touched rows only)
        rows, gd = rs
        w = weight._data
        wr = w[rows]
        g = _prep_grad(gd, rescale_grad, clip, wd, wr)
        tgt = out if out is not None else weight
        tgt._set_data(w.at[rows].set(_c().sgd(wr, g, lr).astype(w.dtype)))
        return tgt
    w, g = weight._data, _as_dense_grad(grad)._data
    g = _prep_grad(g, rescale_grad, clip, wd, w)
    new_w = _c().sgd(w, g, lr)
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def sgd_mom_update(weight: NDArray, grad: NDArray, mom: NDArray, lr,
                   momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True, out=None):
    clip = clip_gradient if clip_gradient > 0 else None
    rs = _row_sparse_grad(grad, lazy_update)
    if rs is not None:
        rows, gd = rs
        w, m = weight._data, mom._data
        wr, mr = w[rows], m[rows]
        g = _prep_grad(gd, rescale_grad, clip, wd, wr)
        new_wr, new_mr = _c().sgd_momentum(wr, g, mr, lr, momentum)
        mom._set_data(m.at[rows].set(new_mr.astype(m.dtype)))
        tgt = out if out is not None else weight
        tgt._set_data(w.at[rows].set(new_wr.astype(w.dtype)))
        return tgt
    w, g, m = weight._data, _as_dense_grad(grad)._data, mom._data
    g = _prep_grad(g, rescale_grad, clip, wd, w)
    new_w, new_m = _c().sgd_momentum(w, g, m, lr, momentum)
    mom._set_data(new_m.astype(m.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def nag_mom_update(weight: NDArray, grad: NDArray, mom: NDArray, lr,
                   momentum=0.0, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None):
    """Nesterov (reference: nag_mom_update kernel)."""
    w, g, m = weight._data, _as_dense_grad(grad)._data, mom._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w)
    new_w, new_m = _c().nag_momentum(w, g, m, lr, momentum)
    mom._set_data(new_m.astype(m.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def adam_update(weight: NDArray, grad: NDArray, mean: NDArray, var: NDArray,
                lr, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True,
                out=None):
    """reference: adam_update — lr is expected pre-scaled by
    sqrt(1-beta2^t)/(1-beta1^t) as the python Adam class does."""
    jnp = _jnp()
    clip = clip_gradient if clip_gradient > 0 else None
    rs = _row_sparse_grad(grad, lazy_update)
    if rs is not None:
        # lazy adam: moments and weight move only on touched rows
        # (reference: AdamUpdateRspImpl)
        rows, gd = rs
        w, m, v = weight._data, mean._data, var._data
        wr, mr, vr = w[rows], m[rows], v[rows]
        g = _prep_grad(gd, rescale_grad, clip, wd, wr)
        new_wr, new_mr, new_vr = _c().adam(wr, g, mr, vr, lr, beta1,
                                           beta2, epsilon)
        mean._set_data(m.at[rows].set(new_mr.astype(m.dtype)))
        var._set_data(v.at[rows].set(new_vr.astype(v.dtype)))
        tgt = out if out is not None else weight
        tgt._set_data(w.at[rows].set(new_wr.astype(w.dtype)))
        return tgt
    w, g = weight._data, _as_dense_grad(grad)._data
    m, v = mean._data, var._data
    g = _prep_grad(g, rescale_grad, clip, wd, w)
    new_w, new_m, new_v = _c().adam(w, g, m, v, lr, beta1, beta2, epsilon)
    mean._set_data(new_m.astype(m.dtype))
    var._set_data(new_v.astype(v.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def rmsprop_update(weight: NDArray, grad: NDArray, n: NDArray, lr,
                   gamma1=0.95, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, clip_weights=-1.0, out=None):
    jnp = _jnp()
    w, g, nn = weight._data, _as_dense_grad(grad)._data, n._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w)
    new_w, new_n = _c().rmsprop(w, g, nn, lr, gamma1, epsilon)
    if clip_weights and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    n._set_data(new_n.astype(nn.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def rmspropalex_update(weight: NDArray, grad: NDArray, n: NDArray,
                       g_mean: NDArray, delta: NDArray, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, out=None):
    """Centered RMSProp (Graves 2013; reference: rmspropalex_update)."""
    jnp = _jnp()
    w, g = weight._data, _as_dense_grad(grad)._data
    nn, gm, d = n._data, g_mean._data, delta._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w)
    new_n = (1 - gamma1) * g * g + gamma1 * nn
    new_gm = (1 - gamma1) * g + gamma1 * gm
    new_d = gamma2 * d - lr * g / jnp.sqrt(new_n - new_gm * new_gm + epsilon)
    new_w = w + new_d
    if clip_weights and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    n._set_data(new_n.astype(nn.dtype))
    g_mean._set_data(new_gm.astype(gm.dtype))
    delta._set_data(new_d.astype(d.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def ftrl_update(weight: NDArray, grad: NDArray, z: NDArray, n: NDArray, lr,
                lamda1=0.01, beta=1.0, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, out=None):
    jnp = _jnp()
    w, g = weight._data, _as_dense_grad(grad)._data
    zz, nn = z._data, n._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None)
    new_z = zz + g - (jnp.sqrt(nn + g * g) - jnp.sqrt(nn)) / lr * w
    new_n = nn + g * g
    new_w = (jnp.sign(new_z) * lamda1 - new_z) / \
        ((beta + jnp.sqrt(new_n)) / lr + wd) * (jnp.abs(new_z) > lamda1)
    z._set_data(new_z.astype(zz.dtype))
    n._set_data(new_n.astype(nn.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def signsgd_update(weight: NDArray, grad: NDArray, lr, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, out=None):
    jnp = _jnp()
    w, g = weight._data, _as_dense_grad(grad)._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None)
    new_w = w - lr * (jnp.sign(g) + wd * w)
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def signum_update(weight: NDArray, grad: NDArray, mom: NDArray, lr,
                  momentum=0.0, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                  wd_lh=0.0, out=None):
    jnp = _jnp()
    w, g, m = weight._data, _as_dense_grad(grad)._data, mom._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w)
    new_m = momentum * m - (1 - momentum) * g
    new_w = w + lr * (jnp.sign(new_m) - wd_lh * w)
    mom._set_data(new_m.astype(m.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def mp_sgd_update(weight: NDArray, grad: NDArray, weight32: NDArray, lr,
                  wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                  lazy_update=True, out=None):
    """Multi-precision: fp32 master weights, low-precision model weights
    (reference: mp_sgd_update)."""
    jnp = _jnp()
    w32, g = weight32._data, _as_dense_grad(grad)._data.astype(jnp.float32)
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w32)
    new_w32 = _c().sgd(w32, g, lr)
    weight32._set_data(new_w32)
    tgt = out if out is not None else weight
    tgt._set_data(new_w32.astype(weight._data.dtype))
    return tgt


def mp_sgd_mom_update(weight: NDArray, grad: NDArray, mom: NDArray,
                      weight32: NDArray, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=True, out=None):
    jnp = _jnp()
    w32, g, m = weight32._data, _as_dense_grad(grad)._data.astype(jnp.float32), mom._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w32)
    new_w32, new_m = _c().sgd_momentum(w32, g, m, lr, momentum)
    mom._set_data(new_m)
    weight32._set_data(new_w32)
    tgt = out if out is not None else weight
    tgt._set_data(new_w32.astype(weight._data.dtype))
    return tgt


def lamb_update_phase1(weight: NDArray, grad: NDArray, mean: NDArray,
                       var: NDArray, beta1=0.9, beta2=0.999, epsilon=1e-6,
                       t=1, bias_correction=True, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0):
    """reference: lamb_update_phase1 — returns the raw update direction."""
    jnp = _jnp()
    w, g = weight._data, _as_dense_grad(grad)._data
    m, v = mean._data, var._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None)
    new_m, new_v = _c().moments(m, v, g, beta1, beta2)
    mean._set_data(new_m.astype(m.dtype))
    var._set_data(new_v.astype(v.dtype))
    if bias_correction:
        mhat = new_m / (1 - beta1 ** t)
        vhat = new_v / (1 - beta2 ** t)
    else:
        mhat, vhat = new_m, new_v
    upd = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w
    return NDArray(upd, ctx=weight.ctx)


def lamb_update_phase2(weight: NDArray, g: NDArray, r1: NDArray,
                       r2: NDArray, lr, lower_bound=-1.0, upper_bound=-1.0,
                       out=None):
    """reference: lamb_update_phase2 — trust-ratio scaled step."""
    jnp = _jnp()
    w = weight._data
    r1v, r2v = r1._data, r2._data
    if lower_bound and lower_bound > 0:
        r1v = jnp.maximum(r1v, lower_bound)
    if upper_bound and upper_bound > 0:
        r1v = jnp.minimum(r1v, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1v > 0, r2v > 0), r1v / r2v, 1.0)
    new_w = w - lr * ratio * g._data
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def adagrad_update(weight: NDArray, grad: NDArray, history: NDArray, lr,
                   epsilon=1e-7, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, out=None):
    jnp = _jnp()
    w, g, h = weight._data, _as_dense_grad(grad)._data, history._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None)
    new_w, new_h = _c().adagrad(w, g, h, lr, epsilon, wd)
    history._set_data(new_h.astype(h.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def adadelta_update(weight: NDArray, grad: NDArray, acc_g: NDArray,
                    acc_delta: NDArray, rho=0.9, epsilon=1e-5, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, out=None):
    jnp = _jnp()
    w, g = weight._data, _as_dense_grad(grad)._data
    ag, ad = acc_g._data, acc_delta._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w)
    new_ag = rho * ag + (1 - rho) * g * g
    delta = jnp.sqrt(ad + epsilon) / jnp.sqrt(new_ag + epsilon) * g
    new_ad = rho * ad + (1 - rho) * delta * delta
    new_w = w - delta
    acc_g._set_data(new_ag.astype(ag.dtype))
    acc_delta._set_data(new_ad.astype(ad.dtype))
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt


def sgld_update(weight: NDArray, grad: NDArray, lr, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, out=None):
    """Stochastic Gradient Langevin Dynamics (reference: sgld_update)."""
    import jax
    jnp = _jnp()
    from .. import random as _random
    w, g = weight._data, _as_dense_grad(grad)._data
    g = _prep_grad(g, rescale_grad,
                   clip_gradient if clip_gradient > 0 else None, wd, w)
    key = _random.new_key(weight.ctx)
    noise = jax.random.normal(key, w.shape, dtype=w.dtype) * \
        jnp.sqrt(jnp.asarray(lr, w.dtype))
    new_w = w - lr / 2 * g + noise
    tgt = out if out is not None else weight
    tgt._set_data(new_w.astype(w.dtype))
    return tgt
