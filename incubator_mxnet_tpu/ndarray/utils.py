"""mx.nd.save / mx.nd.load — reference-compatible binary serialization.

Byte-level re-implementation of the reference format so checkpoints move
between frameworks (reference: src/c_api/c_api.cc MXNDArraySave — list magic
0x112; src/ndarray/ndarray.cc NDArray::Save — NDARRAY_V2_MAGIC 0xF993fac9,
storage type, dmlc TShape (int32 ndim + int64 dims), Context (int32
dev_type/dev_id), int32 mshadow type flag, raw buffer).  Pure Python struct
packing — no dmlc.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Union

import numpy as _np

from ..base import MXNetError
from ..context import Context
from .ndarray import NDArray, array as _array

__all__ = ["save", "load", "load_frombuffer"]

_LIST_MAGIC = 0x112
_V1_MAGIC = 0xF993FAC8
_V2_MAGIC = 0xF993FAC9
_V3_MAGIC = 0xF993FACA

# mshadow type flags (reference: 3rdparty/mshadow/mshadow/base.h)
_TYPE_TO_FLAG = {
    _np.dtype(_np.float32): 0, _np.dtype(_np.float64): 1,
    _np.dtype(_np.float16): 2, _np.dtype(_np.uint8): 3,
    _np.dtype(_np.int32): 4, _np.dtype(_np.int8): 5,
    _np.dtype(_np.int64): 6, _np.dtype(_np.bool_): 7,
}
_FLAG_TO_TYPE = {v: k for k, v in _TYPE_TO_FLAG.items()}
_BF16_FLAG = 12  # mshadow kBfloat16 (oneDNN builds)


def _dtype_flag(dt) -> int:
    import jax.numpy as jnp
    if _np.dtype(dt) == _np.dtype(jnp.bfloat16):
        return _BF16_FLAG
    try:
        return _TYPE_TO_FLAG[_np.dtype(dt)]
    except KeyError:
        raise MXNetError(f"cannot serialize dtype {dt}")


def _flag_dtype(flag: int):
    if flag == _BF16_FLAG:
        import jax.numpy as jnp
        return _np.dtype(jnp.bfloat16)
    try:
        return _FLAG_TO_TYPE[flag]
    except KeyError:
        raise MXNetError(f"unknown mshadow type flag {flag}")


# storage-type enum (reference: include/mxnet/ndarray.h NDArrayStorageType)
_STYPE_DENSE = 0
_STYPE_ROW_SPARSE = 1
_STYPE_CSR = 2
_INT64_FLAG = 6


def _shape_pack(shape):
    return struct.pack("<i", len(shape)) \
        + struct.pack(f"<{len(shape)}q", *shape)


def _shape_unpack(mv, off):
    (ndim,) = struct.unpack_from("<i", mv, off); off += 4
    shape = struct.unpack_from(f"<{ndim}q", mv, off); off += 8 * ndim
    return shape, off


def _blob(a):
    return _np.ascontiguousarray(a).tobytes()


def _save_ndarray(buf: bytearray, arr):
    """One chunk.  Sparse layout follows the reference save sequence
    (src/ndarray/ndarray.cc NDArray::Save sparse branch): V2 magic,
    int32 stype, the STORAGE shape (the packed values buffer's TShape),
    the logical shape, ctx, values dtype, then per aux array an int32
    dtype flag + TShape, then the VALUES blob, then the aux blobs.
    CSR aux order is (indptr, indices) — CSRAuxType kIndPtr=0, kIdx=1;
    RowSparse has one aux (row indices), both int64.  Re-verify byte
    order against genuine reference artifacts when the mount populates
    (it has been empty every round)."""
    from .sparse import CSRNDArray, RowSparseNDArray
    if isinstance(arr, RowSparseNDArray):
        stype = _STYPE_ROW_SPARSE
        auxes = [_np.asarray(arr.indices.asnumpy(), _np.int64)]
        values = _np.asarray(arr.data.asnumpy())
        shape = tuple(arr.shape)
    elif isinstance(arr, CSRNDArray):
        stype = _STYPE_CSR
        auxes = [_np.asarray(arr.indptr.asnumpy(), _np.int64),
                 _np.asarray(arr.indices.asnumpy(), _np.int64)]
        values = _np.asarray(arr.data.asnumpy())
        shape = tuple(arr.shape)
    else:
        np_data = (arr.asnumpy() if isinstance(arr, NDArray)
                   else _np.asarray(arr))
        buf += struct.pack("<I", _V2_MAGIC)
        buf += struct.pack("<i", _STYPE_DENSE)
        buf += _shape_pack(np_data.shape)
        buf += struct.pack("<ii", 1, 0)              # Context: cpu(0)
        buf += struct.pack("<i", _dtype_flag(np_data.dtype))
        buf += np_data.tobytes()
        return
    buf += struct.pack("<I", _V2_MAGIC)
    buf += struct.pack("<i", stype)
    buf += _shape_pack(values.shape)                 # storage shape
    buf += _shape_pack(shape)                        # logical shape
    buf += struct.pack("<ii", 1, 0)
    buf += struct.pack("<i", _dtype_flag(values.dtype))
    for a in auxes:
        buf += struct.pack("<i", _INT64_FLAG)
        buf += _shape_pack(a.shape)
    buf += _blob(values)                             # data blob first
    for a in auxes:
        buf += _blob(a)


def _read_blob(mv, off, shape, dt):
    n = int(_np.prod(shape)) if len(shape) else 1
    data = _np.frombuffer(mv, dtype=dt, count=n,
                          offset=off).reshape(shape)
    return data, off + n * dt.itemsize


def _load_ndarray(mv: memoryview, off: int):
    (magic,) = struct.unpack_from("<I", mv, off); off += 4
    stype = _STYPE_DENSE
    storage_shape = None
    if magic in (_V2_MAGIC, _V3_MAGIC):
        (stype,) = struct.unpack_from("<i", mv, off); off += 4
        if stype not in (_STYPE_DENSE, _STYPE_ROW_SPARSE, _STYPE_CSR):
            raise MXNetError(f"unknown storage type {stype} in file")
        if stype != _STYPE_DENSE:
            storage_shape, off = _shape_unpack(mv, off)
        shape, off = _shape_unpack(mv, off)
        ndim = len(shape)
    elif magic == _V1_MAGIC:
        shape, off = _shape_unpack(mv, off)
        ndim = len(shape)
    else:
        # legacy V0: the "magic" was actually ndim (uint32 dims)
        ndim = magic
        shape = struct.unpack_from(f"<{ndim}I", mv, off); off += 4 * ndim
    _dev_type, _dev_id = struct.unpack_from("<ii", mv, off); off += 8
    (flag,) = struct.unpack_from("<i", mv, off); off += 4
    dt = _flag_dtype(flag)
    if stype == _STYPE_DENSE:
        n = int(_np.prod(shape)) if ndim else 1
        data = _np.frombuffer(mv, dtype=dt, count=n,
                              offset=off).reshape(shape)
        off += n * dt.itemsize
        return _array(_np.array(data), dtype=dt), off
    # sparse: aux descriptors, then the VALUES blob (its shape is the
    # stored storage_shape), then the aux blobs — the reference's order
    nad = 1 if stype == _STYPE_ROW_SPARSE else 2
    aux_dts, aux_shapes = [], []
    for _ in range(nad):
        (aflag,) = struct.unpack_from("<i", mv, off); off += 4
        aux_dts.append(_flag_dtype(aflag))
        ashape, off = _shape_unpack(mv, off)
        aux_shapes.append(ashape)
    values, off = _read_blob(mv, off, storage_shape, dt)
    values = _np.array(values)
    auxes = []
    for adt, ashape in zip(aux_dts, aux_shapes):
        a, off = _read_blob(mv, off, ashape, adt)
        auxes.append(_np.array(a))
    from . import sparse as _sp
    if stype == _STYPE_ROW_SPARSE:
        return _sp.row_sparse_array(
            (values, auxes[0]), shape=tuple(shape)), off
    return _sp.csr_matrix(
        (values, auxes[1], auxes[0]), shape=tuple(shape)), off


def save(fname: str, data):
    """Save NDArray / list / dict-of-str→NDArray (reference: mx.nd.save)."""
    if isinstance(data, NDArray):
        data, names = [data], []
    elif isinstance(data, (list, tuple)):
        if not all(isinstance(a, (NDArray, _np.ndarray)) for a in data):
            raise MXNetError("save expects NDArray/numpy elements")
        data, names = list(data), []
    elif isinstance(data, dict):
        names = list(data.keys())
        data = list(data.values())
    else:
        raise MXNetError(f"cannot save {type(data)}")

    buf = bytearray()
    buf += struct.pack("<QQ", _LIST_MAGIC, 0)
    buf += struct.pack("<Q", len(data))
    for arr in data:
        _save_ndarray(buf, arr)
    buf += struct.pack("<Q", len(names))
    for name in names:
        b = name.encode("utf-8")
        buf += struct.pack("<Q", len(b)) + b
    with open(fname, "wb") as f:
        f.write(bytes(buf))


def load_frombuffer(raw: bytes) -> Union[List[NDArray], Dict[str, NDArray]]:
    mv = memoryview(raw)
    header, _reserved = struct.unpack_from("<QQ", mv, 0)
    if header != _LIST_MAGIC:
        raise MXNetError("invalid NDArray file format (bad magic)")
    off = 16
    (n,) = struct.unpack_from("<Q", mv, off); off += 8
    arrays = []
    for _ in range(n):
        arr, off = _load_ndarray(mv, off)
        arrays.append(arr)
    (n_names,) = struct.unpack_from("<Q", mv, off); off += 8
    names = []
    for _ in range(n_names):
        (ln,) = struct.unpack_from("<Q", mv, off); off += 8
        names.append(bytes(mv[off:off + ln]).decode("utf-8")); off += ln
    if n_names == 0:
        return arrays
    if n_names != n:
        raise MXNetError("corrupt NDArray file: names/arrays mismatch")
    return dict(zip(names, arrays))


def load(fname: str):
    """Load NDArray file (reference: mx.nd.load)."""
    with open(fname, "rb") as f:
        return load_frombuffer(f.read())
